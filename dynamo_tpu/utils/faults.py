"""Deterministic transport-fault injection for tests and chaos drills.

``ChaosProxy`` is a byte-level TCP proxy that sits between an RPC client and
an ``RpcServer`` (or any TCP service) and injects the faults that are hard to
provoke on a real socket pair:

- ``blackhole()``: stop forwarding in both directions while KEEPING every
  connection open — the alive-but-stuck worker (engine deadlock, GC pause,
  network partition with open TCP).  Stream-drop detection never fires; only
  keepalive probing or request deadlines can catch it.
- ``set_delay(s)``: add latency to every forwarded chunk (slow network).
- ``delay_jitter(p, min_s, max_s)``: tail-latency mode — each CONNECTION
  independently draws (with probability ``p``) a random stall in
  [min_s, max_s] applied to its forwarded chunks, while other connections
  run at full speed.  Distinct from the blanket ``delay``: this is the
  slow-but-alive worker whose victims are only some callers — the case a
  failure-aware router must route around rather than merely detect.
  Seedable for deterministic chaos tests.
- ``heal()``: resume forwarding (bytes held during the blackhole flow again).
- ``corrupt(after_bytes, nbytes)``: flip (XOR 0xFF) ``nbytes`` of the
  forwarded byte stream starting at absolute offset ``after_bytes`` — the
  silently-corrupting link/NIC that only end-to-end checksums can catch.
  One-shot; disarms once the window has been applied.
- ``truncate(after_bytes)``: forward exactly ``after_bytes`` then hard-cut
  both sides of the connection — the mid-transfer socket reset. One-shot.

``corrupt``/``truncate`` take a ``direction`` (``"down"`` = server→client
bytes, the default — where KV frames flow — or ``"up"``) and count bytes
cumulatively per direction across the proxy's lifetime; they work against
any TCP service (the bulk data plane and the RPC plane alike).

Scenarios become deterministic: point the client at ``proxy.address`` instead
of the worker's own, then flip faults mid-stream.  Parity in intent with the
reference's fault-tolerance suite (``tests/fault_tolerance/``), which kills
processes; this adds the fault class process-kills can't express.

``CoordinatorOutage`` is the control-plane sibling: kill an in-process
``Coordinator`` abruptly (clients see a hard TCP close, like ``kill -9``) and
relisten on the SAME port, with or without a state wipe — so chaos tests can
exercise both a blipped connection (state intact, leases still ticking) and a
fresh empty coordinator (the real crash/restart, everything to resync).

``CoordinatorPair`` composes both with a replicated primary + hot-standby
pair whose replication link runs through a ``ChaosProxy``: primary kill -9,
replication-link partition while both halves stay client-reachable (the
dual-primary fencing drill), and standby blips during catch-up.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Optional, Set

from dynamo_tpu.utils.aio import reap_task

logger = logging.getLogger(__name__)


class ChaosProxy:
    """TCP proxy with switchable blackhole/delay fault injection."""

    def __init__(self, upstream: str, host: str = "127.0.0.1", port: int = 0):
        uhost, _, uport = upstream.rpartition(":")
        self.upstream_host = uhost or "127.0.0.1"
        self.upstream_port = int(uport)
        self.host = host
        self.port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._forwarding = asyncio.Event()
        self._forwarding.set()
        self._delay_s = 0.0
        # tail-latency jitter: (p, min_s, max_s, rng); None = off
        self._jitter: Optional[tuple] = None
        self._tasks: Set[asyncio.Task] = set()
        self._writers: Set[asyncio.StreamWriter] = set()
        self.bytes_forwarded = 0
        # byte-stream faults, armed per direction ("up" client->server,
        # "down" server->client); offsets are cumulative per direction
        self._dir_bytes = {"up": 0, "down": 0}
        self._corrupt: dict = {}   # direction -> (start, nbytes)
        self._truncate: dict = {}  # direction -> cut offset
        self.corruptions = 0
        self.truncations = 0

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def blackholed(self) -> bool:
        return not self._forwarding.is_set()

    async def start(self) -> "ChaosProxy":
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, limit=16 * 1024 * 1024)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server:
            self._server.close()
        for t in list(self._tasks):
            await reap_task(t)
        for w in list(self._writers):
            try:
                w.close()
            except Exception:
                pass
        if self._server:
            await self._server.wait_closed()

    # -- fault controls ----------------------------------------------------

    def blackhole(self) -> None:
        """Stop forwarding, keep connections open (the stuck-worker fault)."""
        self._forwarding.clear()

    def heal(self) -> None:
        """Resume forwarding; bytes held during the blackhole flow again."""
        self._forwarding.set()

    def set_delay(self, seconds: float) -> None:
        """Add per-chunk forwarding latency (0 restores full speed)."""
        self._delay_s = max(0.0, seconds)

    def delay_jitter(self, p: float, min_s: float, max_s: float,
                     seed: Optional[int] = None) -> None:
        """Tail-latency mode: each connection draws — with probability
        ``p``, at its first forwarded chunk after arming — a random stall
        in [min_s, max_s] it then applies to every chunk it forwards.
        Unlucky connections are consistently slow, the rest run at full
        speed (per-connection, unlike the blanket ``set_delay``).  Pass
        ``seed`` for a deterministic draw sequence; ``delay_jitter(0, 0,
        0)`` disarms."""
        if p <= 0:
            self._jitter = None
            return
        self._jitter = (min(1.0, p), max(0.0, min_s),
                        max(0.0, min_s, max_s), random.Random(seed))

    def corrupt(self, after_bytes: int = 0, nbytes: int = 1,
                direction: str = "down") -> None:
        """Flip ``nbytes`` of the ``direction`` byte stream starting at
        cumulative offset ``after_bytes`` (XOR 0xFF — the bytes still
        arrive, just wrong). One-shot: disarms once fully applied."""
        self._corrupt[direction] = (int(after_bytes), max(1, int(nbytes)))

    def truncate(self, after_bytes: int, direction: str = "down") -> None:
        """Forward exactly ``after_bytes`` cumulative bytes in
        ``direction`` then hard-close both sides of that connection (a
        mid-transfer reset). One-shot."""
        self._truncate[direction] = int(after_bytes)

    def clear_faults(self) -> None:
        """Disarm any pending corrupt/truncate faults."""
        self._corrupt.clear()
        self._truncate.clear()

    # -- plumbing ----------------------------------------------------------

    async def _handle(self, creader: asyncio.StreamReader,
                      cwriter: asyncio.StreamWriter) -> None:
        try:
            ureader, uwriter = await asyncio.open_connection(
                self.upstream_host, self.upstream_port,
                limit=16 * 1024 * 1024)
        except OSError:
            cwriter.close()
            return
        self._writers.update((cwriter, uwriter))
        # per-connection jitter state, shared by both pump directions so a
        # slow connection is slow both ways (one stall draw per connection)
        conn: dict = {}
        up = asyncio.create_task(self._pump(creader, uwriter, "up", cwriter,
                                            conn))
        down = asyncio.create_task(self._pump(ureader, cwriter, "down",
                                              uwriter, conn))
        for t in (up, down):
            self._tasks.add(t)
            t.add_done_callback(self._tasks.discard)
        try:
            await asyncio.gather(up, down, return_exceptions=True)
        finally:
            for w in (cwriter, uwriter):
                self._writers.discard(w)
                try:
                    w.close()
                except Exception:
                    pass

    def _apply_faults(self, direction: str, data: bytes):
        """Apply any armed corrupt/truncate fault to one chunk; returns
        (data, cut) where ``cut`` means: write what remains, then hard-
        close the connection."""
        pos = self._dir_bytes[direction]
        armed = self._corrupt.get(direction)
        if armed is not None:
            start, n = armed
            lo, hi = max(start, pos), min(start + n, pos + len(data))
            if lo < hi:
                b = bytearray(data)
                for i in range(lo - pos, hi - pos):
                    b[i] ^= 0xFF
                data = bytes(b)
                self.corruptions += 1
            if start + n <= pos + len(data):  # window fully applied
                self._corrupt.pop(direction, None)
        cut = False
        trunc = self._truncate.get(direction)
        if trunc is not None and pos + len(data) >= trunc:
            data = data[:max(0, trunc - pos)]
            self._truncate.pop(direction, None)
            self.truncations += 1
            cut = True
        self._dir_bytes[direction] += len(data)
        return data, cut

    def _jitter_stall(self, conn: Optional[dict]) -> float:
        """This connection's stall for the armed jitter config.  Drawn
        lazily at the first chunk after arming (a pooled connection opened
        before ``delay_jitter`` still participates), atomically between
        awaits, once per (connection, arming)."""
        jit = self._jitter
        if jit is None or conn is None:
            return 0.0
        key = id(jit)  # re-arming re-draws
        if conn.get("jitter_key") != key:
            p, min_s, max_s, rng = jit
            conn["jitter_key"] = key
            conn["stall"] = (rng.uniform(min_s, max_s)
                             if rng.random() < p else 0.0)
        return conn.get("stall", 0.0)

    async def _pump(self, reader: asyncio.StreamReader,
                    writer: asyncio.StreamWriter,
                    direction: str = "down",
                    peer_writer: "asyncio.StreamWriter" = None,
                    conn: Optional[dict] = None) -> None:
        try:
            while True:
                data = await reader.read(64 * 1024)
                if not data:
                    break
                if self._delay_s:
                    await asyncio.sleep(self._delay_s)
                stall = self._jitter_stall(conn)
                if stall:
                    await asyncio.sleep(stall)
                # blackhole: hold the chunk here — the connection stays
                # open and silent, exactly like a frozen remote
                await self._forwarding.wait()
                data, cut = self._apply_faults(direction, data)
                if data:
                    writer.write(data)
                    await writer.drain()
                    self.bytes_forwarded += len(data)
                if cut:
                    # hard-cut BOTH halves: the peer sees a mid-frame
                    # close, exactly like a socket reset under transfer
                    if peer_writer is not None:
                        try:
                            peer_writer.close()
                        except Exception:
                            pass
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass


class CoordinatorOutage:
    """Kill-and-relisten harness around an in-process ``Coordinator``.

    ``kill()`` tears the server down abruptly (live connections get a hard
    close — what a ``kill -9`` looks like from the client side) while
    remembering the bound port; ``restart(wipe_state=...)`` re-binds the
    same port, optionally after wiping every piece of server state (KV,
    leases, watches, subscriptions, queues) to model a fresh process.
    """

    def __init__(self, coordinator):
        self.coordinator = coordinator
        self.outages = 0

    @property
    def address(self) -> str:
        return self.coordinator.address

    async def kill(self) -> None:
        """Stop serving; the port stays reserved for ``restart()``."""
        await self.coordinator.stop()
        self.outages += 1
        logger.info("coordinator %s killed (outage #%d)",
                    self.coordinator.address, self.outages)

    async def restart(self, wipe_state: bool = True) -> None:
        """Relisten on the same host:port; ``wipe_state=True`` models a
        crashed-and-respawned coordinator (empty KV, no leases), False a
        supervisor restart that kept state in memory."""
        c = self.coordinator
        if wipe_state:
            c._kv.clear()
            c._leases.clear()
            c._watches.clear()
            c._subs_exact.clear()
            c._subs_wild.clear()
            c._queue_rr.clear()
            c._queues.clear()
            c._queue_pulls.clear()
            # a genuinely fresh process restarts the id counter at 1, so
            # fresh watch/sub/lease ids COLLIDE with pre-outage ids —
            # resync code must survive that, so the drill reproduces it
            c._next_id = 1
            c._epoch = random.getrandbits(63)  # new process, new boot epoch
            c._term = 0                 # fresh lineage: term restarts too
            c._deposed_term = None
            c._repl_seq = 0
            # a wiped standby has no mirrored state: it must re-attach
            # before auto-promotion trusts it again
            c._ever_attached = False
        await c.start()
        logger.info("coordinator restarted on %s (state %s)", c.address,
                    "wiped" if wipe_state else "kept")

    async def blip(self, downtime_s: float = 0.0,
                   wipe_state: bool = True) -> None:
        """kill -> (optional dwell) -> restart, one call."""
        await self.kill()
        if downtime_s > 0:
            await asyncio.sleep(downtime_s)
        await self.restart(wipe_state=wipe_state)


class CoordinatorPair:
    """Chaos harness for a replicated coordinator pair (primary + hot
    standby) with a controllable replication link.

    The standby attaches to the primary THROUGH a ``ChaosProxy``, so the
    replication link can be partitioned while both processes stay
    client-reachable — the dual-primary drill.  The primary learns the
    standby's REAL listen address from the attach, so its split-brain peer
    probe bypasses the proxy: when the standby promotes behind the
    partition, the deposed primary observes the higher fencing term,
    bounces its writers, and demotes itself into a standby of the winner.

    Drills:

    - ``kill9_primary()`` — abrupt primary death (clients see a hard TCP
      close, like ``kill -9``); the standby self-promotes after its
      promote window and clients walk their address list onto it.
    - ``partition()`` / ``heal()`` — blackhole the replication link (open
      TCP, no bytes) while both coordinators keep serving clients.
    - ``blip_standby()`` — kill the standby mid-catch-up and bring it
      back; it re-attaches with a fresh full snapshot.
    - ``promote()`` — manual promotion (the operator/SIGUSR1 path).
    """

    def __init__(self, promote_after_s: float = 0.6,
                 lease_grace_s: float = 0.5):
        self.promote_after_s = promote_after_s
        self.lease_grace_s = lease_grace_s
        self.primary = None
        self.standby = None
        self.repl_proxy: Optional[ChaosProxy] = None
        self.primary_outage: Optional[CoordinatorOutage] = None
        self.standby_outage: Optional[CoordinatorOutage] = None

    async def start(self) -> "CoordinatorPair":
        from dynamo_tpu.runtime.coordinator import Coordinator

        self.primary = await Coordinator(
            port=0, promote_after_s=self.promote_after_s,
            lease_grace_s=self.lease_grace_s).start()
        self.repl_proxy = await ChaosProxy(self.primary.address).start()
        self.standby = await Coordinator(
            port=0, standby_of=self.repl_proxy.address,
            promote_after_s=self.promote_after_s,
            lease_grace_s=self.lease_grace_s).start()
        self.primary_outage = CoordinatorOutage(self.primary)
        self.standby_outage = CoordinatorOutage(self.standby)
        await self.wait_attached()
        return self

    @property
    def addresses(self) -> str:
        """The multi-address string clients take (primary first)."""
        return f"{self.primary.address},{self.standby.address}"

    async def wait_attached(self, timeout: float = 5.0) -> None:
        """Until the standby has installed the primary's snapshot (mirrored
        boot epoch) and applied the log to the primary's sequence."""
        deadline = asyncio.get_running_loop().time() + timeout
        while (self.standby._epoch != self.primary._epoch
               or self.standby._repl_seq < self.primary._repl_seq):
            if asyncio.get_running_loop().time() >= deadline:
                raise TimeoutError("standby never caught up")
            await asyncio.sleep(0.02)

    wait_caught_up = wait_attached

    async def wait_promoted(self, timeout: float = 10.0) -> None:
        deadline = asyncio.get_running_loop().time() + timeout
        while self.standby.role != "primary":
            if asyncio.get_running_loop().time() >= deadline:
                raise TimeoutError("standby never promoted")
            await asyncio.sleep(0.02)

    async def kill9_primary(self) -> None:
        """Abrupt primary death; the port stays available for
        ``primary_outage.restart()`` (which rejoins via the peer probe)."""
        await self.primary_outage.kill()

    def partition(self) -> None:
        """Cut primary<->standby replication while both stay
        client-reachable (the dual-primary drill)."""
        self.repl_proxy.blackhole()

    def heal(self) -> None:
        self.repl_proxy.heal()

    async def blip_standby(self, downtime_s: float = 0.1) -> None:
        """Kill the standby during replication catch-up and bring it back;
        the fresh attach re-snapshots, repairing any missed tail."""
        await self.standby_outage.blip(downtime_s=downtime_s,
                                       wipe_state=True)

    def promote(self, reason: str = "harness") -> None:
        self.standby.promote(reason)

    async def stop(self) -> None:
        for part in (self.standby, self.repl_proxy, self.primary):
            if part is None:
                continue
            try:
                await part.stop()
            except Exception:  # noqa: BLE001 — already-dead halves are fine
                pass


class WorkerDrain:
    """Lifecycle fault harness around one in-process worker: drives the
    SAME staged drain protocol the production worker runs
    (``worker/drain.DrainController``), plus the abrupt deaths chaos
    tests pit it against.

    Scenarios:

    - ``sigterm()`` — the graceful path: announce draining, freeze the
      in-flight streams into resume/replay tokens, wait (bounded) for
      survivors to ack the pinned-KV leases, then tear the worker down.
      What the real worker's SIGTERM handler / ``POST /drain`` does.
    - ``kill9()`` — no drain at all: the runtime closes abruptly; callers
      see connection teardown and the migration operator replays (the
      PR 2 path).
    - ``kill9_mid_drain()`` — the race: announce + freeze complete (resume
      tokens shipped, KV pinned), then the process dies BEFORE survivors
      pull — their resume pulls fail and admission falls back to
      recompute; no stream may be lost and no lease may leak on the
      survivors.
    - ``drain(timeout_s=0)`` — the drain-timeout scenario: exit without
      waiting for lease acks.
    """

    def __init__(self, drt, engine, served=(), resume_extras=None):
        from dynamo_tpu.worker.drain import DrainController

        self.drt = drt
        self.engine = engine
        self.controller = DrainController(engine, served=served,
                                          resume_extras=resume_extras)
        self.dead = False

    async def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Graceful drain WITHOUT tearing the worker down (the post-drain
        window where survivors pull pinned KV); returns the
        resume/replay counts."""
        self.controller.timeout_s = timeout_s
        return await self.controller.drain("WorkerDrain")

    async def sigterm(self, timeout_s: Optional[float] = None) -> dict:
        """Full graceful shutdown: drain, then close the runtime."""
        counts = await self.drain(timeout_s)
        await self._close()
        return counts

    async def kill9(self) -> None:
        """Abrupt death — no announcement, no freeze, streams drop."""
        await self._close()

    async def kill9_mid_drain(self) -> dict:
        """Announce + freeze, then die before any survivor pulls."""
        await self.controller.announce()
        counts = await self.controller.freeze()
        await self._close()
        return counts

    async def _close(self) -> None:
        if self.dead:
            return
        self.dead = True
        try:
            await self.drt.close()
        finally:
            stop = getattr(self.engine, "stop", None)
            if stop is not None:
                await stop()


def stub_worker_cmd(ready_after_s: float = 0.0,
                    exit_after_s: Optional[float] = None,
                    exit_code: int = 1,
                    drain_s: float = 0.0,
                    ignore_term: bool = False,
                    banner: str = "stub worker up") -> list:
    """Command line for a scripted minimal fake worker — the fleet
    supervisor's unit-test counterpart to the mocker.

    The child honors the supervisor contract without importing anything
    heavy: it serves ``/healthz/ready`` on ``DYN_SYSTEM_PORT`` (503 until
    ``ready_after_s`` has elapsed), answers ``POST /drain`` with 202 and
    exits 0 after ``drain_s`` (how long its pretend migration takes),
    treats SIGTERM the same way (or ignores it with ``ignore_term`` — the
    SIGKILL-escalation drill), prints ``banner`` to stdout (log-capture
    assertions), and optionally crashes with ``exit_code`` after
    ``exit_after_s``.
    """
    import sys as _sys
    script = f"""
import http.server, os, signal, sys, threading, time
T0 = time.monotonic()
def bail(code, delay=0.0):
    def run():
        time.sleep(delay); os._exit(code)
    threading.Thread(target=run, daemon=True).start()
class H(http.server.BaseHTTPRequestHandler):
    def log_message(self, *a): pass
    def do_GET(self):
        if self.path == '/healthz/ready':
            ok = time.monotonic() - T0 >= {ready_after_s!r}
            self.send_response(200 if ok else 503); self.end_headers()
            self.wfile.write(b'ready' if ok else b'not ready')
        else:
            self.send_response(404); self.end_headers()
    def do_POST(self):
        if self.path == '/drain':
            self.send_response(202); self.end_headers()
            self.wfile.write(b'draining')
            if not {ignore_term!r}:
                bail(0, {drain_s!r})
        else:
            self.send_response(404); self.end_headers()
port = int(os.environ.get('DYN_SYSTEM_PORT', '0') or 0)
if port:
    srv = http.server.ThreadingHTTPServer(('127.0.0.1', port), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
if {ignore_term!r}:
    signal.signal(signal.SIGTERM, signal.SIG_IGN)
else:
    signal.signal(signal.SIGTERM, lambda *a: bail(0, {drain_s!r}))
print({banner!r}, flush=True)
"""
    if exit_after_s is not None:
        script += f"""
time.sleep({exit_after_s!r})
print('stub worker exiting rc={exit_code}', flush=True)
sys.exit({exit_code!r})
"""
    script += """
while True:
    time.sleep(3600)
"""
    return [_sys.executable, "-c", script]


__all__ = ["ChaosProxy", "CoordinatorOutage", "CoordinatorPair",
           "WorkerDrain", "stub_worker_cmd"]
