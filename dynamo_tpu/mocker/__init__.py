"""Mocker engine: a full engine simulator with real KV/scheduling behavior.

Parity: reference rust mocker (``lib/llm/src/mocker/`` — paged ``KvManager``
with LRU eviction + block events, prefill-cost model, chunked scheduler with
watermark/preemption, ``MockEngineArgs``), the reference's key trick for
testing multi-worker routing without GPUs
(``tests/router/test_router_e2e_with_mockers.py``).

Here the mocker IS the production scheduling stack — it shares
``ScheduledEngineBase`` (admission, chunked prefill, preemption, prefix cache,
KV events, metrics) with the real ``JaxEngine`` and swaps only the compute
for a timing model. Router/planner behavior observed against the mocker is
therefore exactly what the real engine produces, token-for-token and
event-for-event.
"""

from dynamo_tpu.mocker.engine import MockEngineArgs, MockerEngine

__all__ = ["MockEngineArgs", "MockerEngine"]
