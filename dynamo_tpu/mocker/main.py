"""Mocker worker process: serve a simulated engine behind the runtime.

Parity: reference ``components/backends/mocker/src/dynamo/mocker/main.py`` —
full distributed-stack testing (router, planner, fault tolerance) with no
accelerator: real registration, real KV events, real metrics, simulated
timing.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.llm.register import register_llm, serve_engine
from dynamo_tpu.mocker.engine import MockEngineArgs, MockerEngine
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.runtime.runtime import DEFAULT_COORDINATOR, DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging
from dynamo_tpu.worker.events import kv_events_subject, ordered_kv_publisher


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="dynamo_tpu mocker worker")
    p.add_argument("--coordinator", default=DEFAULT_COORDINATOR)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="mocker")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--model-name", default="mock-model")
    p.add_argument("--model-path", default=None,
                   help="optional HF dir for a real tokenizer/card")
    p.add_argument("--num-pages", type=int, default=512)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-num-seqs", type=int, default=64)
    p.add_argument("--max-context", type=int, default=4096)
    p.add_argument("--speedup-ratio", type=float, default=1.0)
    p.add_argument("--no-kv-events", action="store_true")
    return p


async def amain(args: argparse.Namespace) -> None:
    drt = await DistributedRuntime.create(coordinator=args.coordinator)
    if args.model_path:
        card = ModelDeploymentCard.from_local_path(args.model_path,
                                                   name=args.model_name)
    else:
        from dynamo_tpu.utils.testing import make_test_card
        card = make_test_card(name=args.model_name,
                              kv_cache_block_size=args.page_size)
    card.kv_cache_block_size = args.page_size
    try:
        # sample inside the served tokenizer's vocab so detokenization
        # produces real text downstream
        vocab = card.load_tokenizer().vocab_size
    except Exception:
        vocab = 32000
    engine = MockerEngine(MockEngineArgs(
        num_pages=args.num_pages, page_size=args.page_size,
        max_num_seqs=args.max_num_seqs, max_context=args.max_context,
        speedup_ratio=args.speedup_ratio, vocab_size=vocab))
    endpoint = (drt.namespace(args.namespace).component(args.component)
                .endpoint(args.endpoint))
    event_pump = None
    if not args.no_kv_events:
        lease = await drt.primary_lease()
        engine.kv_event_cb, event_pump = ordered_kv_publisher(
            drt, kv_events_subject(args.namespace, args.component),
            lease.lease_id)
    served = await serve_engine(endpoint, engine,
                                stats_provider=lambda:
                                engine.stats().to_dict())
    await register_llm(drt, endpoint, card)
    # same observability surface as the real worker (worker/main.py):
    # counters + stage histogram + flight recorder on the system server
    from dynamo_tpu.runtime.system_server import SystemServer
    from dynamo_tpu.utils.tracing import get_tracer
    from dynamo_tpu.worker.metrics import get_worker_metrics
    tracer = get_tracer()
    if not tracer.service:
        tracer.service = "mocker"
    wm = get_worker_metrics()
    wm.attach_tracer(tracer)
    from functools import partial

    from dynamo_tpu.worker.metrics import engine_dispatch_stats
    wm.engine.attach(partial(engine_dispatch_stats, engine))
    # step flight recorder parity with the real worker: the mocker's
    # simulated dispatches stamp the same ring via ScheduledEngineBase
    wm.steptrace.attach(engine.steptrace.aggregates)
    system = SystemServer.from_env(registry=wm.registry, tracer=tracer,
                                   steptrace=engine.steptrace)
    if system is not None:
        system.health.register("engine", ready=True)
        system.attach_coord(drt.coord)  # 503 /healthz/ready in an outage
        await system.start()
    # graceful drain parity with the real worker: the mocker cannot
    # export KV, so every frozen stream ships an empty (replay) token —
    # fleet tests exercise the announcement/refusal/failover machinery
    from dynamo_tpu.worker.drain import DrainController, install_signal_drain
    drain = DrainController(engine, served=[served],
                            on_drained=drt.runtime.shutdown)
    install_signal_drain(drain)
    if system is not None:
        system.register_drain(drain)
    print(f"mocker worker serving model {card.name}", flush=True)
    try:
        await drt.runtime.wait_shutdown()
    finally:
        if system is not None:
            await system.stop()
        if event_pump is not None:
            event_pump.cancel()
        await engine.stop()
        await drt.close()


def main() -> None:
    args = build_parser().parse_args()
    configure_logging()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
