"""The mock engine: production scheduling + a calibrated timing model.

Cost model (defaults approximate one v5e chip serving an 8B model, scaled by
``speedup_ratio`` like the reference's ``MockEngineArgs.speedup_ratio``):

- prefill chunk of ``n`` tokens against ``c`` cached tokens:
  ``base + n * per_prefill_token + n * c * attn_quadratic`` — the quadratic
  term models attention against the growing context, which is what makes
  chunked prefill of long prompts progressively slower (the reference's
  prefill-cost model serves the same purpose, ``mocker/scheduler.rs``).
- decode step over a batch of ``b`` sequences: ``base + b * per_decode_token``.

Tokens are sampled deterministically from the request id (stable across
migrations/retries) unless the request carries nonzero temperature, in which
case they are pseudo-random.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass
from typing import Tuple

import numpy as np

from dynamo_tpu.engine.loop import ScheduledEngineBase
from dynamo_tpu.engine.scheduler import (
    MixedStepBatch,
    PrefillBatch,
    StepPlan,
)


@dataclass
class MockEngineArgs:
    """Parity: reference ``mocker/protocols.rs:80-142`` ``MockEngineArgs``."""

    num_pages: int = 512           # reference: num_gpu_blocks
    page_size: int = 16            # reference: block_size
    max_num_seqs: int = 64
    max_prefill_chunk: int = 512
    max_prefill_seqs: int = 8
    max_context: int = 4096
    speedup_ratio: float = 1.0     # >1 = faster than real time
    vocab_size: int = 32000
    # timing model (seconds)
    prefill_base_s: float = 0.004
    prefill_per_token_s: float = 25e-6
    prefill_attn_quadratic_s: float = 3e-9
    decode_base_s: float = 0.006
    decode_per_seq_s: float = 120e-6
    dp_size: int = 1               # metadata only (reported in stats)
    # fused decode: the mocker honors the same dispatch_multistep /
    # fetch_packed_block hook pair as the real engine, so pipeline tests
    # exercise the block path end to end. One block pays ONE decode_base_s
    # for ``width`` tokens — exactly the amortization the fused dispatch
    # models. 1 disables.
    decode_multistep: int = 8
    # mixed prefill+decode dispatch: the mocker executes MixedStepBatch
    # plans (prefill chunks + decode rows in ONE step paying one shared
    # base cost) so pipeline/disagg tests exercise the mixed path without
    # a TPU, matching the real engine's scheduling
    mixed_batch: bool = True
    decode_progress_every: int = 2


class MockerEngine(ScheduledEngineBase):
    def __init__(self, args: MockEngineArgs = None):
        self.args = args or MockEngineArgs()
        a = self.args
        super().__init__(num_pages=a.num_pages, page_size=a.page_size,
                         max_num_seqs=a.max_num_seqs,
                         max_prefill_chunk=a.max_prefill_chunk,
                         max_context=a.max_context,
                         max_prefill_seqs=a.max_prefill_seqs,
                         decode_multistep=a.decode_multistep,
                         mixed_batch=a.mixed_batch,
                         decode_progress_every=a.decode_progress_every)
        self._rng = np.random.default_rng(0)
        self.decode_dispatches = 0
        self.multistep_blocks = 0
        self.mixed_steps = 0

    def _simulate(self, seconds: float) -> None:
        if self.args.speedup_ratio > 0:
            time.sleep(seconds / self.args.speedup_ratio)

    def _token_for(self, request_id: str, position: int,
                   temperature: float) -> int:
        if temperature and temperature > 0:
            return int(self._rng.integers(1, self.args.vocab_size))
        digest = hashlib.blake2b(f"{request_id}:{position}".encode(),
                                 digest_size=4).digest()
        return int.from_bytes(digest, "little") % self.args.vocab_size

    def _execute_plan(self, plan: StepPlan) -> Tuple[np.ndarray, np.ndarray]:
        a = self.args
        if isinstance(plan, (PrefillBatch, MixedStepBatch)):
            # one shared step base + per-chunk token/attention costs: chunks
            # batched into one step amortize the launch overhead, which is
            # exactly the benefit batched prefill exists to model. A mixed
            # plan's decode rows ride the SAME base (that amortization is
            # what the mixed dispatch exists to model) and pay only their
            # per-sequence decode cost.
            decode_seqs = list(getattr(plan, "decode_seqs", ()))
            cost = a.prefill_base_s + len(decode_seqs) * a.decode_per_seq_s
            n = len(plan.chunks) + len(decode_seqs)
            toks = np.empty(n, np.int64)
            for i, c in enumerate(plan.chunks):
                cost += (c.length * a.prefill_per_token_s
                         + c.length * c.start * a.prefill_attn_quadratic_s)
                seq = c.seq
                so = seq.request.sampling_options
                toks[i] = self._token_for(seq.request.request_id, len(seq),
                                          so.temperature or 0.0)
            for j, seq in enumerate(decode_seqs, start=len(plan.chunks)):
                so = seq.request.sampling_options
                toks[j] = self._token_for(seq.request.request_id, len(seq),
                                          so.temperature or 0.0)
            self._simulate(cost)
            if decode_seqs:
                self.decode_dispatches += 1
                self.mixed_steps += 1
            return toks, np.full(n, -1.0, np.float32), None
        b = len(plan.seqs)
        self._simulate(a.decode_base_s + b * a.decode_per_seq_s)
        self.decode_dispatches += 1
        toks = np.empty(b, np.int64)
        for i, seq in enumerate(plan.seqs):
            so = seq.request.sampling_options
            toks[i] = self._token_for(seq.request.request_id, len(seq),
                                      so.temperature or 0.0)
        return toks, np.full(b, -1.0, np.float32), None

    # -- fused decode hooks (loop.py) --------------------------------------
    # The mocker's "device" is the host, so the block's tokens are computed
    # at dispatch time and the handle just carries them; the SINGLE
    # decode_base_s per block (vs per step) is the amortization the fused
    # dispatch exists to model. Token values match the per-step path:
    # _token_for keys on (request_id, position) and a block's row j sits at
    # position start_lens[i] + j — start_lens already carries the chained
    # offset, so chained blocks stay position-exact while host appends lag.

    @property
    def supports_multistep(self) -> bool:
        return self.args.decode_multistep > 1

    def dispatch_multistep(self, plan, prev_handle=None):
        a = self.args
        b, w = len(plan.seqs), plan.width
        self._simulate(a.decode_base_s + w * b * a.decode_per_seq_s)
        self.decode_dispatches += 1
        self.multistep_blocks += 1
        toks = np.empty((b, w), np.int64)
        for i, seq in enumerate(plan.seqs):
            so = seq.request.sampling_options
            for j in range(w):
                toks[i, j] = self._token_for(seq.request.request_id,
                                             plan.start_lens[i] + j,
                                             so.temperature or 0.0)
        return (toks, np.full((b, w), -1.0, np.float32))

    def fetch_packed_block(self, handle):
        toks, lps = handle
        return toks, lps, None


__all__ = ["MockerEngine", "MockEngineArgs"]
