"""Worker-side Prometheus metrics, served on the worker's system server.

Until now admission/migration/disagg signals existed only as frontend
metrics (``http/metrics.py``); a worker's own ``/metrics``
(``DYN_SYSTEM_ENABLED=1``, ``runtime/system_server.py``) showed nothing about
the requests it actually absorbed.  This registry closes that gap:

- ``dynamo_worker_requests_total{outcome}`` — requests by admission outcome:
  ``admitted``, ``refused_expired`` (deadline already passed on arrival),
  ``deadline_cancelled`` (expired mid-generation), ``error``.
- ``dynamo_worker_migration_replays_total`` — migration replays this worker
  ABSORBED (requests re-issued by a frontend after another worker dropped
  the stream; stamped via ``PreprocessedRequest.migration_attempt``).
- ``dynamo_worker_disagg_kv_bytes_total{direction,plane}`` — disagg KV block
  bytes moved, by direction (``pulled``) and transport plane
  (``direct``/``bulk``/``rpc``) — the FlowKV-dominant cost made visible.
- ``dynamo_tpu_stage_duration_seconds{stage}`` — per-stage latency breakdown
  (queue/prefill/kv_transfer/decode/...), observed from locally-finished
  trace spans (``http/metrics.StageMetrics`` listener), the same series the
  frontend registers so dashboards join on one name.

A process-wide singleton (``get_worker_metrics``) because the handler
factories (``llm/register.engine_handler``) and the disagg handlers have no
shared construction point; the worker main passes its registry to the
system server.
"""

from __future__ import annotations

from typing import Optional

from prometheus_client import CollectorRegistry, Counter

from dynamo_tpu.http.metrics import StageMetrics


class WorkerMetrics:
    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry or CollectorRegistry()
        ns = "dynamo_worker"
        self.requests_total = Counter(
            f"{ns}_requests_total",
            "Requests by admission outcome (admitted, refused_expired, "
            "deadline_cancelled, error)",
            ["outcome"], registry=self.registry)
        self.migration_replays = Counter(
            f"{ns}_migration_replays_total",
            "Migration replays absorbed (streams re-issued by a frontend "
            "after another worker dropped them)",
            registry=self.registry)
        self.disagg_kv_bytes = Counter(
            f"{ns}_disagg_kv_bytes_total",
            "Disaggregated-prefill KV block bytes transferred, by direction "
            "and transport plane (direct/bulk/rpc)",
            ["direction", "plane"], registry=self.registry)
        self.stage = StageMetrics(self.registry)

    def attach_tracer(self, tracer) -> None:
        """Observe stage spans finished in this process into the stage
        histogram (idempotent per tracer)."""
        self.stage.attach(tracer)


_metrics: Optional[WorkerMetrics] = None


def get_worker_metrics() -> WorkerMetrics:
    global _metrics
    if _metrics is None:
        _metrics = WorkerMetrics()
    return _metrics


__all__ = ["WorkerMetrics", "get_worker_metrics"]
