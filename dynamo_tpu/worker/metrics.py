"""Worker-side Prometheus metrics, served on the worker's system server.

Until now admission/migration/disagg signals existed only as frontend
metrics (``http/metrics.py``); a worker's own ``/metrics``
(``DYN_SYSTEM_ENABLED=1``, ``runtime/system_server.py``) showed nothing about
the requests it actually absorbed.  This registry closes that gap:

- ``dynamo_worker_requests_total{outcome}`` — requests by admission outcome:
  ``admitted``, ``refused_expired`` (deadline already passed on arrival),
  ``deadline_cancelled`` (expired mid-generation), ``error``.
- ``dynamo_worker_migration_replays_total{mode}`` — migrated streams this
  worker ABSORBED (requests re-issued by a frontend after another worker
  dropped or drained the stream; stamped via
  ``PreprocessedRequest.migration_attempt``): ``resume`` rode a pinned-KV
  resume token, ``replay`` recomputed from scratch.
- ``dynamo_worker_drain_state`` / ``dynamo_worker_migrated_sequences_total``
  — the graceful-drain lifecycle (``worker/drain.py``): drain progress and
  how many in-flight sequences were handed off resumable vs replayed.
- ``dynamo_worker_disagg_kv_bytes_total{direction,plane}`` — disagg KV block
  bytes moved, by direction (``pulled``) and transport plane
  (``direct``/``bulk``/``rpc``) — the FlowKV-dominant cost made visible.
- ``dynamo_tpu_stage_duration_seconds{stage}`` — per-stage latency breakdown
  (queue/prefill/kv_transfer/decode/...), observed from locally-finished
  trace spans (``http/metrics.StageMetrics`` listener), the same series the
  frontend registers so dashboards join on one name.

A process-wide singleton (``get_worker_metrics``) because the handler
factories (``llm/register.engine_handler``) and the disagg handlers have no
shared construction point; the worker main passes its registry to the
system server.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from prometheus_client import CollectorRegistry, Counter, Gauge
from prometheus_client.core import (CounterMetricFamily, GaugeMetricFamily,
                                    HistogramMetricFamily)

from dynamo_tpu.http.metrics import StageMetrics


class KvbmStatsCollector:
    """Scrape-time collector mapping ``TieredEngine.kvbm_stats()`` onto
    ``dynamo_worker_kvbm_*`` gauges/counters.

    Registered UNCONDITIONALLY (zero-valued until a tiered engine is
    attached via :meth:`attach`) so the metrics<->docs drift gate
    (``tools/check_metrics_docs.py``) always sees the full surface, and a
    worker without tiers still exposes a stable schema."""

    # kvbm_stats key -> help text; metric name = "dynamo_worker_" + key
    GAUGES: Dict[str, str] = {
        "kvbm_host_blocks": "KV blocks resident in the G2 host-RAM tier",
        "kvbm_host_bytes": "Bytes used by the G2 host-RAM tier",
        "kvbm_disk_blocks": "KV blocks resident in the G3 disk tier",
        "kvbm_disk_bytes": "Bytes used by the G3 disk tier",
        "kvbm_pending_spills": "Eviction batches waiting in the bounded "
                               "background spill queue",
        "kvbm_prefetch_pinned_pages": "Pages currently pinned by prefetch "
                                      "promotion leases (released when the "
                                      "request commits or aborts)",
        "kvbm_prefetch_inflight": "Requests with a live lookahead "
                                  "promotion task",
    }
    COUNTERS: Dict[str, str] = {
        "kvbm_offloaded_blocks": "Blocks offloaded G1->G2 on eviction",
        "kvbm_onboarded_blocks": "Tier blocks injected back into HBM "
                                 "(synchronous fast path + prefetch)",
        "kvbm_dropped_spills": "Spill batches dropped because the bounded "
                               "queue was full (tiers are best-effort)",
        "kvbm_peer_onboarded_blocks": "Blocks onboarded from the G4 peer "
                                      "tier on a local tier miss",
        "kvbm_disk_corrupt_dropped": "Disk-tier entries rejected by length/"
                                     "crc32 verification on read (treated "
                                     "as a miss, evicted — never injected)",
        "kvbm_prefetch_hits": "Blocks the prefetch scheduler promoted "
                              "ahead of the prefill cursor",
        "kvbm_prefetch_late": "Prefetch promotions that lost the race (the "
                              "block was already resident, or no pages "
                              "were free for it)",
        "kvbm_prefetch_misses": "Planned blocks that fell out of every "
                                "tier before promotion reached them",
        "kvbm_prefetch_evicted_pinned": "Canary: pinned prefetched blocks "
                                        "missing from HBM at release time "
                                        "(must stay 0)",
        "kvbm_prefetch_bytes": "Bytes of KV promoted by the prefetch "
                               "scheduler",
        "kvbm_prefetch_adopted_blocks": "Blocks adopted mid-prefill from "
                                        "the prefix cache instead of "
                                        "recomputed",
    }

    def __init__(self, registry: CollectorRegistry):
        self._source: Optional[Callable[[], Dict[str, float]]] = None
        registry.register(self)

    def attach(self, source: Callable[[], Dict[str, float]]) -> None:
        """Point the collector at a live ``kvbm_stats`` provider."""
        self._source = source

    def collect(self):
        stats: Dict[str, float] = {}
        if self._source is not None:
            try:
                stats = self._source() or {}
            except Exception:  # noqa: BLE001 — a scrape must never fail
                import logging
                logging.getLogger(__name__).debug(
                    "kvbm stats sample failed", exc_info=True)
        for key, help_text in self.GAUGES.items():
            yield GaugeMetricFamily(f"dynamo_worker_{key}", help_text,
                                    value=float(stats.get(key, 0)))
        for key, help_text in self.COUNTERS.items():
            yield CounterMetricFamily(f"dynamo_worker_{key}", help_text,
                                      value=float(stats.get(key, 0)))


class EngineDispatchCollector:
    """Scrape-time collector mapping the engine's dispatch taps onto
    ``dynamo_worker_decode_*`` counters (the PR 5 scatter-tap style:
    counts of jitted dispatches, not timing walls).

    Registered UNCONDITIONALLY (zero-valued until an engine is attached)
    so the metrics<->docs drift gate always sees the schema."""

    COUNTERS: Dict[str, str] = {
        "decode_dispatches": "Decode-family jitted dispatches (per-step, "
                             "chained, spec-verify, mixed, and fused "
                             "multi-step blocks each count ONE) — with "
                             "fusion on, M decoded tokens cost ~M/width "
                             "dispatches",
        "decode_multistep_blocks": "Fused multi-step decode blocks "
                                   "dispatched (DYN_DECODE_MULTISTEP steps "
                                   "per block before scheduler narrowing)",
        "mixed_dispatches": "Mixed prefill+decode dispatches (prefill "
                            "chunks and decode rows advanced in ONE "
                            "ragged [B, S] step, DYN_MIXED_BATCH)",
        "guided_parity_mismatches": "Guided rows whose host-side automaton "
                                    "re-walk disagreed with the device "
                                    "transition table after a fused block "
                                    "(logged once per row; any nonzero "
                                    "value is a device/host lowering bug)",
    }

    # the known fallback reasons, pre-seeded so every label shows on the
    # scrape at 0 and dashboards/alerts can reference them before the
    # first refusal happens. "mesh" is GONE on purpose: sharded engines
    # run the fused block program (explicit in/out shardings) — a mesh
    # engine reporting fallbacks again would be a regression, and the
    # parity suite asserts the counter stays 0 there. "penalties" and
    # "guided" now only fire when the device path is unavailable
    # (penalty_window=0 / no grammar lowering); "penalty_window" counts
    # rows whose distinct-token set outgrew the configured ring buffer,
    # "guided_table" grammars whose transition table exceeded the byte
    # cap (JaxEngineConfig.guided_table_bytes) — both per-batch, not
    # per-deployment.
    FALLBACK_REASONS = ("waiters", "prefill", "penalties",
                        "penalty_window", "guided", "guided_table",
                        "spec", "budget", "pages", "multihost")

    def __init__(self, registry: CollectorRegistry):
        self._source: Optional[Callable[[], Dict[str, float]]] = None
        registry.register(self)

    def attach(self, source: Callable[[], Dict[str, float]]) -> None:
        """Point the collector at a live engine's dispatch counters."""
        self._source = source

    def collect(self):
        stats: Dict[str, float] = {}
        if self._source is not None:
            try:
                stats = self._source() or {}
            except Exception:  # noqa: BLE001 — a scrape must never fail
                import logging
                logging.getLogger(__name__).debug(
                    "engine dispatch sample failed", exc_info=True)
        for key, help_text in self.COUNTERS.items():
            yield CounterMetricFamily(f"dynamo_worker_{key}", help_text,
                                      value=float(stats.get(key, 0)))
        # why the fused multi-step path was refused, by reason — the
        # ROADMAP "fallback-reason near zero" criterion, measurable
        fb = CounterMetricFamily(
            "dynamo_worker_multistep_fallback",
            "Fused multi-step decode refusals by reason (waiters/prefill "
            "only with DYN_MIXED_BATCH=0; penalties/guided/spec/budget/"
            "pages from the block planner; multihost from the engine "
            "mode — mesh-sharded engines fuse and never fall back)",
            labels=["reason"])
        reasons = dict.fromkeys(self.FALLBACK_REASONS, 0.0)
        reasons.update(stats.get("multistep_fallbacks") or {})
        for reason, value in sorted(reasons.items()):
            fb.add_metric([str(reason)], float(value))
        yield fb


class StepTraceCollector:
    """Scrape-time collector rendering the engine step flight recorder's
    inline aggregates (``engine/steptrace.StepRecorder.aggregates()``) as
    the fleet accounting layer: per-kind step duration / batch-occupancy
    histograms, the step-gap histogram (host overhead between
    dispatches), page-pool pressure gauges, and compile-event counters.

    Registered UNCONDITIONALLY (zero-valued until a recorder is attached)
    so the metrics<->docs drift gate always sees the schema. The recorder
    does the bucketing inline on the hot path; this collector only
    re-renders plain dicts at scrape time — a scrape never touches the
    step loop."""

    # the dispatch families the loop stamps; pre-seeded so dashboards can
    # reference every kind before the first dispatch of that kind runs
    KINDS = ("prefill", "decode", "chained", "multistep", "mixed", "spec",
             "gather")

    def __init__(self, registry: CollectorRegistry):
        self._source = None
        registry.register(self)

    def attach(self, source) -> None:
        """Point the collector at a live recorder's ``aggregates``."""
        self._source = source

    @staticmethod
    def _zero_hist(bounds) -> list:
        return [(str(b), 0) for b in bounds] + [("+Inf", 0)]

    def collect(self):
        agg: Dict[str, object] = {}
        if self._source is not None:
            try:
                agg = self._source() or {}
            except Exception:  # noqa: BLE001 — a scrape must never fail
                import logging
                logging.getLogger(__name__).debug(
                    "steptrace aggregate sample failed", exc_info=True)
        from dynamo_tpu.engine.steptrace import (_DUR_BOUNDS, _GAP_BOUNDS,
                                                 _OCC_BOUNDS)
        dur = HistogramMetricFamily(
            "dynamo_worker_step_duration_seconds",
            "Engine dispatch wall time by kind (prefill/decode/chained/"
            "multistep/mixed/spec/gather) — the host-side dispatch call, "
            "which includes compile time on a fresh jit bucket",
            labels=["kind"])
        occ = HistogramMetricFamily(
            "dynamo_worker_step_occupancy",
            "Batch occupancy per dispatch: real tokens / padded tokens "
            "(bucket-padding waste is 1 - occupancy), by kind",
            labels=["kind"])
        durs = dict(agg.get("duration") or {})
        occs = dict(agg.get("occupancy") or {})
        for kind in sorted(set(self.KINDS) | set(durs) | set(occs)):
            b, s, _n = durs.get(kind) or (self._zero_hist(_DUR_BOUNDS),
                                          0.0, 0)
            dur.add_metric([kind], buckets=b, sum_value=s)
            b, s, _n = occs.get(kind) or (self._zero_hist(_OCC_BOUNDS),
                                          0.0, 0)
            occ.add_metric([kind], buckets=b, sum_value=s)
        yield dur
        yield occ
        gap = HistogramMetricFamily(
            "dynamo_worker_step_gap_seconds",
            "Host time between the end of one dispatch and the start of "
            "the next while work was available (scheduler planning, token "
            "processing, exclusive-window stalls — idle waits excluded)")
        gb, gs, _gn = (agg.get("gap")
                       or (self._zero_hist(_GAP_BOUNDS), 0.0, 0))
        gap.add_metric([], buckets=gb, sum_value=gs)
        yield gap
        yield GaugeMetricFamily(
            "dynamo_worker_page_pool_free_pages",
            "Free KV pages at the most recent dispatch's plan time",
            value=float(agg.get("pool_free", 0)))
        yield GaugeMetricFamily(
            "dynamo_worker_page_pool_pinned_pages",
            "KV pages pinned under export leases at the most recent "
            "dispatch's plan time",
            value=float(agg.get("pool_pinned", 0)))
        ev = CounterMetricFamily(
            "dynamo_worker_compile_events",
            "XLA compiles detected mid-run (first call on a fresh "
            "(kind, batch, seq) jit bucket), by dispatch kind",
            labels=["kind"])
        secs = CounterMetricFamily(
            "dynamo_worker_compile_seconds",
            "Wall seconds spent in mid-run XLA compiles, by dispatch kind",
            labels=["kind"])
        cev = dict(agg.get("compile_events") or {})
        csec = dict(agg.get("compile_seconds") or {})
        for kind in sorted(set(self.KINDS) | set(cev) | set(csec)):
            ev.add_metric([kind], float(cev.get(kind, 0)))
            secs.add_metric([kind], float(csec.get(kind, 0.0)))
        yield ev
        yield secs


def engine_dispatch_stats(engine) -> Dict[str, object]:
    """The ``EngineDispatchCollector.attach`` source for a
    ``ScheduledEngineBase`` engine (JaxEngine and the mocker both carry
    the counters). Values are floats, except ``multistep_fallbacks``:
    a per-reason count dict the collector renders as a labeled family."""
    sched = getattr(engine, "scheduler", None)
    return {
        "decode_dispatches": float(getattr(engine, "decode_dispatches", 0)),
        "decode_multistep_blocks": float(
            getattr(engine, "multistep_blocks", 0)),
        "mixed_dispatches": float(getattr(engine, "mixed_steps", 0)),
        "guided_parity_mismatches": float(
            getattr(engine, "guided_parity_mismatches", 0)),
        "multistep_fallbacks": dict(
            getattr(sched, "multistep_fallbacks", None) or {}),
    }


class WorkerMetrics:
    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry or CollectorRegistry()
        ns = "dynamo_worker"
        self.requests_total = Counter(
            f"{ns}_requests_total",
            "Requests by admission outcome (admitted, refused_expired, "
            "deadline_cancelled, error)",
            ["outcome"], registry=self.registry)
        self.migration_replays = Counter(
            f"{ns}_migration_replays_total",
            "Migrated streams absorbed (re-issued by a frontend after "
            "another worker dropped or drained them), by mode: 'resume' "
            "carries a pinned-KV resume token (no recomputed prefill), "
            "'replay' recomputes from scratch",
            ["mode"], registry=self.registry)
        # -- graceful drain ---------------------------------------------
        self.drain_state = Gauge(
            f"{ns}_drain_state",
            "Worker lifecycle state: 0 serving, 1 draining (in-flight "
            "streams being frozen/handed off), 2 drained (migration "
            "complete or timed out; about to exit)",
            registry=self.registry)
        self.migrated_sequences = Counter(
            f"{ns}_migrated_sequences_total",
            "In-flight sequences this worker handed off during a graceful "
            "drain, by outcome: 'ok' shipped a pinned-KV resume token, "
            "'fallback' shipped a replay marker (nothing committed yet, "
            "or the engine cannot export KV)",
            ["outcome"], registry=self.registry)
        # pre-seed the label sets so every mode/outcome shows on the
        # scrape at 0 (dashboards/alerts can reference them before the
        # first drain happens)
        for mode in ("replay", "resume"):
            self.migration_replays.labels(mode)
        for outcome in ("ok", "fallback"):
            self.migrated_sequences.labels(outcome)
        self.disagg_kv_bytes = Counter(
            f"{ns}_disagg_kv_bytes_total",
            "Disaggregated-prefill KV block bytes transferred, by direction "
            "and transport plane (direct/bulk/rpc)",
            ["direction", "plane"], registry=self.registry)
        # -- data-plane fault tolerance ---------------------------------
        self.kv_exports_active = Gauge(
            f"{ns}_kv_exports_active",
            "KV export leases currently pinning pages for a pending pull "
            "(returns to 0 once pullers ack or the TTL GC reclaims)",
            registry=self.registry)
        self.kv_exports_reclaimed = Counter(
            f"{ns}_kv_exports_reclaimed_total",
            "Export leases reclaimed by the TTL GC sweep (the puller "
            "crashed or never acked — orphaned KV bounded, not leaked)",
            registry=self.registry)
        self.prefill_jobs = Counter(
            f"{ns}_prefill_jobs_total",
            "Prefill queue jobs by outcome (ok, failed, stale — dropped "
            "because the job outlived the decode side's reply timeout)",
            ["outcome"], registry=self.registry)
        self.kv_offer_acks = Counter(
            f"{ns}_kv_offer_acks_total",
            "Device-direct offer acks by outcome (ok, failed — a failed "
            "ack leaves the peer's pinned HBM to its offer TTL)",
            ["outcome"], registry=self.registry)
        self.kv_frames_corrupt = Counter(
            f"{ns}_kv_frames_corrupt_total",
            "Wire-v4 KV frames rejected by checksum verification before "
            "staging (corrupted/truncated in transit; never injected)",
            registry=self.registry)
        self.kv_pull_resumes = Counter(
            f"{ns}_kv_pull_resumes_total",
            "KV block pulls resumed after a mid-pull failure, re-pulling "
            "only the blocks not yet committed",
            registry=self.registry)
        # -- fleet-wide KV reuse (admission onboarding) -------------------
        self.kv_onboard = Counter(
            f"{ns}_kv_onboard_total",
            "Prompt blocks the admission path had to source beyond the "
            "local tiers, by source: 'peer' onboarded from another "
            "worker's KV export, 'recompute' left for local prefill "
            "(no peer held them, or every pull failed)",
            ["source"], registry=self.registry)
        self.kv_onboard_bytes = Counter(
            f"{ns}_kv_onboard_bytes_total",
            "KV bytes behind those admission decisions, by source: 'peer' "
            "counts wire bytes pulled, 'recompute' the cache bytes the "
            "local prefill will regenerate",
            ["source"], registry=self.registry)
        for source in ("peer", "recompute"):
            self.kv_onboard.labels(source)
            self.kv_onboard_bytes.labels(source)
        self.prefill_failovers = Counter(
            f"{ns}_prefill_failovers_total",
            "Remote-prefill retries on an alternate prefill instance "
            "after the first one failed, by outcome (ok, failed)",
            ["outcome"], registry=self.registry)
        self.stage = StageMetrics(self.registry)
        # KVBM tier/prefetch gauges+counters, sampled at scrape time from
        # TieredEngine.kvbm_stats() once attached (zero-valued until then)
        self.kvbm = KvbmStatsCollector(self.registry)
        # decode dispatch taps, sampled at scrape time from the engine's
        # counters once attached (zero-valued until then)
        self.engine = EngineDispatchCollector(self.registry)
        # step flight recorder aggregates (duration/occupancy/gap
        # histograms, pool gauges, compile counters), sampled at scrape
        # time once attached (zero-valued until then)
        self.steptrace = StepTraceCollector(self.registry)

    def attach_tracer(self, tracer) -> None:
        """Observe stage spans finished in this process into the stage
        histogram (idempotent per tracer)."""
        self.stage.attach(tracer)


_metrics: Optional[WorkerMetrics] = None


def get_worker_metrics() -> WorkerMetrics:
    global _metrics
    if _metrics is None:
        _metrics = WorkerMetrics()
    return _metrics


def count_metric(name: str, *labels: str, inc: float = 1) -> None:
    """Best-effort increment of a ``WorkerMetrics`` counter by attribute
    name — accounting must never fail serving, so lookup/label errors are
    swallowed (logged at debug). The one place the try/inc/except shape
    lives, instead of a copy per call site."""
    import logging
    try:
        c = getattr(get_worker_metrics(), name)
        if labels:
            c = c.labels(*labels)
        c.inc(inc)
    except Exception:  # noqa: BLE001 — accounting is never load-bearing
        logging.getLogger(__name__).debug(
            "worker metric %s%r increment failed", name, labels,
            exc_info=True)


__all__ = ["WorkerMetrics", "KvbmStatsCollector", "EngineDispatchCollector",
           "StepTraceCollector", "engine_dispatch_stats",
           "get_worker_metrics", "count_metric"]
