"""Graceful drain & live decode migration: the worker-side lifecycle.

A decode worker leaving the fleet on purpose (SIGTERM, ``POST /drain`` on
the system server, a planner scale-down) should not cost its in-flight
streams a full replay. The drain protocol, end to end:

1. **Announce** — every served endpoint re-puts its instance record with
   ``draining`` set (``ServedEndpoint.announce_draining``); routers exclude
   the instance from selection the moment their watch delivers the re-put,
   while the instance stays directly addressable for KV pulls.
2. **Freeze** — ``engine.drain_migrate`` (``engine/loop.py``) freezes each
   in-flight sequence at a step boundary: commits its full pages to the
   prefix cache, pins them under a TTL'd export lease, and emits a resume
   token (block chain + lease + sampling budgets + this worker's pull
   coordinates) as the stream's last frame. The serving layer relays the
   token and ends the stream through the failover path
   (``StreamMigrationSignal`` -> ``drop``), so the frontend's
   ``MigrationOperator`` re-issues the request on a survivor immediately.
3. **Resume** (survivor side) — ``ResumeAdmission`` pulls the pinned pages
   over the transport ladder (``worker/disagg.KvBlockPuller`` — the same
   machinery the disagg prefill handoff uses), acks the lease, and normal
   prefix-match admission adopts the resident chain: the request admits
   with ``cached_tokens`` covering everything already computed and decode
   continues from the next token — bit-identical for greedy/seeded rows
   (sampling is position-keyed).
4. **Wait & exit** — the draining worker waits (bounded by
   ``DYN_DRAIN_TIMEOUT_S``) for survivors to ack the export leases, then
   shuts its runtime down. A ``kill -9`` at ANY point degrades to the
   PR 2/6 behavior: keepalive detects the death, lease GC unpins, and the
   migration operator replays from scratch — migration is strictly an
   upgrade, never a new failure mode.

Observability: ``dynamo_worker_drain_state`` (0 serving / 1 draining /
2 drained), ``dynamo_worker_migrated_sequences_total{ok|fallback}`` on the
draining side, ``dynamo_worker_migration_replays_total{mode}`` on the
receiving side, and ``mode``/``resumed_tokens`` attrs on the frontend's
``migration`` trace event.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Dict, Iterable, Optional

logger = logging.getLogger(__name__)

# default bound on the post-freeze wait for survivors to pull + ack the
# pinned KV; past it the worker exits anyway (the TTL GC on nothing — the
# process is dying — and the survivors' resume pulls simply fail over to
# replay). Env DYN_DRAIN_TIMEOUT_S overrides.
DRAIN_TIMEOUT_S = 30.0


def drain_timeout_s() -> float:
    raw = os.environ.get("DYN_DRAIN_TIMEOUT_S")
    if raw is None:
        return DRAIN_TIMEOUT_S
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        logger.warning("malformed DYN_DRAIN_TIMEOUT_S %r; using %.0f",
                       raw, DRAIN_TIMEOUT_S)
        return DRAIN_TIMEOUT_S


def _drain_engine(engine):
    """The object carrying ``drain_migrate`` (unwraps one wrapper layer —
    ``TieredEngine.engine``, ``DisaggDecodeHandler.engine``)."""
    for cand in (engine, getattr(engine, "engine", None)):
        if cand is not None and hasattr(cand, "drain_migrate"):
            return cand
    return None


class ResumeAdmission:
    """Survivor-side half of live migration: turn an inbound resume token
    into resident KV before admission.

    ``engine_handler`` (``llm/register.py``) hands every inbound
    ``kv_transfer_params["migration"]`` token here. The token's block
    chain was committed by the draining worker under the SAME chained
    content hashes this engine computes for the rebuilt prompt (original
    prompt + generated tokens), so after the pull the scheduler's normal
    prefix-match admission adopts the chain — ``cached_tokens`` covers
    everything already computed and the stream continues from the next
    token. Every failure degrades to a replay (the token ids carry the
    whole prompt); resume is an optimization, never a gate."""

    def __init__(self, engine, kv_client=None):
        from dynamo_tpu.worker.disagg import KvBlockPuller

        self.engine = engine
        self.puller = KvBlockPuller(engine, kv_client=kv_client)

    @property
    def kv_client(self):
        return self.puller.kv_client

    @kv_client.setter
    def kv_client(self, client) -> None:
        self.puller.kv_client = client

    async def admit(self, request, token: Dict[str, Any],
                    span=None) -> bool:
        """Pull the token's pinned blocks so admission resumes; returns
        True when the full advertised chain is resident afterwards."""
        blocks = token.get("blocks") or []
        if not blocks or self.engine is None:
            return False
        hashes = [b[0] for b in blocks]
        ok = False
        try:
            missing = self.puller.missing(hashes)
            if missing:
                iid = int(token.get("instance_id", 0) or 0)
                await self.puller.pull_blocks(
                    hashes, iid,
                    bulk_address=str(token.get("bulk_address", "") or ""),
                    lease=token.get("lease"))
            elif token.get("lease") is not None:
                # nothing to pull (all resident) — still ack so the
                # draining worker unpins now instead of at its timeout
                iid = int(token.get("instance_id", 0) or 0)
                await self.puller._ack_export_lease(iid,
                                                    int(token["lease"]))
            ok = not self.puller.missing(hashes)
        except Exception as e:  # noqa: BLE001 — resume must never fail the
            # request: missing blocks just recompute (replay semantics)
            logger.warning("resume pull for %s failed (%s); admission "
                           "falls back to recompute", request.request_id, e)
        if span is not None:
            span.set_attr("resume_blocks", len(blocks))
            span.set_attr("resume_resident",
                          len(blocks) - len(self.puller.missing(hashes)))
            span.set_attr("resume_ok", ok)
        if not ok:
            logger.info(
                "resume admission for %s incomplete (%d/%d blocks "
                "resident); missing prefix recomputes",
                request.request_id,
                len(blocks) - len(self.puller.missing(hashes)), len(blocks))
        return ok


class DrainController:
    """Worker-side drain orchestration: announce -> freeze -> wait -> exit.

    One controller per worker process, shared by the SIGTERM handler, the
    system server's ``POST /drain``, and (in tests) the ``WorkerDrain``
    fault harness — all of them drive the same staged methods, so chaos
    drills exercise exactly the production path. ``drain()`` is
    idempotent: concurrent triggers await the first run."""

    STATE = {"serving": 0, "draining": 1, "drained": 2}

    def __init__(self, engine, served: Iterable = (),
                 resume_extras: Optional[dict] = None,
                 on_drained=None, timeout_s: Optional[float] = None):
        self.engine = engine
        self.served = list(served)
        # the pull coordinates survivors need, stamped into every resume
        # token: this worker's instance id (for kv_export .direct calls)
        # and, when it runs a bulk server, its bulk address
        self.resume_extras = dict(resume_extras or {})
        self.on_drained = on_drained
        self.timeout_s = timeout_s
        self.state = "serving"
        self.counts: Dict[str, int] = {}
        self._task: Optional[asyncio.Task] = None

    # -- state --------------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self.state != "serving"

    def _set_state(self, state: str) -> None:
        self.state = state
        try:
            from dynamo_tpu.worker.metrics import get_worker_metrics
            get_worker_metrics().drain_state.set(self.STATE[state])
        except Exception:  # noqa: BLE001 — accounting never gates the drain
            pass

    # -- staged protocol ----------------------------------------------------

    async def announce(self) -> None:
        """Flag every served endpoint as draining so routers route around
        this worker (new work stops arriving before anything freezes)."""
        for se in self.served:
            try:
                await se.announce_draining()
            except Exception:  # noqa: BLE001 — refusal-and-replay covers
                logger.warning("drain announcement failed", exc_info=True)

    async def freeze(self) -> Dict[str, int]:
        """Freeze the in-flight streams into resume/replay handoffs and
        count them (``dynamo_worker_migrated_sequences_total``)."""
        eng = _drain_engine(self.engine)
        if eng is None:
            self.counts = {"resume": 0, "replay": 0}
            return self.counts
        counts = await eng.drain_migrate(resume_extras=self.resume_extras)
        self.counts = counts
        from dynamo_tpu.worker.metrics import count_metric
        if counts.get("resume"):
            count_metric("migrated_sequences", "ok", inc=counts["resume"])
        if counts.get("replay"):
            count_metric("migrated_sequences", "fallback",
                         inc=counts["replay"])
        return counts

    async def wait_leases(self, timeout_s: Optional[float] = None) -> bool:
        """Wait for survivors to pull + ack the KV the FREEZE pinned
        (bounded). Only the drain's own lease ids are waited on —
        unrelated export leases (an orphaned disagg handoff waiting out
        its TTL, a peer-tier pull) must not stall the exit. Returns True
        when every drain lease was released in time."""
        from dynamo_tpu.engine.transfer import get_export_leases
        eng = _drain_engine(self.engine)
        mgr = get_export_leases(eng) if eng is not None else None
        ids = list(getattr(eng, "_drain_leases", ()) or ())
        if mgr is None or not ids:
            return True
        timeout = drain_timeout_s() if timeout_s is None else timeout_s
        deadline = time.monotonic() + timeout
        while any(mgr.holds(i) for i in ids):
            if time.monotonic() >= deadline:
                logger.warning(
                    "drain timeout (%.1fs): %d drain lease(s) still "
                    "pinned; exiting anyway — survivors fall back to "
                    "replay", timeout,
                    sum(1 for i in ids if mgr.holds(i)))
                return False
            await asyncio.sleep(0.05)
        return True

    # -- the one-shot entrypoint --------------------------------------------

    def trigger(self, reason: str = "signal") -> "asyncio.Task":
        """Start (or join) the drain from a sync context (signal handler,
        HTTP route)."""
        if self._task is None:
            if self.state == "serving":
                # flip synchronously so the caller (signal handler, HTTP
                # route) observes the transition before the task runs
                self._set_state("draining")
            self._task = asyncio.ensure_future(self.drain(reason))
        return self._task

    async def drain(self, reason: str = "request") -> Dict[str, int]:
        if self._task is not None and self._task is not asyncio.current_task():
            return await asyncio.shield(self._task)
        # register ourselves so a concurrent trigger() (SIGTERM racing
        # POST /drain, or either racing a direct drain() call) joins this
        # run instead of starting a second announce/freeze pass
        self._task = asyncio.current_task()
        if self.state == "drained":
            return self.counts
        self._set_state("draining")
        logger.info("graceful drain started (%s)", reason)
        t0 = time.monotonic()
        await self.announce()
        counts = await self.freeze()
        acked = await self.wait_leases(self.timeout_s)
        self._set_state("drained")
        logger.info(
            "drain complete in %.2fs: %d resumable + %d replay stream(s) "
            "handed off%s", time.monotonic() - t0,
            counts.get("resume", 0), counts.get("replay", 0),
            "" if acked else " (lease-ack wait timed out)")
        if self.on_drained is not None:
            try:
                self.on_drained()
            except Exception:  # noqa: BLE001
                logger.exception("on_drained hook failed")
        return counts


def install_signal_drain(controller: DrainController) -> bool:
    """Route SIGTERM into a graceful drain (the k8s/preemption path).
    Returns False when signal handlers cannot be installed here (non-main
    thread, non-unix) — the worker still drains via ``POST /drain``."""
    import signal

    try:
        loop = asyncio.get_running_loop()
        loop.add_signal_handler(signal.SIGTERM,
                                lambda: controller.trigger("SIGTERM"))
        return True
    except (NotImplementedError, RuntimeError, ValueError):
        logger.debug("SIGTERM drain handler unavailable", exc_info=True)
        return False


__all__ = ["ResumeAdmission", "DrainController", "install_signal_drain",
           "drain_timeout_s", "DRAIN_TIMEOUT_S"]
