"""TPU worker: serve a Llama-family model on jax behind the runtime.

Flow parity with the reference worker startup (SURVEY §3.2;
``components/backends/vllm/src/dynamo/vllm/main.py:43-65``):
connect the distributed runtime → build the model card → spin up the engine →
publish KV events + load metrics → ``serve_endpoint`` + ``register_llm``.
The engine here is the native ``JaxEngine`` rather than a subprocess CUDA
stack, so "spin up" is: load HF weights into a stacked-layer pytree (sharded
onto the TPU mesh when ``--tensor-parallel-size`` > 1) and allocate the paged
KV cache.

KV events ride the coordinator event bus on subject
``{namespace}.{component}.kv_events`` (reference: per-worker NATS ``kv_events``
subject, ``lib/llm/src/kv_router/publisher.rs:57-99``); worker load metrics are
served to stat scrapers via the endpoint stats hook (reference:
``WorkerMetricsPublisher`` + ``$SRV.STATS``).
"""

from __future__ import annotations

import argparse
import asyncio
import logging

import jax

from dynamo_tpu.engine.jax_engine import JaxEngine, JaxEngineConfig
from dynamo_tpu.llm.register import register_llm, serve_engine
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.hf_loader import load_hf_params
from dynamo_tpu.runtime.runtime import DEFAULT_COORDINATOR, DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging
from dynamo_tpu.worker.events import kv_events_subject, ordered_kv_publisher

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="dynamo_tpu jax worker")
    p.add_argument("--coordinator", default=DEFAULT_COORDINATOR)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="tpu")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--model-path", required=True,
                   help="HF-style model dir (config/tokenizer[/safetensors])")
    p.add_argument("--model-name", default=None)
    p.add_argument("--random-weights", action="store_true",
                   help="skip checkpoint load; random init (dev/benchmarks)")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--num-pages", type=int, default=2048)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-num-seqs", type=int, default=64)
    p.add_argument("--max-prefill-chunk", type=int, default=1024)
    p.add_argument("--max-context", type=int, default=8192)
    p.add_argument("--tensor-parallel-size", type=int, default=1,
                   help="shard the model over this many local devices")
    p.add_argument("--data-parallel-size", type=int, default=1,
                   help="shard the BATCH over this many mesh devices "
                        "(one engine, dp x tp mesh — composes with "
                        "multi-host; distinct from running dp separate "
                        "engines behind the router)")
    p.add_argument("--pipeline-parallel-size", type=int, default=1,
                   help="stage the layers over this many devices "
                        "(microbatch pipeline; scan attention path)")
    p.add_argument("--sequence-parallel-size", type=int, default=1,
                   help="ring-attention sequence parallelism: prompts longer "
                        "than the prefill chunk budget prefill in one "
                        "sequence-sharded step over this many devices")
    p.add_argument("--attn-impl", default="auto",
                   choices=["auto", "pallas", "pallas_unrolled", "scan",
                            "unrolled"],
                   help="engine attention implementation (auto = Pallas "
                        "kernels on TPU, XLA scan elsewhere); explicit "
                        "values drive on-chip A/Bs")
    p.add_argument("--quantize", choices=["", "int8"], default="",
                   help="load-time weight quantization: int8 = W8A8 "
                        "dynamic (halves the decode-step parameter "
                        "stream; llama-family dense models)")
    p.add_argument("--moe-backend", choices=["dense", "dispatch"],
                   default=None,
                   help="MoE expert compute: dense (every expert, every "
                        "token — decode-batch default) or dispatch "
                        "(capacity-factor token gather — wide-EP)")
    p.add_argument("--host-cache-bytes", type=int, default=0,
                   help="KVBM G2 host-RAM KV tier budget (0 disables)")
    p.add_argument("--disk-cache-bytes", type=int, default=0,
                   help="KVBM G3 disk KV tier budget (0 disables)")
    p.add_argument("--disk-cache-path", default="/tmp/dynamo_tpu_kvbm")
    p.add_argument("--num-top-logprobs", type=int, default=8,
                   help="alternatives computed per sampled token (serves "
                        "OpenAI top_logprobs up to this; 0 disables)")
    p.add_argument("--speculative-num-tokens", type=int, default=0,
                   help="n-gram prompt-lookup speculative decoding: "
                        "drafts verified per [B, K+1] step (0 disables; "
                        "all built-in families; composes with pipelined "
                        "decode — engine/spec.py)")
    p.add_argument("--speculative-ngram-max", type=int, default=4,
                   help="largest context-suffix n-gram the prompt-lookup "
                        "proposer matches")
    p.add_argument("--speculative-ngram-min", type=int, default=2,
                   help="smallest n-gram worth matching (1 is aggressive)")
    p.add_argument("--speculative-chain-break", type=int, default=8,
                   help="with speculation on, break a pipelined decode "
                        "chain after this many steps so fresh context "
                        "gets a chance to draft (0 disables chaining)")
    p.add_argument("--decode-multistep", type=int, default=None,
                   help="decode steps fused into one jitted dispatch with "
                        "on-device sampling/stop checks (default: "
                        "DYN_DECODE_MULTISTEP or 8; 1 disables fusion)")
    p.add_argument("--penalty-window", type=int, default=32,
                   help="device ring-buffer slots per penalized/logit_bias "
                        "row — such rows ride the fused decode block while "
                        "their distinct penalizable ids fit (raise for "
                        "long penalized generations; 0 disables the "
                        "device path and such rows decode per-step)")
    p.add_argument("--guided-table-bytes", type=int, default=8 << 20,
                   help="byte cap for a guided grammar's dense device "
                        "transition table; grammars over the cap degrade "
                        "per-row to per-step decode (fallback reason "
                        "guided_table)")
    p.add_argument("--no-kv-events", action="store_true")
    p.add_argument("--num-nodes", type=int, default=1,
                   help="multi-host: total processes in the jax world")
    p.add_argument("--node-rank", type=int, default=0,
                   help="multi-host: this process's rank (0 = leader, "
                        "serves the endpoint; >0 = step follower)")
    p.add_argument("--jax-coordinator", default=None,
                   help="multi-host: jax.distributed coordinator address "
                        "(host:port of rank 0)")
    p.add_argument("--local-devices", type=int, default=None,
                   help="multi-host: local device count override "
                        "(virtual-CPU tests; autodetected on TPU)")
    p.add_argument("--disagg", choices=["none", "prefill", "decode"],
                   default="none",
                   help="disaggregated role: 'prefill' serves prefill+KV "
                        "export; 'decode' pulls prefixes from the prefill "
                        "component and decodes")
    p.add_argument("--prefill-component", default="prefill",
                   help="component name of the prefill workers (decode role)")
    p.add_argument("--disagg-strategy", choices=["decode_first",
                                                 "prefill_first"],
                   default="decode_first",
                   help="decode_first: decode workers receive requests and "
                        "delegate prefill (default). prefill_first: prefill "
                        "workers receive requests, prefill locally, and "
                        "forward to decode workers with the KV handoff "
                        "attached (reference: trtllm handler_base.py:34-60)")
    p.add_argument("--decode-component", default="tpu",
                   help="component name of the decode workers "
                        "(prefill role, prefill_first strategy)")
    p.add_argument("--data-parallel-rank", type=int, default=None,
                   help="engine-dp rank advertised in load metrics (the "
                        "router's per-rank dp accounting)")
    p.add_argument("--bulk-host", default="127.0.0.1",
                   help="bind host for the bulk KV data plane (prefill "
                        "role); use this host's DCN address for cross-host "
                        "disagg")
    return p


def arm_guided(engine, card) -> None:
    """Give the engine the tokenizer's byte vocabulary so response_format
    guided decoding works; a failure disables the feature, never the
    process. Shared by the worker and the single-process run CLI."""
    if not hasattr(engine, "enable_guided"):
        return
    try:
        engine.enable_guided(card.load_tokenizer().token_bytes(),
                             card.eos_token_ids)
    except Exception:  # noqa: BLE001 — guided off beats worker down
        logging.getLogger(__name__).exception(
            "guided decoding disabled: token_bytes extraction failed")


def build_engine(args: argparse.Namespace) -> JaxEngine:
    is_gguf = args.model_path.endswith(".gguf")
    if is_gguf:
        from dynamo_tpu.models.gguf import GgufFile
        cfg = GgufFile(args.model_path).to_model_config(dtype=args.dtype)
    else:
        cfg = ModelConfig.from_pretrained(args.model_path, dtype=args.dtype)
    if args.moe_backend is not None and cfg.num_experts:
        import dataclasses
        cfg = dataclasses.replace(cfg, moe_backend=args.moe_backend)
    engine_cfg = JaxEngineConfig(
        num_pages=args.num_pages, page_size=args.page_size,
        max_num_seqs=args.max_num_seqs,
        max_prefill_chunk=args.max_prefill_chunk,
        max_context=min(args.max_context, cfg.max_position_embeddings),
        num_top_logprobs=args.num_top_logprobs,
        attn_impl=args.attn_impl, quantize=args.quantize,
        spec_tokens=args.speculative_num_tokens,
        spec_ngram_max=args.speculative_ngram_max,
        spec_ngram_min=args.speculative_ngram_min,
        spec_chain_break=args.speculative_chain_break,
        decode_multistep=args.decode_multistep,
        penalty_window=args.penalty_window,
        guided_table_bytes=args.guided_table_bytes)
    forward_fn = None
    pp = args.pipeline_parallel_size
    if pp > 1:
        import functools

        from dynamo_tpu.parallel.mesh import MeshSpec, make_mesh
        from dynamo_tpu.parallel.pipeline import (
            pipeline_forward, pp_sharding_fns)
        if args.sequence_parallel_size > 1:
            raise SystemExit("--pipeline-parallel-size does not combine "
                             "with sp yet")
        if args.num_nodes > 1:
            raise SystemExit("--pipeline-parallel-size with --num-nodes>1 "
                             "is not wired yet (the engine's multihost "
                             "input broadcast is gated on cfg.mesh, which "
                             "the pp path does not set)")
        if cfg.num_layers % pp:
            raise SystemExit(
                f"model has {cfg.num_layers} layers — not divisible by "
                f"--pipeline-parallel-size {pp}")
        from dynamo_tpu.parallel.pipeline import stage_adapter_for
        if stage_adapter_for(cfg) is None:
            # only families with a pipeline stage adapter (llama tree,
            # gemma-2, MoE) may stage; running an MLA model through
            # another family's layers would serve silently wrong outputs
            raise SystemExit(
                f"--pipeline-parallel-size has no stage adapter for "
                f"{cfg.model_type!r}; this family is served by tp/dp/sp "
                f"instead")
        if cfg.num_experts and cfg.moe_backend == "dispatch":
            logger.warning(
                "MoE dispatch drop accounting is not surfaced under "
                "--pipeline-parallel-size: worker_stats.moe_dropped_tokens "
                "will read 0 even when experts overflow capacity")
        pp_tp = args.tensor_parallel_size
        pp_dp = args.data_parallel_size
        mesh = make_mesh(MeshSpec(pp=pp, tp=pp_tp, dp=pp_dp),
                         devices=jax.devices()[:pp * pp_tp * pp_dp])
        shard_params, shard_pages = pp_sharding_fns(mesh, cfg)
        engine_cfg.shard_params_fn = shard_params
        engine_cfg.shard_pages_fn = shard_pages
        if pp_dp > 1:
            # the engine aligns batch buckets to dp and re-replicates the
            # packed sample output when cfg.mesh carries a dp axis
            engine_cfg.mesh = mesh
        forward_fn = functools.partial(pipeline_forward, mesh=mesh)
    tp, sp = args.tensor_parallel_size, args.sequence_parallel_size
    dp = args.data_parallel_size
    if (tp > 1 or sp > 1 or dp > 1) and pp == 1:
        from dynamo_tpu.parallel.mesh import MeshSpec, make_mesh
        from dynamo_tpu.parallel.sharding import ModelSharding
        # multi-host: the mesh spans every process's devices (global set)
        mesh = make_mesh(MeshSpec(dp=dp, tp=tp, sp=sp),
                         devices=jax.devices()[:dp * tp * sp])
        shard = ModelSharding(cfg, mesh)
        engine_cfg.shard_params_fn = shard.shard_params
        engine_cfg.shard_pages_fn = shard.shard_pages
        engine_cfg.mesh = mesh
    if args.random_weights:
        from dynamo_tpu.models import get_family
        params = get_family(cfg).init_params(cfg, jax.random.PRNGKey(0))
    elif is_gguf:
        from dynamo_tpu.models.gguf import load_gguf_params
        params = load_gguf_params(cfg, args.model_path)
    else:
        params = load_hf_params(cfg, args.model_path)
    return JaxEngine(cfg, params, engine_cfg, forward_fn=forward_fn)


async def amain(args: argparse.Namespace) -> None:
    # accept HF repo ids as well as local dirs/.gguf (reference: hub.rs)
    from dynamo_tpu.models.hub import resolve_model_path
    args.model_path = resolve_model_path(args.model_path)

    multihost = args.num_nodes > 1
    if multihost:
        if args.jax_coordinator is None:
            raise SystemExit("--jax-coordinator required with --num-nodes>1")
        # must precede any jax backend use (build_engine, jax.devices)
        from dynamo_tpu.parallel.multihost import initialize_distributed
        initialize_distributed(args.jax_coordinator, args.num_nodes,
                               args.node_rank,
                               local_device_count=args.local_devices)

    drt = await DistributedRuntime.create(coordinator=args.coordinator)

    if multihost and args.node_rank > 0:
        await _follower_main(args, drt)
        return

    card = ModelDeploymentCard.from_local_path(args.model_path,
                                               name=args.model_name)
    card.kv_cache_block_size = args.page_size
    card.num_top_logprobs = args.num_top_logprobs
    endpoint = (drt.namespace(args.namespace).component(args.component)
                .endpoint(args.endpoint))
    engine = build_engine(args)
    # advertise the engine's sparse penalty/logit_bias window so the
    # frontend preprocessor rejects requests the device would truncate
    card.penalty_window = engine.cfg.penalty_window
    # arm guided decoding (response_format): the engine needs the
    # tokenizer's byte view of the vocabulary to walk grammar masks
    arm_guided(engine, card)

    # a dead engine loop takes the worker's registration down with it, so
    # routers stop sending to a zombie (reference: task.rs critical tasks)
    engine.on_loop_exit = drt.runtime.shutdown
    engine.scheduler.dp_rank = args.data_parallel_rank

    tiered = None
    prefix_reader = None
    if args.host_cache_bytes > 0 or args.disk_cache_bytes > 0:
        # multihost OK: tier gathers/scatters ride the broadcast step
        # stream (engine.dispatch_gather_pages / scatter_pages_host), so
        # every rank joins the jits on the sharded cache
        if args.disagg == "decode":
            raise SystemExit(
                "KVBM tiers with --disagg decode are not supported yet: "
                "the disagg decode path pulls prefixes from prefill "
                "workers and bypasses tier onboarding")
        from dynamo_tpu.kvbm.manager import (
            TieredEngine, TieredKvConfig, serve_tiered_kv_export)
        from dynamo_tpu.worker.disagg import KV_EXPORT_ENDPOINT
        tiered = TieredEngine(engine, TieredKvConfig(
            host_budget_bytes=max(args.host_cache_bytes, 1),
            disk_budget_bytes=args.disk_cache_bytes,
            disk_path=args.disk_cache_path))
        # G4 remote tier: serve this worker's HBM+tier blocks to peers on
        # the same component, and fetch from peers on a local tier miss
        # (reference: CacheLevel::G4, block_manager/distributed/).
        # The disagg-prefill branch below registers the SAME endpoint name
        # with the tier-aware handler itself — registering here too would
        # be overwritten (last register wins).
        g4_ep = (drt.namespace(args.namespace).component(args.component)
                 .endpoint(KV_EXPORT_ENDPOINT))
        if args.disagg != "prefill":
            await g4_ep.serve(serve_tiered_kv_export(tiered))
        g4_lease = await drt.primary_lease()
        tiered.enable_peer_fetch(await g4_ep.client(),
                                 self_instance_id=g4_lease.lease_id)
        # fleet-wide KV reuse: mirror the coordinator-backed global prefix
        # index so admission onboarding pulls from the best-overlap holder
        # first instead of probing peers blindly
        from dynamo_tpu.kv_router.global_index import GlobalPrefixIndexReader
        prefix_reader = GlobalPrefixIndexReader(drt.kv_store())
        await prefix_reader.start()
        tiered.enable_global_index(prefix_reader)

    from dynamo_tpu.worker.disagg import get_kv_bandwidth_book

    def worker_stats() -> dict:
        d = engine.stats().to_dict()
        if tiered is not None:
            d["kvbm"] = tiered.kvbm_stats()
        # per-plane KV-transfer bandwidth EWMAs (bulk/rpc/direct) so the
        # frontend cost router sees transfer health without a scrape
        bw = get_kv_bandwidth_book().snapshot()
        if bw:
            d["kv_transfer"] = bw
        return d

    if multihost:
        # followers subscribed before checking in, so serving can't outrun
        # them; install the step broadcast tap only once all are present
        from dynamo_tpu.parallel.multihost import (
            StepFanout, barrier_id, step_subject)
        from dynamo_tpu.runtime.barrier import leader_barrier
        subject = step_subject(args.namespace, args.component)
        await leader_barrier(drt, barrier_id(args.namespace, args.component),
                             {"model": args.model_name or args.model_path},
                             num_workers=args.num_nodes - 1, timeout=120.0)
        StepFanout(drt, subject).install(engine)
        logger.info("multihost leader: %d followers in lockstep",
                    args.num_nodes - 1)

    event_pump: asyncio.Task | None = None
    prefix_pub = None
    if not args.no_kv_events:
        lease = await drt.primary_lease()
        publish_kv, event_pump = ordered_kv_publisher(
            drt, kv_events_subject(args.namespace, args.component),
            lease.lease_id)
        # the same event stream also feeds the fleet-wide prefix index:
        # batched/deduped holder snapshots in the coordinator kv-store so
        # OTHER frontends and peers see this worker's cache contents
        from dynamo_tpu.kv_router.global_index import GlobalPrefixPublisher
        prefix_pub = GlobalPrefixPublisher(drt.kv_store(), lease.lease_id)
        await prefix_pub.start()

        def _kv_event_cb(events, _pub=publish_kv, _gp=prefix_pub):
            _pub(events)
            for ev in events:
                _gp.apply_event(ev)

        engine.kv_event_cb = _kv_event_cb

    handler = None
    prefill_first = args.disagg_strategy == "prefill_first"
    # graceful drain & live migration (worker/drain.py): workers that hold
    # decode streams serve their component's kv_export endpoint so a
    # SURVIVOR can pull a draining peer's pinned sequence KV, and admit
    # inbound resume tokens through ResumeAdmission. Tiered and
    # disagg-prefill workers already serve the endpoint (G4 peer tier /
    # prefill export) — registering again would clobber the richer handler.
    resume_admission = None
    served_main = None
    comp = drt.namespace(args.namespace).component(args.component)
    if args.disagg != "prefill":
        from dynamo_tpu.engine.transfer import serve_kv_export
        from dynamo_tpu.worker.disagg import KV_EXPORT_ENDPOINT
        from dynamo_tpu.worker.drain import ResumeAdmission
        if tiered is None:
            await comp.endpoint(KV_EXPORT_ENDPOINT).serve(
                serve_kv_export(engine))
        resume_admission = ResumeAdmission(
            engine, kv_client=await comp.endpoint(KV_EXPORT_ENDPOINT)
            .client())
    if args.disagg == "decode":
        from dynamo_tpu.worker.disagg import DisaggDecodeHandler
        handler = await DisaggDecodeHandler(
            engine, drt, args.namespace, args.prefill_component,
            # prefill-first decode workers never INITIATE remote prefill —
            # they receive requests with the KV handoff already attached
            use_queue=not prefill_first,
            strategy=args.disagg_strategy).start()
        from dynamo_tpu.llm.register import engine_handler
        await engine.start()
        served_main = await endpoint.serve(
            engine_handler(handler, resume_admission),
            stats_provider=worker_stats)
    elif args.disagg == "prefill" and prefill_first:
        from dynamo_tpu.llm.register import engine_handler
        from dynamo_tpu.worker.disagg import PrefillFirstHandler
        pf_lease = await drt.primary_lease()
        handler = await PrefillFirstHandler(
            engine, drt, args.namespace, args.decode_component,
            instance_id=pf_lease.lease_id).start()
        await engine.start()
        served_main = await endpoint.serve(engine_handler(handler),
                                           stats_provider=worker_stats)
    else:
        served_main = await serve_engine(
            endpoint, tiered if tiered is not None else engine,
            stats_provider=worker_stats,
            resume_admission=resume_admission)
    # the aux plane (embeddings + prompt scoring) rides every worker that
    # serves chat traffic, so DISTRIBUTED frontends can offer
    # /v1/embeddings and completions echo (RemotePipeline calls it)
    if args.disagg != "prefill" or prefill_first:
        from dynamo_tpu.llm.register import serve_aux
        await serve_aux(
            drt.namespace(args.namespace).component(args.component), engine)
    bulk_server = None
    queue_worker = None
    if args.disagg == "prefill":
        # serve the KV block fetch endpoint for decode workers; register as
        # model_type=prefill so frontends don't route chat traffic here.
        # Bulk KV bytes ride the dedicated raw-socket plane (runtime/bulk.py
        # — the NIXL-role transport); the RPC endpoint stays as the
        # control/fallback path.
        from dynamo_tpu.engine.transfer import (
            serve_kv_export, serve_kv_export_bulk)
        from dynamo_tpu.runtime.bulk import BulkServer
        from dynamo_tpu.worker.disagg import KV_EXPORT_ENDPOINT
        kv_ep = (drt.namespace(args.namespace).component(args.component)
                 .endpoint(KV_EXPORT_ENDPOINT))
        lease = await drt.primary_lease()
        bulk_server = BulkServer(
            host=args.bulk_host,
            unix_path=f"/tmp/dynamo_tpu_bulk_{lease.lease_id:x}.sock",
            ident=f"{lease.lease_id:x}").start()
        if tiered is not None:
            # tier-aware export on BOTH planes: peers and decode workers
            # can fetch blocks that fell out of this worker's HBM into
            # G2/G3 whichever transport they pick
            from dynamo_tpu.kvbm.manager import (
                serve_tiered_kv_export, serve_tiered_kv_export_bulk)
            kv_handler = serve_tiered_kv_export(tiered)
            bulk_handler = serve_tiered_kv_export_bulk(
                tiered, asyncio.get_running_loop())
        else:
            kv_handler = serve_kv_export(engine)
            bulk_handler = serve_kv_export_bulk(
                engine, asyncio.get_running_loop())
        bulk_server.register(KV_EXPORT_ENDPOINT, bulk_handler)
        # device-direct plane (jax transfer server): blocks pull chip-to-
        # chip with no host bounce when the decode side supports it; HBM-
        # resident blocks only, so the tiered export keeps the host planes
        direct_address = ""
        if tiered is None:
            from dynamo_tpu.engine.transfer import (
                KV_EXPORT_DIRECT_ENDPOINT, serve_kv_export_direct)
            from dynamo_tpu.worker.disagg import make_device_transfer_plane
            plane = make_device_transfer_plane(engine)
            if plane is not None:
                try:
                    plane.host = args.bulk_host
                    direct_address = plane.address
                    direct_ep = (drt.namespace(args.namespace)
                                 .component(args.component)
                                 .endpoint(KV_EXPORT_DIRECT_ENDPOINT))
                    await direct_ep.serve(
                        serve_kv_export_direct(engine, plane))
                except Exception:  # noqa: BLE001 — serving must not die
                    logger.exception("device-direct KV plane unavailable; "
                                     "bulk/RPC planes serve")
                    direct_address = ""
        await kv_ep.serve(kv_handler, bulk_address=bulk_server.address,
                          direct_address=direct_address)
        if prefill_first:
            # prefill-first: THIS worker is the chat entrypoint; decode
            # workers are internal. The handler forwards with our bulk
            # (and device-direct) addresses so decode pulls ride the
            # fastest available plane.
            handler.bulk_address = bulk_server.address
            handler.direct_address = direct_address
            await register_llm(drt, endpoint, card)
        else:
            await register_llm(drt, endpoint, card, model_type="prefill")
            # pull-based prefill queue consumer (reference PrefillQueue
            # role): decode workers enqueue; the first free prefill worker
            # takes a job
            from dynamo_tpu.worker.disagg import PrefillQueueWorker
            queue_worker = await PrefillQueueWorker(
                tiered if tiered is not None else engine, drt, args.namespace,
                instance_id=lease.lease_id,
                bulk_address=bulk_server.address,
                direct_address=direct_address).start()
    elif args.disagg == "decode" and prefill_first:
        await register_llm(drt, endpoint, card, model_type="decode")
    else:
        await register_llm(drt, endpoint, card)
    from dynamo_tpu.runtime.system_server import SystemServer
    from dynamo_tpu.utils.tracing import get_tracer
    from dynamo_tpu.worker.metrics import get_worker_metrics
    # worker-side observability: admission/replay/disagg-KV counters plus
    # the per-stage latency histogram on this worker's /metrics, and the
    # flight recorder on /v1/traces (runtime/system_server.py)
    tracer = get_tracer()
    if not tracer.service:
        tracer.service = (f"worker-{args.disagg}" if args.disagg != "none"
                          else "worker")
    wm = get_worker_metrics()
    wm.attach_tracer(tracer)
    if tiered is not None:
        # dynamo_worker_kvbm_* tier/prefetch series sample the live tiers
        # at scrape time (zero-valued otherwise)
        wm.kvbm.attach(tiered.kvbm_stats)
    from dynamo_tpu.worker.metrics import engine_dispatch_stats
    import functools as _functools
    wm.engine.attach(_functools.partial(engine_dispatch_stats, engine))
    # step flight recorder: duration/occupancy/step-gap histograms +
    # compile counters on /metrics, raw timeline on /v1/steptrace
    wm.steptrace.attach(engine.steptrace.aggregates)
    system = SystemServer.from_env(registry=wm.registry, tracer=tracer,
                                   steptrace=engine.steptrace)
    if system is not None:
        system.health.register("engine", ready=True)
        # /healthz/ready turns 503 while the coordinator connection is
        # down (and later during drain, via register_drain below)
        system.attach_coord(drt.coord)
        await system.start()
    # graceful drain: SIGTERM (and POST /drain on the system server) stops
    # new work via the coordinator announcement, freezes in-flight streams
    # into resume tokens survivors pull the pinned KV for, waits (bounded
    # by DYN_DRAIN_TIMEOUT_S) for the lease acks, then shuts down. kill -9
    # keeps the keepalive-detect + replay path — drain is strictly better.
    from dynamo_tpu.worker.drain import DrainController, install_signal_drain
    drain_lease = await drt.primary_lease()
    resume_extras = {"instance_id": drain_lease.lease_id}
    if bulk_server is not None:
        resume_extras["bulk_address"] = bulk_server.address
    drain = DrainController(
        engine, served=[se for se in (served_main,) if se is not None],
        resume_extras=resume_extras, on_drained=drt.runtime.shutdown)
    install_signal_drain(drain)
    if system is not None:
        system.register_drain(drain)
    print(f"jax worker serving model {card.name} "
          f"on {len(jax.devices())} device(s) (disagg={args.disagg})",
          flush=True)
    try:
        await drt.runtime.wait_shutdown()
    finally:
        if queue_worker is not None:
            await queue_worker.stop()
        if bulk_server is not None:
            bulk_server.stop()
        if system is not None:
            await system.stop()
        if handler is not None:
            await handler.stop()
        if event_pump is not None:
            event_pump.cancel()
        if prefix_pub is not None:
            await prefix_pub.close()
        if prefix_reader is not None:
            await prefix_reader.close()
        await engine.stop()
        await drt.close()


async def _follower_main(args: argparse.Namespace, drt) -> None:
    """Rank>0: a pure step executor — no endpoint, no registration."""
    from dynamo_tpu.parallel.multihost import (
        barrier_id, follow_steps, step_subject)
    from dynamo_tpu.runtime.barrier import worker_barrier

    engine = build_engine(args)
    subject = step_subject(args.namespace, args.component)
    ready = asyncio.Event()
    follow = asyncio.ensure_future(
        follow_steps(drt, subject, engine, ready_event=ready))
    # subscribed (no step can be missed) — or the subscribe itself failed,
    # which must surface instead of wedging the barrier wait
    ready_wait = asyncio.ensure_future(ready.wait())
    done, _ = await asyncio.wait([ready_wait, follow],
                                 return_when=asyncio.FIRST_COMPLETED)
    if follow in done:
        ready_wait.cancel()
        follow.result()  # raises the subscribe/loop error
        raise RuntimeError("follower step loop exited before ready")
    await worker_barrier(drt, barrier_id(args.namespace, args.component),
                         f"rank{args.node_rank}", timeout=120.0)
    print(f"multihost follower rank {args.node_rank} in lockstep "
          f"({len(jax.devices())} global devices)", flush=True)
    shutdown = asyncio.ensure_future(drt.runtime.wait_shutdown())
    try:
        done, _pending = await asyncio.wait(
            [follow, shutdown], return_when=asyncio.FIRST_COMPLETED)
        for t in done:
            t.result()
    finally:
        for t in (follow, shutdown):
            t.cancel()
        await drt.close()


def main() -> None:
    import os
    import sys

    argv = list(sys.argv[1:])
    # planner-chosen parallelism config (the k8s reconciler patches this
    # env on the Deployment instead of doing arg-list surgery, see
    # deploy/reconciler.py); appended last so it overrides static flags
    extra = os.environ.get("DYN_PARALLEL_ARGS", "").split()
    if extra:
        argv += extra
    args = build_parser().parse_args(argv)
    configure_logging()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
