"""The native TPU worker process (``python -m dynamo_tpu.worker.main``).

Role parity: the reference's backend worker processes
(``components/backends/vllm/src/dynamo/vllm/main.py`` etc.) — but where those
wrap external CUDA engines, this worker owns the model loop natively via
``dynamo_tpu.engine.jax_engine.JaxEngine``.
"""
