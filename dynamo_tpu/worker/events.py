"""Ordered KV-event publication for workers.

The allocator emits ``KvCacheEvent``s with strictly increasing ``event_id``;
the router's indexer relies on that order (its gap detector treats a reorder
as loss and resyncs the whole worker). A task-per-batch publisher interleaves
at publish awaits, so all workers publish through ONE long-lived consumer
task fed by a queue — wire order matches allocator emission order.

Parity in role: the reference's per-worker NATS ``kv_events`` publisher
(``lib/llm/src/kv_router/publisher.rs:57-99``), which is likewise a single
sender per worker.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, List, Tuple

# canonical subject definition lives with the subscriber
from dynamo_tpu.kv_router.router import kv_events_subject  # noqa: F401
from dynamo_tpu.protocols.events import KvCacheEvent, RouterEvent

logger = logging.getLogger(__name__)


def ordered_kv_publisher(
    drt, subject: str, worker_id: int,
) -> Tuple[Callable[[List[KvCacheEvent]], None], asyncio.Task]:
    """Returns (callback, pump_task). Install the callback as
    ``engine.kv_event_cb``; cancel the task on shutdown."""
    q: asyncio.Queue = asyncio.Queue()

    async def _pump() -> None:
        while True:
            ev = await q.get()
            rev = RouterEvent(worker_id=worker_id, event=ev)
            try:
                await drt.publish_event(subject, rev.to_dict())
            except Exception:  # noqa: BLE001 — one lost event must not kill
                # the pump; the indexer's gap detector resyncs the worker
                logger.exception("kv event publish failed (event %s dropped)",
                                 ev.event_id)

    task = asyncio.create_task(_pump())

    def publish(events: List[KvCacheEvent]) -> None:
        for ev in events:
            q.put_nowait(ev)

    return publish, task


__all__ = ["ordered_kv_publisher", "kv_events_subject"]
