"""Disaggregated prefill/decode: the decode-side orchestration.

Decode-first flow, capability parity with the reference's vLLM disagg path
(SURVEY §3.4; ``components/backends/vllm/src/dynamo/vllm/handlers.py:107-183``):
the decode worker receives the request, round-robins it to a prefill worker
with ``prefill_only`` set, receives the first token plus
``kv_transfer_params`` (the prefix's block hashes), pulls those KV blocks
over the runtime RPC plane (``engine/transfer.py`` — the NIXL replacement),
injects them into the local cache, and decodes from the prefix hit.

Short prompts skip the remote hop: ``max_local_prefill_length`` is
hot-reloaded from the coordinator KV (parity: ``DisaggRouterConf`` etcd watch,
``lib/llm/src/disagg_router.rs:25-120``). If no prefill worker is live, or the
remote leg fails, the decode worker silently falls back to local prefill —
disagg is an optimization, never a point of failure.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.engine.jax_engine import JaxEngine
from dynamo_tpu.engine.transfer import (
    BlockPayload,
    inject_blocks,
    inject_frame,
)
from dynamo_tpu.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.utils.aio import reap_task

logger = logging.getLogger(__name__)

KV_EXPORT_ENDPOINT = "kv_export"


def disagg_conf_key(namespace: str) -> str:
    return f"disagg/{namespace}/conf"


class DisaggConfig:
    """Hot-reloadable disagg policy."""

    def __init__(self, max_local_prefill_length: int = 0):
        # prompts up to this length prefill locally; 0 = always remote
        self.max_local_prefill_length = max_local_prefill_length

    @classmethod
    def from_json(cls, raw: bytes) -> "DisaggConfig":
        d = json.loads(raw)
        return cls(max_local_prefill_length=int(
            d.get("max_local_prefill_length", 0)))


class DisaggDecodeHandler:
    """Wraps a decode engine with the remote-prefill leg."""

    def __init__(self, engine: JaxEngine, drt: DistributedRuntime,
                 namespace: str, prefill_component: str,
                 conf: Optional[DisaggConfig] = None):
        self.engine = engine
        self.drt = drt
        self.namespace = namespace
        self.prefill_component = prefill_component
        self.conf = conf or DisaggConfig()
        self._gen_client = None
        self._kv_client = None
        self._router: Optional[PushRouter] = None
        self._conf_watch = None
        self._conf_task: Optional[asyncio.Task] = None

    async def start(self) -> "DisaggDecodeHandler":
        ns = self.drt.namespace(self.namespace)
        comp = ns.component(self.prefill_component)
        self._gen_client = await comp.endpoint("generate").client()
        self._kv_client = await comp.endpoint(KV_EXPORT_ENDPOINT).client()
        self._router = PushRouter(self._gen_client, RouterMode.ROUND_ROBIN)
        self._conf_watch = await self.drt.coord.watch_prefix(
            disagg_conf_key(self.namespace))
        for _key, value in self._conf_watch.snapshot:
            self._apply_conf(value)
        self._conf_task = asyncio.create_task(self._conf_loop())
        return self

    async def stop(self) -> None:
        await reap_task(self._conf_task)
        if self._conf_watch is not None:
            try:
                await self._conf_watch.cancel()
            except Exception:
                pass
        for c in (self._gen_client, self._kv_client):
            if c is not None:
                await c.close()

    def _apply_conf(self, raw: bytes) -> None:
        try:
            self.conf = DisaggConfig.from_json(raw)
            logger.info("disagg conf updated: max_local_prefill_length=%d",
                        self.conf.max_local_prefill_length)
        except Exception:
            logger.exception("bad disagg conf %r", raw)

    async def _conf_loop(self) -> None:
        async for ev in self._conf_watch:
            if ev.type == "put" and ev.value is not None:
                self._apply_conf(ev.value)

    # -- the disagg leg ----------------------------------------------------

    def _use_remote_prefill(self, request: PreprocessedRequest) -> bool:
        if not self._gen_client.instance_ids():
            return False
        n = len(request.token_ids)
        return n > self.conf.max_local_prefill_length

    async def _remote_prefill(self, request: PreprocessedRequest
                              ) -> Optional[LLMEngineOutput]:
        """Run the prefill leg; returns the final prefill frame (first token +
        kv_transfer_params) or None on any failure (-> local fallback)."""
        preq = PreprocessedRequest.from_dict(request.to_dict())
        preq.prefill_only = True
        try:
            iid = self._router.select_instance()
            final: Optional[LLMEngineOutput] = None
            stream = await self._gen_client.direct(preq.to_dict(), iid)
            async for payload in stream:
                out = LLMEngineOutput.from_dict(payload)
                if out.finish_reason is not None:
                    final = out
            if final is None or final.error:
                return None
            params = final.kv_transfer_params or {}
            hashes = [b[0] for b in params.get("blocks", [])]
            if hashes:
                kv_stream = await self._kv_client.direct(
                    {"block_hashes": hashes, "wire": 2}, iid)
                # batched two-part frames: inject frame k while frame k+1
                # is still in flight (pipelined, zero msgpack re-copies)
                injected = total = 0
                legacy: list = []
                async for frame in kv_stream:
                    if "_raw" in frame:
                        total += len(frame["blocks"])
                        injected += await self.engine.run_exclusive(
                            inject_frame, self.engine, frame)
                    else:  # pre-batched single-block schema
                        legacy.append(BlockPayload.from_wire(frame))
                if legacy:
                    total += len(legacy)
                    injected += await self.engine.run_exclusive(
                        inject_blocks, self.engine, legacy)
                if total:
                    logger.debug("injected %d/%d transferred blocks",
                                 injected, total)
            return final
        except Exception as e:  # noqa: BLE001 — disagg must never fail a
            # request: any remote-leg error (connection, malformed frame,
            # inject failure) falls back to local prefill
            logger.warning("remote prefill failed (%s); falling back local", e,
                           exc_info=not isinstance(e, ConnectionError))
            return None

    async def generate(self, request: PreprocessedRequest,
                       ctx=None) -> AsyncIterator[LLMEngineOutput]:
        first: Optional[LLMEngineOutput] = None
        if self._use_remote_prefill(request):
            first = await self._remote_prefill(request)
        if first is not None and first.token_ids:
            tok = first.token_ids[0]
            yield LLMEngineOutput(token_ids=[tok],
                                  log_probs=first.log_probs)
            sc = request.stop_conditions
            if (not sc.ignore_eos and tok in request.eos_token_ids) or \
               (sc.stop_token_ids and tok in sc.stop_token_ids):
                yield LLMEngineOutput(
                    finish_reason=first.finish_reason,
                    prompt_tokens=len(request.token_ids),
                    completion_tokens=1)
                return
            if sc.max_tokens is not None and sc.max_tokens <= 1:
                yield LLMEngineOutput(
                    finish_reason=first.finish_reason,
                    prompt_tokens=len(request.token_ids),
                    completion_tokens=1)
                return
            request = PreprocessedRequest.from_dict(request.to_dict())
            request.token_ids = list(request.token_ids) + [tok]
            if request.stop_conditions.max_tokens is not None:
                request.stop_conditions.max_tokens -= 1
        async for out in self.engine.generate(request, ctx):
            if (first is not None and out.finish_reason is not None
                    and out.completion_tokens is not None):
                # the handed-off first token counts as completion, not prompt
                out.prompt_tokens = (out.prompt_tokens or 1) - 1
                out.completion_tokens = out.completion_tokens + 1
            yield out


__all__ = ["DisaggDecodeHandler", "DisaggConfig", "disagg_conf_key",
           "KV_EXPORT_ENDPOINT"]
