"""Disaggregated prefill/decode: the decode-side orchestration.

Decode-first flow, capability parity with the reference's vLLM disagg path
(SURVEY §3.4; ``components/backends/vllm/src/dynamo/vllm/handlers.py:107-183``):
the decode worker receives the request, round-robins it to a prefill worker
with ``prefill_only`` set, receives the first token plus
``kv_transfer_params`` (the prefix's block hashes), pulls those KV blocks
over the runtime RPC plane (``engine/transfer.py`` — the NIXL replacement),
injects them into the local cache, and decodes from the prefix hit.

Short prompts skip the remote hop: ``max_local_prefill_length`` is
hot-reloaded from the coordinator KV (parity: ``DisaggRouterConf`` etcd watch,
``lib/llm/src/disagg_router.rs:25-120``). If no prefill worker is live, or the
remote leg fails, the decode worker silently falls back to local prefill —
disagg is an optimization, never a point of failure.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
from typing import Any, AsyncIterator, Dict, Optional

from dynamo_tpu.engine.jax_engine import JaxEngine
from dynamo_tpu.engine.transfer import (
    FRAME_WIRE_VERSION,
    KV_EXPORT_DIRECT_ENDPOINT,
    BlockPayload,
    FrameIntegrityError,
    InjectPipeline,
    inject_device_windowed,
    kv_shard_payload,
    pump_bulk_frames,
    stamp_export_lease,
)
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
from dynamo_tpu.runtime.rpc import DeadlineExceededError, request_headers
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.utils.aio import reap_task
from dynamo_tpu.utils.tracing import (
    SPANS_FRAME_KEY,
    StageStitcher,
    get_tracer,
)

logger = logging.getLogger(__name__)

KV_EXPORT_ENDPOINT = "kv_export"


class KvBandwidthBook:
    """Per-plane KV-transfer bandwidth EWMAs (bulk / rpc / direct).

    Each completed pull leg contributes one (bytes, wall-seconds) sample
    for the plane that served it; the EWMA smooths transient dips while
    tracking a degrading link within a few pulls. Surfaced on the worker
    ``__stats__`` plane (``worker/main.worker_stats`` merges
    ``snapshot()`` as ``kv_transfer``) so the frontend cost router and
    fleet tooling see per-plane transfer health alongside queue depth —
    no Prometheus scrape in the routing path."""

    _ALPHA = 0.3  # weight of the newest sample

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ewma: Dict[str, float] = {}
        self._bytes: Dict[str, int] = {}
        self._samples: Dict[str, int] = {}

    def note(self, plane: str, nbytes: int, seconds: float) -> None:
        if nbytes <= 0 or seconds <= 0:
            return  # empty or unmeasured leg: no bandwidth information
        bw = nbytes / seconds
        with self._lock:
            prev = self._ewma.get(plane)
            self._ewma[plane] = bw if prev is None else (
                self._ALPHA * bw + (1.0 - self._ALPHA) * prev)
            self._bytes[plane] = self._bytes.get(plane, 0) + int(nbytes)
            self._samples[plane] = self._samples.get(plane, 0) + 1

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {p: {"bw_bytes_per_s": round(self._ewma[p], 1),
                        "bytes_total": self._bytes[p],
                        "samples": self._samples[p]}
                    for p in sorted(self._ewma)}


_kv_bw_book: Optional[KvBandwidthBook] = None


def get_kv_bandwidth_book() -> KvBandwidthBook:
    """Process-wide bandwidth book (pull paths write, __stats__ reads)."""
    global _kv_bw_book
    if _kv_bw_book is None:
        _kv_bw_book = KvBandwidthBook()
    return _kv_bw_book


def make_device_transfer_plane(engine: JaxEngine):
    """A ``DeviceTransferPlane`` for this engine, or None when the
    device-direct path does not apply: the jax transfer API is missing,
    or the engine's cache is sharded over a mesh (a cross-process pull
    onto a NamedSharding needs a shared global mesh). Mesh-sharded
    deployments are NOT stuck on a host gather though: their bulk/RPC
    pulls negotiate the wire-v5 per-shard frame schema
    (``transfer.kv_shard_payload``), so each prefill shard's slice
    streams straight to its decode shard's device."""
    from jax.sharding import SingleDeviceSharding

    try:
        from jax.experimental import transfer  # noqa: F401
    except ImportError:
        return None
    ref = engine.pages[0] if isinstance(engine.pages, list) else engine.pages
    if not isinstance(ref.sharding, SingleDeviceSharding) \
            and len(ref.sharding.device_set) > 1:
        logger.info("device-direct KV plane disabled for the mesh-sharded "
                    "cache; shard-to-shard pulls ride the wire-v5 "
                    "per-shard frames on the bulk/RPC planes")
        return None
    from dynamo_tpu.engine.transfer import DeviceTransferPlane
    return DeviceTransferPlane()


def disagg_conf_key(namespace: str) -> str:
    return f"disagg/{namespace}/conf"


def prefill_queue_name(namespace: str) -> str:
    """Coordinator work-queue carrying prefill jobs (the JetStream prefill
    queue role — reference ``rust/llm/nats.rs:109`` ``NatsQueue``, flow in
    ``docs/architecture/dynamo_flow.md`` S7-S10)."""
    return f"prefill/{namespace}"


def prefill_reply_subject(namespace: str, rid: str) -> str:
    return f"{namespace}.prefill_reply.{rid}"


class PrefillQueueWorker:
    """Prefill-side queue consumer: pulls jobs, prefills, publishes the
    result (first token + kv_transfer_params + where to fetch the blocks).

    Queue semantics give disagg what round-robin cannot: jobs wait for the
    FIRST FREE prefill worker (not a blindly-chosen one), depth is a real
    backlog signal for the planner, and adding a worker immediately drains
    the queue."""

    def __init__(self, engine: JaxEngine, drt: DistributedRuntime,
                 namespace: str, instance_id: int, bulk_address: str = "",
                 concurrency: int = 2, direct_address: str = ""):
        self.engine = engine
        self.drt = drt
        self.namespace = namespace
        self.instance_id = instance_id
        self.bulk_address = bulk_address
        self.direct_address = direct_address
        self.concurrency = concurrency
        self._tasks: list = []
        self.jobs_done = 0

    async def start(self) -> "PrefillQueueWorker":
        for i in range(self.concurrency):
            self._tasks.append(asyncio.create_task(
                self._pull_loop(), name=f"prefill-queue-{i}"))
        return self

    async def stop(self) -> None:
        for t in self._tasks:
            await reap_task(t)

    async def _pull_loop(self) -> None:
        from dynamo_tpu.runtime import codec
        queue = prefill_queue_name(self.namespace)
        while True:
            try:
                pulled = await self.drt.coord.queue_pull(queue)
            except ConnectionError:
                # coordinator outage: park until the supervised client
                # reconnects (a queued pull doesn't survive the server's
                # session, so just re-issue it), or exit on permanent close
                try:
                    await self.drt.coord.wait_connected()
                except ConnectionError:
                    return  # gone for good; runtime shutdown handles the rest
                # the write side can fail before the read loop marks the
                # connection down, making wait_connected return immediately;
                # yield briefly so the retry can't hot-spin
                await asyncio.sleep(0.05)
                continue
            if pulled is None:
                continue
            raw, age_s = pulled
            job = None
            try:
                job = codec.unpack(raw)
                outcome = await self._run_job(job, age_s)
                self.jobs_done += 1
            except Exception:  # noqa: BLE001 — one bad job must not kill
                outcome = "failed"
                logger.exception("prefill queue job failed")
                if job is None and isinstance(raw, (bytes, bytearray)):
                    logger.warning("undecodable prefill job dropped")
            from dynamo_tpu.worker.metrics import count_metric
            count_metric("prefill_jobs", outcome)

    async def _run_job(self, job: dict, age_s: float = 0.0) -> str:
        """Run one queued prefill job; returns its outcome label
        (``ok``/``failed``/``stale`` — ``dynamo_worker_prefill_jobs_total``)."""
        from dynamo_tpu.runtime import codec
        tracer = get_tracer()
        # the decode side packed its trace context into the job (the queue
        # rides the coordinator, not RPC headers): this worker's fragment
        # stitches under the decode worker's prefill span
        hop = tracer.start_hop("prefill_worker.job",
                               headers=job.get("trace"),
                               attrs={"request_id":
                                      job.get("req", {}).get("request_id",
                                                             ""),
                                      "queued_s": round(age_s, 6)})
        # staleness by TIME QUEUED (measured on the coordinator's single
        # clock — immune to cross-host wall-clock skew): past the decode
        # side's reply timeout, nobody is waiting for this job
        if age_s > job.get("ttl", float("inf")):
            logger.info("dropping stale prefill job %s (queued %.1fs)",
                        job.get("req", {}).get("request_id"), age_s)
            hop.set_attr("outcome", "stale")
            hop.add_event("stale_drop", queued_s=round(age_s, 3),
                          ttl=job.get("ttl"))
            tracer.finish_hop(hop)  # fragment stays in this recorder
            return "stale"
        stitcher = StageStitcher(tracer, parent=hop, skip_decode=True)
        # pre-set so the finally's publish can never NameError, even on a
        # BaseException (cancellation) out of the engine stream
        reply = {"out": None, "instance_id": self.instance_id}
        outcome = "failed"
        try:
            req = PreprocessedRequest.from_dict(job["req"])
            req.prefill_only = True
            final: Optional[LLMEngineOutput] = None
            async for out in self.engine.generate(req):
                stitcher.on_frame(out)
                if out.finish_reason is not None:
                    final = out
            if final is not None and final.error:
                hop.set_error(final.error)
            elif final is not None and final.kv_transfer_params:
                # pin the advertised blocks until the decode side acks the
                # pull (or the TTL GC reclaims them — crashed decoder)
                await stamp_export_lease(self.engine,
                                         final.kv_transfer_params,
                                         span=hop)
            if final is not None and not final.error:
                outcome = "ok"
            reply = {
                "out": final.to_dict() if final is not None else None,
                "instance_id": self.instance_id,
                "bulk_address": self.bulk_address,
                "direct_address": self.direct_address,
            }
        except Exception as e:  # noqa: BLE001 — reply even on failure, so
            # the decode side falls back immediately instead of waiting out
            # its queue timeout
            hop.set_error(repr(e))
            reply = {"out": None, "instance_id": self.instance_id}
            raise
        finally:
            stitcher.close()
            hop.set_attr("outcome", outcome)
            reply[SPANS_FRAME_KEY] = tracer.finish_hop(hop)
            await self.drt.coord.publish(job["reply"], codec.pack(reply))
        return outcome


class DisaggConfig:
    """Hot-reloadable disagg policy."""

    def __init__(self, max_local_prefill_length: int = 0):
        # prompts up to this length prefill locally; 0 = always remote
        self.max_local_prefill_length = max_local_prefill_length

    @classmethod
    def from_json(cls, raw: bytes) -> "DisaggConfig":
        d = json.loads(raw)
        return cls(max_local_prefill_length=int(
            d.get("max_local_prefill_length", 0)))


class KvBlockPuller:
    """Transport-ladder KV block pull: device-direct -> bulk -> RPC, with
    per-block resumability, wire-v4 checksum NACKs, per-plane byte/trace
    accounting, and export-lease acks.

    Extracted from ``DisaggDecodeHandler`` so the graceful-drain resume
    path (``worker/drain.ResumeAdmission``) pulls a draining worker's
    pinned sequence KV through the exact machinery the disagg prefill
    handoff uses — one pull implementation, two callers. The clients are
    attached by the owner (they need a started runtime); a missing
    direct client/plane simply skips that rung of the ladder."""

    def __init__(self, engine: JaxEngine, kv_client=None,
                 kv_direct_client=None, direct_plane=None):
        self.engine = engine
        self.kv_client = kv_client
        self.kv_direct_client = kv_direct_client
        # device-direct pull plane (engine/transfer.DeviceTransferPlane):
        # built by the owner when the jax transfer API is available and
        # the engine is single-device (mesh engines keep the host planes)
        self.direct_plane = direct_plane
        # bound on one device-direct pull; past it the (abandoned) pull
        # thread is left behind and the transport ladder falls to bulk
        self.direct_pull_timeout = 60.0
        # circuit breaker: a timed-out address is skipped for this long
        # (each timeout strands a 60s executor thread — without the
        # breaker a black-holed peer would saturate the default executor
        # and wedge even the bulk fallback's to_thread calls)
        self.direct_down_window = 300.0
        self.direct_down_until: dict = {}
        # bulk addresses already pre-warmed (one background warmup per
        # peer: later fetches find pooled connections with ramped kernel
        # buffers instead of paying the cold-socket penalty)
        self.bulk_warmed: set = set()
        # resume attempts per host plane after a mid-pull failure: each
        # re-pulls only the blocks not yet committed (DYN_KV_PULL_RETRIES)
        try:
            self.pull_resume_attempts = max(0, int(os.environ.get(
                "DYN_KV_PULL_RETRIES", "1")))
        except (TypeError, ValueError):
            logger.warning("malformed DYN_KV_PULL_RETRIES %r; using 1",
                           os.environ.get("DYN_KV_PULL_RETRIES"))
            self.pull_resume_attempts = 1
        # diagnostics of the most recent block pull (tests, debugging)
        self.last_pull_stats: dict = {}

    async def pull_blocks(self, hashes: list, iid: int,
                           bulk_address: str = "",
                           direct_address: str = "",
                           lease: Optional[int] = None) -> None:
        """Fetch + inject the prefix blocks from prefill worker ``iid``.

        Transport ladder: DEVICE-DIRECT (jax transfer server — blocks move
        chip-to-chip with no host bounce, the NIXL RDMA role) when both
        sides run it, else the bulk data plane (raw sockets, unix-first),
        else batched two-part frames on the RPC plane.

        Fault tolerance: per-block commit state is the allocator's
        content-addressed registry itself, so a mid-pull failure (socket
        reset, corrupt frame, peer death) resumes by re-pulling ONLY the
        blocks not yet committed — first on the same plane, then down the
        ladder — instead of discarding committed work. Wire-v4 frames are
        checksum-verified before staging; a bad frame NACKs (aborts the
        stream) and is re-pulled, never injected. On the way out the
        export ``lease`` is acked (best-effort; the prefill side's TTL GC
        covers a lost ack)."""
        inst = self.kv_client.get_instance(iid)
        if not bulk_address and inst is not None:
            bulk_address = inst.bulk_address
        if not direct_address and inst is not None:
            direct_address = inst.direct_address
        tracer = get_tracer()
        kv_span = tracer.start_span(
            "kv_transfer", attrs={"blocks": len(hashes),
                                  "instance": f"{iid:x}"})

        def _count_bytes(n: int, plane: str) -> None:
            # per-plane attrs: a ladder fall-through (direct pull ok, inject
            # failed, bulk finished the job) must not attribute one plane's
            # bytes to another; "plane" records the plane that served the
            # tail of the transfer
            kv_span.set_attr("plane", plane)
            kv_span.set_attr(
                f"bytes_{plane}",
                int(kv_span.attrs.get(f"bytes_{plane}", 0)) + int(n))
            kv_span.set_attr(
                "bytes", int(kv_span.attrs.get("bytes", 0)) + int(n))
            try:
                from dynamo_tpu.worker.metrics import get_worker_metrics
                get_worker_metrics().disagg_kv_bytes.labels(
                    "pulled", plane).inc(int(n))
            except Exception:  # noqa: BLE001 — accounting must not fail IO
                logger.exception("kv byte accounting failed")

        # per-phase wall time (recv = socket/pull wait, stage = host copy
        # into the scatter buffer, upload = host->device transfer, scatter
        # = exclusive-window commits): the bulk-vs-e2e gap lives in these
        phases = {"recv_s": 0.0, "stage_s": 0.0, "upload_s": 0.0,
                  "scatter_s": 0.0}
        try:
            await self._pull_blocks_inner(hashes, iid, bulk_address,
                                          direct_address, _count_bytes,
                                          kv_span, phases)
        except BaseException as e:
            kv_span.set_error(repr(e))
            raise
        finally:
            for k, v in phases.items():
                if v:
                    kv_span.set_attr(k[:-2] + "_ms", round(v * 1e3, 3))
            try:
                if lease is not None:
                    # ack whatever the outcome: this decode worker never
                    # comes back for more of THIS pull (a failed tail
                    # recomputes locally), so the prefill side can unpin
                    # now instead of waiting out the TTL
                    acked = await self._ack_export_lease(iid, lease)
                    kv_span.set_attr("lease_acked", acked)
            finally:
                # a cancellation landing on the ack await must not leave
                # the span unfinished
                kv_span.finish()

    async def _ack_export_lease(self, iid: int, lease: int) -> bool:
        try:
            stream = await self.kv_client.direct(
                {"ack_lease": int(lease)}, iid)
            async for _ in stream:
                pass
            return True
        except Exception as e:  # noqa: BLE001 — the TTL GC covers it
            logger.debug("export lease %s ack to %x failed (%s); TTL "
                         "covers", lease, iid, e)
            return False

    def missing(self, hashes: list) -> list:
        """The per-block commit state IS the allocator's content-addressed
        registry: a block that committed (this pull, an earlier attempt,
        or any other request) is resident and never re-pulled."""
        resident = self.engine.allocator._by_hash
        return [h for h in hashes if h not in resident]

    def _note_resume(self, kv_span, plane: str, committed: int,
                     remaining: int) -> None:
        kv_span.add_event("pull_resumed", plane=plane, committed=committed,
                          remaining=remaining)
        from dynamo_tpu.worker.metrics import count_metric
        count_metric("kv_pull_resumes")

    @staticmethod
    def _note_corrupt(kv_span, plane: str, err) -> None:
        kv_span.add_event("frame_corrupt", plane=plane, error=str(err))
        from dynamo_tpu.worker.metrics import count_metric
        count_metric("kv_frames_corrupt")

    @staticmethod
    def _note_shard_bytes(kv_span, meta, nbytes: int) -> None:
        """Per-shard byte attrs on the kv_transfer span (wire-v5 frames
        carry their shard index): ``bytes_shard{i}`` sums each shard's
        wire bytes next to the per-plane totals, so an imbalanced or
        stalled shard stream is attributable without a rerun."""
        sh = (meta or {}).get("shard")
        if sh is None:
            return
        try:
            kv_span.set_attr("shards", int(sh["count"]))
            key = f"bytes_shard{int(sh['index'])}"
            kv_span.set_attr(
                key, int(kv_span.attrs.get(key, 0)) + int(nbytes))
        except Exception:  # noqa: BLE001 — accounting must not fail IO
            logger.debug("shard byte accounting failed", exc_info=True)

    async def _pull_blocks_inner(self, hashes: list, iid: int,
                                 bulk_address: str, direct_address: str,
                                 _count_bytes, kv_span, phases) -> None:
        injected = total = 0
        retries = 0
        resumed_blocks = 0  # blocks NOT re-pulled thanks to commit state
        bulk_done = False
        want = self.missing(hashes)
        if len(want) < len(hashes):
            kv_span.set_attr("resident_blocks", len(hashes) - len(want))
        self.last_pull_stats = {"retries": 0, "resumed_blocks": 0,
                                "injected": 0, "corrupt": 0}

        def finish_stats():
            kv_span.set_attr("injected", injected)
            if retries:
                kv_span.set_attr("retries", retries)
                kv_span.set_attr("resumed_blocks", resumed_blocks)
            self.last_pull_stats.update(retries=retries,
                                        resumed_blocks=resumed_blocks,
                                        injected=injected)

        if not want:
            finish_stats()
            return
        now = time.monotonic()
        # prune expired breaker entries: prefill restarts advertise fresh
        # ephemeral ports, so per-address state must not grow unbounded
        self.direct_down_until = {a: t for a, t in
                                   self.direct_down_until.items()
                                   if t > now}
        if (direct_address and self.direct_plane is not None
                and direct_address not in self.direct_down_until):
            offer = None
            try:
                offer_stream = await self.kv_direct_client.direct(
                    {"block_hashes": want}, iid)
                async for o in offer_stream:
                    offer = o
                if offer and offer.get("uuid") is not None:
                    # the network pull runs OUTSIDE the engine's exclusive
                    # window (it touches no engine state) with a timeout —
                    # a stalled transfer connection must never wedge the
                    # decode loop; only the fast device scatter is
                    # exclusive. A timed-out pull abandons its thread,
                    # evicts the connection, opens the circuit breaker for
                    # the address, and falls down the ladder.
                    t0 = time.perf_counter()
                    data = await asyncio.wait_for(
                        asyncio.to_thread(self.direct_plane.pull, offer),
                        timeout=self.direct_pull_timeout)
                    _dt = time.perf_counter() - t0
                    phases["recv_s"] += _dt
                    _count_bytes(getattr(data, "nbytes", 0), "direct")
                    get_kv_bandwidth_book().note(
                        "direct", getattr(data, "nbytes", 0), _dt)
                    # commit in bounded windows, one minimal exclusive
                    # scatter each: decode steps interleave with a large
                    # direct-plane inject instead of stalling behind it
                    metas = [(b[0], b[1], b[2])
                             for b in offer["blocks"]]
                    t0 = time.perf_counter()
                    injected = await inject_device_windowed(
                        self.engine, metas, data[:, :len(metas)])
                    phases["scatter_s"] += time.perf_counter() - t0
                    logger.debug("device-direct pull injected %d blocks "
                                 "from %x", injected, iid)
                    await self._ack_offer(iid, offer["uuid"])
                    finish_stats()
                    return
                # empty offer: blocks evicted remotely OR the peer's offer
                # table is full — fall through to the host planes (the
                # bulk fetch serves the full-table case; the evicted case
                # costs one empty round trip)
            except asyncio.TimeoutError:
                self.direct_plane.evict(offer["address"] if offer
                                         else direct_address)
                self.direct_down_until[direct_address] = (
                    time.monotonic() + self.direct_down_window)
                logger.warning(
                    "device-direct KV pull from %s timed out after %.0fs; "
                    "skipping the plane for %.0fs", direct_address,
                    self.direct_pull_timeout, self.direct_down_window)
            except Exception as e:  # noqa: BLE001 — fall down the ladder
                logger.warning("device-direct KV pull from %s failed (%s); "
                               "trying the bulk plane", direct_address, e)
        # resume budget per host plane: a failed attempt re-pulls only the
        # still-missing blocks before falling down the ladder
        attempts_per_plane = 1 + self.pull_resume_attempts
        if bulk_address:
            from dynamo_tpu.runtime.bulk import prewarm_async
            if bulk_address not in self.bulk_warmed:
                # background warmup: THIS fetch still rides a cold socket,
                # but every later fetch to the peer finds a pooled, ramped
                # connection (and concurrent pulls find extra capacity).
                # A warmup that fails outright un-marks the address so a
                # later pull retries (peer briefly unreachable).
                self.bulk_warmed.add(bulk_address)
                prewarm_async(
                    bulk_address, f"{iid:x}",
                    on_fail=lambda a=bulk_address:
                        self.bulk_warmed.discard(a))
            for attempt in range(attempts_per_plane):
                want = self.missing(hashes)
                if not want:
                    bulk_done = True
                    break
                if attempt:
                    retries += 1
                    resumed_blocks = len(hashes) - len(want)
                    self._note_resume(kv_span, "bulk", resumed_blocks,
                                      len(want))
                pipe = InjectPipeline(self.engine)
                seen_windows: set = set()
                bulk_bytes = [0]  # wire bytes this attempt, for the EWMA

                def on_meta(meta, nbytes):
                    nonlocal total
                    _count_bytes(nbytes, "bulk")
                    bulk_bytes[0] += int(nbytes)
                    self._note_shard_bytes(kv_span, meta, nbytes)
                    if meta.get("shard") is not None:
                        # count each block window once, not per shard slice
                        key = tuple(b[0] for b in meta["blocks"])
                        if key in seen_windows:
                            return
                        seen_windows.add(key)
                    total += len(meta["blocks"])

                try:
                    # stream-and-stage (engine/transfer.pump_bulk_frames):
                    # frames stage/commit while later frames are still on
                    # the wire, wire buffers recycle through the pipeline.
                    # A sharded cache advertises its shard layout so a
                    # same-layout exporter streams per-shard frames
                    # (wire v5) instead of host-gathered merged frames.
                    _recv = await pump_bulk_frames(
                        pipe, bulk_address, KV_EXPORT_ENDPOINT,
                        {"block_hashes": want,
                         "wire": FRAME_WIRE_VERSION,
                         **kv_shard_payload(self.engine)},
                        f"{iid:x}", 60.0, on_meta)
                    phases["recv_s"] += _recv
                    get_kv_bandwidth_book().note(
                        "bulk", bulk_bytes[0], _recv)
                    injected += await pipe.finish()
                    bulk_done = True
                    break
                except FrameIntegrityError as e:
                    # checksum NACK: the corrupted frame was rejected
                    # before staging (never injected) and the stream
                    # aborted; committed frames stay, the resume re-pulls
                    # the rest
                    injected += pipe.injected
                    self.last_pull_stats["corrupt"] += 1
                    self._note_corrupt(kv_span, "bulk", e)
                    logger.warning("bulk KV frame from %s failed checksum "
                                   "(%s); re-pulling missing blocks",
                                   bulk_address, e)
                except Exception as e:  # noqa: BLE001 — bulk plane broke
                    # mid-pull (socket reset, worker bound to 127.0.0.1
                    # across hosts, peer death): resume on this plane,
                    # then the RPC export path below — never waste the
                    # completed remote prefill over a transport problem.
                    # pump already reaped its fetch thread and in-flight
                    # commits; whatever committed cleanly stays (content-
                    # addressed blocks are never wasted, every retry
                    # dedups against them).
                    injected += pipe.injected
                    logger.warning("bulk KV fetch from %s failed (%s); %s",
                                   bulk_address, e,
                                   "resuming missing blocks"
                                   if attempt + 1 < attempts_per_plane
                                   else "falling back to the RPC export "
                                        "path")
                finally:
                    for k, v in pipe.timings.items():
                        phases[k] += v
        if not bulk_done:
            last_err = None
            for attempt in range(attempts_per_plane):
                want = self.missing(hashes)
                if not want:
                    last_err = None
                    break
                if attempt or (bulk_address and injected):
                    # count a ladder/same-plane resume whenever committed
                    # work is being carried over into a new attempt
                    retries += 1
                    resumed_blocks = len(hashes) - len(want)
                    self._note_resume(kv_span, "rpc", resumed_blocks,
                                      len(want))
                def note_blocks(n: int) -> None:
                    nonlocal total
                    total += n

                def note_injected(n: int) -> None:
                    nonlocal injected
                    injected += n

                try:
                    await self._pull_rpc(want, iid, _count_bytes, phases,
                                         note_blocks, note_injected,
                                         kv_span)
                    last_err = None
                    break
                except FrameIntegrityError as e:
                    last_err = e
                    self.last_pull_stats["corrupt"] += 1
                    self._note_corrupt(kv_span, "rpc", e)
                    logger.warning("RPC KV frame from %x failed checksum "
                                   "(%s); re-pulling missing blocks",
                                   iid, e)
                except Exception as e:  # noqa: BLE001 — retried below
                    last_err = e
                    logger.warning("RPC KV fetch from %x failed (%s)",
                                   iid, e)
            if last_err is not None:
                finish_stats()
                raise last_err
        if total:
            logger.debug("injected %d/%d transferred blocks",
                         injected, total)
        finish_stats()

    async def _pull_rpc(self, want: list, iid: int, _count_bytes,
                        phases, note_blocks, note_injected,
                        kv_span=None) -> None:
        """One RPC-plane pull attempt of ``want`` through the staged
        pipeline. Blocks injected are reported through ``note_injected``
        — on the failure path too, so partial commits reaped by the drain
        still count (the caller's resume dedups against them)."""
        from dynamo_tpu.runtime.codec import release_buffer

        kv_stream = await self.kv_client.direct(
            {"block_hashes": want, "wire": FRAME_WIRE_VERSION,
             **kv_shard_payload(self.engine)}, iid)
        # batched two-part frames through the staged pipeline: frame k
        # stages/commits while frame k+1 is still in flight (zero
        # msgpack re-copies). Old exporters answering with the
        # per-block schema ride the same pipeline via add_blocks.
        pipe = InjectPipeline(self.engine)
        seen_windows: set = set()
        rpc_bytes = 0
        rpc_recv = 0.0
        try:
            t0 = time.perf_counter()
            async for frame in kv_stream:
                _dt = time.perf_counter() - t0
                rpc_recv += _dt
                phases["recv_s"] += _dt
                if "_raw" in frame:
                    _count_bytes(len(frame["_raw"]), "rpc")
                    rpc_bytes += len(frame["_raw"])
                    if kv_span is not None:
                        self._note_shard_bytes(kv_span, frame,
                                               len(frame["_raw"]))
                    if frame.get("shard") is not None:
                        key = tuple(b[0] for b in frame["blocks"])
                        if key not in seen_windows:
                            seen_windows.add(key)
                            note_blocks(len(frame["blocks"]))
                        # fall through to staging either way
                    else:
                        note_blocks(len(frame["blocks"]))
                    # pipeline recycles the pooled trailer buffer
                    # once its bytes are consumed
                    await pipe.add_frame(frame, release=release_buffer)
                else:  # pre-batched single-block schema
                    note_blocks(1)
                    await pipe.add_blocks(
                        [BlockPayload.from_wire(frame)])
                t0 = time.perf_counter()
            note_injected(await pipe.finish())
            get_kv_bandwidth_book().note("rpc", rpc_bytes, rpc_recv)
        except BaseException:
            note_injected(await pipe.drain())
            raise
        finally:
            for k, v in pipe.timings.items():
                phases[k] += v

    async def _ack_offer(self, iid: int, uuid: int) -> None:
        """Release the peer's pinned device-direct offer. Retried once —
        a lost ack leaves the gathered array pinned in the peer's HBM
        until its offer TTL — and counted
        (``dynamo_worker_kv_offer_acks_total``)."""
        acked = False
        for attempt in range(2):
            try:
                ack = await self.kv_direct_client.direct(
                    {"ack": int(uuid)}, iid)
                async for _ in ack:
                    pass
                acked = True
                break
            except Exception as e:  # noqa: BLE001 — retry once, then TTL
                logger.debug("device-direct offer ack to %x failed "
                             "(attempt %d: %s)", iid, attempt + 1, e)
        if not acked:
            logger.warning("device-direct offer %s ack to %x failed "
                           "twice; peer unpins at its offer TTL",
                           uuid, iid)
        from dynamo_tpu.worker.metrics import count_metric
        count_metric("kv_offer_acks", "ok" if acked else "failed")


class DisaggDecodeHandler:
    """Wraps a decode engine with the remote-prefill leg."""

    def __init__(self, engine: JaxEngine, drt: DistributedRuntime,
                 namespace: str, prefill_component: str,
                 conf: Optional[DisaggConfig] = None,
                 use_queue: bool = True, queue_timeout: float = 30.0,
                 strategy: str = "decode_first"):
        self.engine = engine
        self.drt = drt
        self.namespace = namespace
        self.prefill_component = prefill_component
        self.conf = conf or DisaggConfig()
        # prefill-queue leg (reference PrefillQueue): jobs go to the first
        # FREE worker; disable to force the direct round-robin leg only
        self.use_queue = use_queue
        self.queue_timeout = queue_timeout
        # "prefill_first": this decode worker only ACCEPTS forwarded
        # requests (kv_transfer_params inbound) and never initiates the
        # remote-prefill leg itself
        self.strategy = strategy
        self._gen_client = None
        self._router: Optional[PushRouter] = None
        self._conf_watch = None
        self._conf_task: Optional[asyncio.Task] = None
        # the transport-ladder pull machinery (device-direct -> bulk ->
        # RPC, resumable, checksum-NACKing) lives in KvBlockPuller so the
        # drain/migration resume path (worker/drain.ResumeAdmission) can
        # reuse it verbatim; clients are attached in start()
        self._puller = KvBlockPuller(self.engine)

    # -- puller surface (delegated; tests monkeypatch/inspect these) -------

    @property
    def _kv_client(self):
        return self._puller.kv_client

    @property
    def _kv_direct_client(self):
        return self._puller.kv_direct_client

    @property
    def _direct_plane(self):
        return self._puller.direct_plane

    @property
    def direct_pull_timeout(self) -> float:
        return self._puller.direct_pull_timeout

    @direct_pull_timeout.setter
    def direct_pull_timeout(self, v: float) -> None:
        self._puller.direct_pull_timeout = v

    @property
    def _direct_down_until(self) -> dict:
        return self._puller.direct_down_until

    @property
    def _bulk_warmed(self) -> set:
        return self._puller.bulk_warmed

    @property
    def last_pull_stats(self) -> dict:
        return self._puller.last_pull_stats

    def _missing_blocks(self, hashes: list) -> list:
        return self._puller.missing(hashes)

    async def _pull_blocks(self, hashes: list, iid: int,
                           bulk_address: str = "",
                           direct_address: str = "",
                           lease: Optional[int] = None) -> None:
        await self._puller.pull_blocks(hashes, iid,
                                       bulk_address=bulk_address,
                                       direct_address=direct_address,
                                       lease=lease)

    async def start(self) -> "DisaggDecodeHandler":
        ns = self.drt.namespace(self.namespace)
        comp = ns.component(self.prefill_component)
        self._gen_client = await comp.endpoint("generate").client()
        self._puller.kv_client = await comp.endpoint(
            KV_EXPORT_ENDPOINT).client()
        self._puller.kv_direct_client = await comp.endpoint(
            KV_EXPORT_DIRECT_ENDPOINT).client()
        self._puller.direct_plane = make_device_transfer_plane(self.engine)
        self._router = PushRouter(self._gen_client, RouterMode.ROUND_ROBIN)
        self._conf_watch = await self.drt.coord.watch_prefix(
            disagg_conf_key(self.namespace))
        for _key, value in self._conf_watch.snapshot:
            self._apply_conf(value)
        self._conf_task = asyncio.create_task(self._conf_loop())
        return self

    async def stop(self) -> None:
        await reap_task(self._conf_task)
        if self._conf_watch is not None:
            try:
                await self._conf_watch.cancel()
            except Exception:
                pass
        for c in (self._gen_client, self._kv_client,
                  self._kv_direct_client):
            if c is not None:
                await c.close()

    def _apply_conf(self, raw: bytes) -> None:
        try:
            self.conf = DisaggConfig.from_json(raw)
            logger.info("disagg conf updated: max_local_prefill_length=%d",
                        self.conf.max_local_prefill_length)
        except Exception:
            logger.exception("bad disagg conf %r", raw)

    async def _conf_loop(self) -> None:
        async for ev in self._conf_watch:
            if ev.type == "put" and ev.value is not None:
                self._apply_conf(ev.value)

    # -- the disagg leg ----------------------------------------------------

    def _use_remote_prefill(self, request: PreprocessedRequest) -> bool:
        if self.strategy == "prefill_first":
            return False
        if not self._gen_client.instance_ids():
            return False
        n = len(request.token_ids)
        if n <= self.conf.max_local_prefill_length:
            return False
        # migration re-issue: the prompt is already (mostly) resident
        # locally — a resume just pulled its pinned KV, or a replay's
        # prefix survives in the cache — so remote prefill would
        # recompute what local admission adopts for free. Gated on
        # resumed_tokens: ordinary requests skip the O(prompt) hash walk
        # on this hot path (admission computes the chain anyway)
        resident = 0
        if request.resumed_tokens:
            resident = self._resumable_blocks(request) \
                * self.engine.allocator.page_size
        return (n - resident) > self.conf.max_local_prefill_length

    async def _queue_prefill(self, preq: PreprocessedRequest
                             ) -> Optional[LLMEngineOutput]:
        """Prefill via the coordinator work queue: push the job, await the
        reply event, pull the KV blocks from whichever prefill worker took
        it. Returns None on timeout/failure (caller falls back to the
        direct round-robin leg, then to local prefill)."""
        from dynamo_tpu.runtime import codec
        # no queue consumers -> don't park the request behind a timeout;
        # the direct round-robin leg handles pre-queue prefill workers
        depth, pullers = await self.drt.coord.queue_depth(
            prefill_queue_name(self.namespace))
        if pullers == 0 and depth == 0:
            return None
        rid = preq.request_id or f"pf-{id(preq):x}"
        subject = prefill_reply_subject(self.namespace, rid)
        # a DISTINCT request id for the queued copy: if this leg times out
        # and the direct leg re-sends rid to the same worker, a late queue
        # pull must not collide in the engine's request_id-keyed state
        preq = PreprocessedRequest.from_dict(preq.to_dict())
        preq.request_id = f"{rid}-q"
        preq.prefill_only = True
        tracer = get_tracer()
        sub = await self.drt.subscribe_events(subject)
        try:
            with tracer.span("prefill", attrs={"remote": True,
                                               "leg": "queue"}) as psp:
                await self.drt.coord.queue_push(
                    prefill_queue_name(self.namespace),
                    codec.pack({"req": preq.to_dict(), "reply": subject,
                                "ttl": self.queue_timeout,
                                # the prefill worker's fragment parents here
                                "trace": psp.headers() or None}))
                try:
                    _subj, reply = await asyncio.wait_for(
                        sub.__anext__(), timeout=self.queue_timeout)
                except asyncio.TimeoutError:
                    logger.warning("prefill queue reply timed out after "
                                   "%.1fs", self.queue_timeout)
                    psp.set_error("prefill queue reply timeout")
                    return None
                tracer.adopt(reply.get(SPANS_FRAME_KEY))
                if not reply.get("out"):
                    return None
                final = LLMEngineOutput.from_dict(reply["out"])
                if final.error:
                    psp.set_error(final.error)
                    return None
            params = final.kv_transfer_params or {}
            hashes = [b[0] for b in params.get("blocks", [])]
            if hashes:
                await self._pull_blocks(
                    hashes, reply["instance_id"],
                    bulk_address=reply.get("bulk_address", ""),
                    direct_address=reply.get("direct_address", ""),
                    lease=params.get("lease"))
            return final
        finally:
            try:
                await sub.cancel()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def _pick_prefill_instance(self, exclude: set) -> Optional[int]:
        """Round-robin the next prefill instance, skipping ``exclude``
        (failed legs of this request); None when no other instance is
        live."""
        ids = [i for i in sorted(self._gen_client.instance_ids())
               if i not in exclude]
        if not ids:
            return None
        try:
            iid = self._router.select_instance()
        except ConnectionError:
            return None
        return iid if iid not in exclude else ids[0]

    def _resumable_blocks(self, request: PreprocessedRequest) -> int:
        """Leading prompt blocks ALREADY committed locally (a partially
        successful pull) — the local-prefill fallback resumes from them
        via normal prefix-match admission instead of recomputing."""
        try:
            from dynamo_tpu.tokens import compute_block_hash_for_seq
            alloc = self.engine.allocator
            return alloc.peek_prefix(compute_block_hash_for_seq(
                request.token_ids, alloc.page_size))
        except Exception:  # noqa: BLE001 — accounting only
            return 0

    async def _remote_prefill(self, request: PreprocessedRequest
                              ) -> Optional[LLMEngineOutput]:
        """Run the prefill leg; returns the final prefill frame (first token +
        kv_transfer_params) or None on any failure (-> local fallback).
        Tries the prefill queue first (workers pull when free — reference
        PrefillQueue role), then the direct round-robin leg — retried ONCE
        on an alternate instance (deadline budget allowing) before giving
        up, so a single crashed prefill worker doesn't cost the whole
        prompt a local re-prefill. The fallback itself resumes from
        whatever blocks a partial pull already committed (prefix-match
        admission picks them up)."""
        preq = PreprocessedRequest.from_dict(request.to_dict())
        preq.prefill_only = True
        if self.use_queue:
            try:
                final = await self._queue_prefill(preq)
            except Exception as e:  # noqa: BLE001 — queue leg must not fail
                logger.warning("prefill queue leg failed (%s); trying "
                               "direct", e)
                final = None
            if final is not None:
                return final
        tracer = get_tracer()
        tried: set = set()
        for attempt in range(2):
            iid = self._pick_prefill_instance(tried)
            if iid is None:
                break
            if attempt and preq.deadline_unix is not None \
                    and preq.deadline_unix - time.time() <= 0:
                # out of deadline budget: a failover leg would prefill for
                # a caller whose request already expired
                logger.warning("skipping prefill failover: deadline spent")
                break
            try:
                final: Optional[LLMEngineOutput] = None
                # the end-to-end deadline and request id ride the internal
                # hop too (trace context auto-injected by the connection),
                # so a stuck prefill worker can't hold the decode worker
                # past it
                with tracer.span("prefill",
                                 attrs={"remote": True, "leg": "direct",
                                        "instance": f"{iid:x}",
                                        "retries": attempt}) as psp:
                    stream = await self._gen_client.direct(
                        preq.to_dict(), iid,
                        request_headers(preq.deadline_unix,
                                        preq.request_id))
                    async for payload in stream:
                        if isinstance(payload, dict) \
                                and SPANS_FRAME_KEY in payload:
                            tracer.adopt(payload.pop(SPANS_FRAME_KEY))
                        out = LLMEngineOutput.from_dict(payload)
                        if out.finish_reason is not None:
                            final = out
                    if final is None or final.error:
                        psp.set_error((final.error if final is not None
                                       else None)
                                      or "no final prefill frame")
                        raise RuntimeError(
                            (final.error if final is not None else None)
                            or "no final prefill frame")
                params = final.kv_transfer_params or {}
                hashes = [b[0] for b in params.get("blocks", [])]
                if hashes:
                    await self._pull_blocks(hashes, iid,
                                            lease=params.get("lease"))
                if attempt:
                    self._count_failover("ok")
                return final
            except DeadlineExceededError:
                # the request is already expired: a local-prefill fallback
                # would burn the longest class of prompts for a caller
                # that's gone
                raise
            except Exception as e:  # noqa: BLE001 — disagg must never fail
                # a request: any remote-leg error (connection, malformed
                # frame, inject failure) retries an alternate instance,
                # then falls back to local prefill
                tried.add(iid)
                if attempt:
                    self._count_failover("failed")
                retry = (attempt == 0
                         and self._pick_prefill_instance(tried) is not None)
                logger.warning(
                    "remote prefill on %x failed (%s); %s", iid, e,
                    "retrying an alternate instance" if retry
                    else "falling back local",
                    exc_info=not isinstance(e, ConnectionError))
                if not retry and attempt == 0:
                    break
        resumed = self._resumable_blocks(request)
        if resumed:
            logger.info("local prefill fallback resumes from %d committed "
                        "block(s)", resumed)
        return None

    @staticmethod
    def _count_failover(outcome: str) -> None:
        from dynamo_tpu.worker.metrics import count_metric
        count_metric("prefill_failovers", outcome)

    async def _inbound_prefill(self, request: PreprocessedRequest
                               ) -> Optional[LLMEngineOutput]:
        """PREFILL-FIRST inbound leg: the request arrives WITH
        ``kv_transfer_params`` already attached (a prefill worker computed
        the prefix and forwarded the request here — reference:
        ``DisaggregationStrategy.PREFILL_FIRST``,
        ``trtllm/utils/request_handlers/handler_base.py:34-60``). Pull the
        advertised blocks and synthesize the first-token frame; any failure
        returns None and the prompt prefills locally (the blocks are an
        optimization, the token ids are the truth)."""
        params = request.kv_transfer_params or {}
        blocks = params.get("blocks") or []
        if not blocks or "first_token" not in params:
            return None
        request.kv_transfer_params = None  # consumed; never forward again
        try:
            hashes = [b[0] for b in blocks]
            await self._pull_blocks(hashes, int(params.get("instance_id", 0)),
                                    bulk_address=params.get("bulk_address",
                                                            ""),
                                    direct_address=params.get(
                                        "direct_address", ""),
                                    lease=params.get("lease"))
        except Exception as e:  # noqa: BLE001 — prefix pull is best-effort
            logger.warning("inbound prefill block pull failed (%s); "
                           "decoding with local prefill", e)
        return LLMEngineOutput(
            token_ids=[int(params["first_token"])],
            log_probs=([float(params["logprob"])]
                       if params.get("logprob") is not None else None),
            finish_reason=FinishReason.LENGTH)

    async def generate(self, request: PreprocessedRequest,
                       ctx=None) -> AsyncIterator[LLMEngineOutput]:
        first: Optional[LLMEngineOutput] = None
        if getattr(self.engine, "draining", False):
            # a request that raced the drain announcement: don't burn a
            # remote prefill for an engine that will refuse it — the
            # engine's replay marker sends it straight back to the
            # frontend's migration layer
            pass
        elif request.kv_transfer_params:
            first = await self._inbound_prefill(request)
        elif self._use_remote_prefill(request):
            first = await self._remote_prefill(request)
        async for out in _continue_after_first(self.engine, request, first,
                                               ctx):
            yield out


async def _continue_after_first(engine: JaxEngine,
                                request: PreprocessedRequest,
                                first: Optional[LLMEngineOutput],
                                ctx=None) -> AsyncIterator[LLMEngineOutput]:
    """Stream a request on ``engine`` given an optional handed-off FIRST
    token (a completed remote/local prefill leg): emit it, resolve its
    stop conditions (EOS / stop tokens / max_tokens), then decode the rest
    with the token appended to the prompt — the one shared continuation
    for the decode-first, prefill-first-inbound, and prefill-first-local-
    fallback paths, so their stop semantics can never drift apart."""
    if first is not None and first.token_ids:
        tok = first.token_ids[0]
        yield LLMEngineOutput(token_ids=[tok], log_probs=first.log_probs)
        sc = request.stop_conditions
        done = ((not sc.ignore_eos and tok in request.eos_token_ids)
                or (sc.stop_token_ids and tok in sc.stop_token_ids)
                or (sc.max_tokens is not None and sc.max_tokens <= 1))
        if done:
            yield LLMEngineOutput(
                finish_reason=first.finish_reason,
                prompt_tokens=len(request.token_ids),
                completion_tokens=1)
            return
        request = PreprocessedRequest.from_dict(request.to_dict())
        request.token_ids = list(request.token_ids) + [tok]
        # the handed-off token is GENERATED output riding the prompt:
        # penalties keep counting it, and a later graceful drain's
        # resume token counts it in its cumulative tokens_done (the
        # frontend's desync check compares against the client-side
        # stream, which includes it)
        request.resumed_tokens = (request.resumed_tokens or 0) + 1
        if request.stop_conditions.max_tokens is not None:
            request.stop_conditions.max_tokens -= 1
    async for out in engine.generate(request, ctx):
        if (first is not None and out.finish_reason is not None
                and out.completion_tokens is not None):
            # the handed-off first token counts as completion, not prompt
            out.prompt_tokens = (out.prompt_tokens or 1) - 1
            out.completion_tokens = out.completion_tokens + 1
        yield out


class PrefillFirstHandler:
    """PREFILL-FIRST entry: this (prefill) worker receives the request,
    prefills locally, attaches ``kv_transfer_params`` (block hashes + where
    to fetch them + the first token), and forwards the request to a decode
    worker, relaying its stream. The mirror of ``DisaggDecodeHandler``'s
    decode-first flow, selectable per deployment (reference:
    ``handler_base.py:34-60`` ``DisaggregationStrategy``)."""

    def __init__(self, engine: JaxEngine, drt: DistributedRuntime,
                 namespace: str, decode_component: str,
                 instance_id: int = 0, bulk_address: str = "",
                 direct_address: str = ""):
        self.engine = engine
        self.drt = drt
        self.namespace = namespace
        self.decode_component = decode_component
        self.instance_id = instance_id
        self.bulk_address = bulk_address
        self.direct_address = direct_address
        self._decode_client = None
        self._router: Optional[PushRouter] = None

    async def start(self) -> "PrefillFirstHandler":
        comp = self.drt.namespace(self.namespace).component(
            self.decode_component)
        self._decode_client = await comp.endpoint("generate").client()
        self._router = PushRouter(self._decode_client, RouterMode.ROUND_ROBIN)
        return self

    async def stop(self) -> None:
        if self._decode_client is not None:
            await self._decode_client.close()

    async def generate(self, request: PreprocessedRequest,
                       ctx=None) -> AsyncIterator[LLMEngineOutput]:
        if not self._decode_client.instance_ids():
            # no decode workers live: serve the whole request here rather
            # than fail (disagg is an optimization, never a point of
            # failure)
            async for out in self.engine.generate(request, ctx):
                yield out
            return
        preq = PreprocessedRequest.from_dict(request.to_dict())
        preq.request_id = f"{request.request_id}-pf"
        preq.prefill_only = True
        final: Optional[LLMEngineOutput] = None
        stitcher = StageStitcher(get_tracer(), skip_decode=True)
        try:
            async for out in self.engine.generate(preq):
                stitcher.on_frame(out)
                if out.finish_reason is not None:
                    final = out
        finally:
            stitcher.close()
        if final is None or final.error or not final.token_ids:
            logger.warning("local prefill leg failed; serving fully local")
            async for out in self.engine.generate(request, ctx):
                yield out
            return
        fwd = PreprocessedRequest.from_dict(request.to_dict())
        params = dict(final.kv_transfer_params or {})
        # pin the advertised blocks until the decode side acks its pull
        # (or the TTL GC reclaims — decode worker crashed)
        lease = await stamp_export_lease(self.engine, params)
        params["first_token"] = final.token_ids[0]
        if final.log_probs:
            params["logprob"] = final.log_probs[0]
        params["instance_id"] = self.instance_id
        params["bulk_address"] = self.bulk_address
        if self.direct_address:
            params["direct_address"] = self.direct_address
        fwd.kv_transfer_params = params
        relayed = False
        try:
            tracer = get_tracer()
            iid = self._router.select_instance()
            stream = await self._decode_client.direct(
                fwd.to_dict(), iid,
                request_headers(fwd.deadline_unix, fwd.request_id))
            async for payload in stream:
                if isinstance(payload, dict) and SPANS_FRAME_KEY in payload:
                    # decode worker's fragment: adopt so it ships upward
                    # with THIS worker's hop spans
                    tracer.adopt(payload.pop(SPANS_FRAME_KEY))
                out = LLMEngineOutput.from_dict(payload)
                # the decode worker already turned its timing stamps into
                # spans; relaying them would double-stitch queue/prefill
                out.timings = None
                relayed = relayed or bool(out.token_ids)
                yield out
            return
        except DeadlineExceededError:
            raise  # expired request: never restart it locally
        except Exception as e:  # noqa: BLE001 — decode hop failed: the
            # prefix is still cached here, finish the request locally —
            # but ONLY if nothing was relayed yet. After a partial relay a
            # local restart would repeat tokens the client already has;
            # surface the break instead (the frontend's migration layer
            # handles mid-stream worker loss).
            if relayed:
                logger.warning("decode stream broke mid-relay (%s)", e)
                yield LLMEngineOutput(finish_reason=FinishReason.ERROR,
                                      error=f"decode worker lost: {e}")
                return
            logger.warning("decode forward failed (%s); continuing local", e)
            if lease is not None:
                # nobody will ever pull this export: unpin now rather than
                # waiting out the TTL
                from dynamo_tpu.engine.transfer import release_export_lease
                await release_export_lease(self.engine, lease)
            async for out in _continue_after_first(self.engine, request,
                                                   final, ctx):
                yield out


__all__ = ["DisaggDecodeHandler", "PrefillFirstHandler", "DisaggConfig",
           "KvBlockPuller",
           "disagg_conf_key", "KV_EXPORT_ENDPOINT"]
