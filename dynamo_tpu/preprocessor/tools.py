"""Tool-call extraction from generated text.

Parity: reference ``lib/llm/src/preprocessor/tools.rs`` —
``ToolCallingMatcher`` accepts a completed assistant message that IS a
JSON tool invocation and converts it to OpenAI ``tool_calls`` entries.
Accepted shapes (same as the reference's serde attempts, in order):

- ``{"name": ..., "parameters": {...}}``
- ``[{"name": ..., "parameters": {...}}, ...]``
- ``{"name": ..., "arguments": {...}}``
- ``[{"name": ..., "arguments": {...}}, ...]``

Extension beyond the reference (the models this framework serves
natively emit it): the qwen/hermes ``<tool_call> {...} </tool_call>``
wrapper — each wrapped block parses with the same shapes. A message that
parses as tool calls returns them and the HTTP layer reports
``finish_reason: "tool_calls"`` with ``content: null``; anything else
returns ``[]`` and the text passes through untouched.
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Any, Dict, List, Optional

_TOOL_CALL_RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>",
                           re.DOTALL)


def _one_call(obj: Any) -> Optional[Dict[str, Any]]:
    """A dict of {name, parameters|arguments} -> OpenAI tool_call entry."""
    if not isinstance(obj, dict) or not isinstance(obj.get("name"), str):
        return None
    args = obj.get("parameters", obj.get("arguments"))
    if not isinstance(args, dict):
        return None
    return {
        "id": f"call-{uuid.uuid4()}",
        "type": "function",
        "function": {"name": obj["name"],
                     "arguments": json.dumps(args)},
    }


def _from_json_text(text: str) -> List[Dict[str, Any]]:
    try:
        obj = json.loads(text)
    except (json.JSONDecodeError, ValueError):
        return []
    if isinstance(obj, dict):
        call = _one_call(obj)
        return [call] if call else []
    if isinstance(obj, list):
        calls = [_one_call(o) for o in obj]
        if calls and all(c is not None for c in calls):
            return calls  # type: ignore[return-value]
    return []


def parse_tool_calls(message: str,
                     tool_choice: Any = "auto") -> List[Dict[str, Any]]:
    """Extract OpenAI ``tool_calls`` from a completed message, or ``[]``.

    ``tool_choice="none"`` disables parsing (reference:
    ``ToolCallingMatcher::get_call``)."""
    if tool_choice == "none":
        return []
    text = message.strip()
    if not text:
        return []
    wrapped = _TOOL_CALL_RE.findall(text)
    if wrapped:
        # the whole message must be tool calls (modulo whitespace) — a
        # prose answer that merely MENTIONS the tag stays text
        remainder = _TOOL_CALL_RE.sub("", text).strip()
        if remainder:
            return []
        calls: List[Dict[str, Any]] = []
        for block in wrapped:
            got = _from_json_text(block)
            if not got:
                return []
            calls.extend(got)
        return calls
    return _from_json_text(text)


def forced_tool_guided_spec(tools: Optional[List[Dict[str, Any]]],
                            tool_choice: Any) -> Optional[Dict[str, Any]]:
    """Guided-decoding spec that GUARANTEES a parseable tool call when
    ``tool_choice`` demands one — the engine-side realization of OpenAI's
    forced function calling (the reference forwards it to engines whose
    guided backends do the same).

    Returns None when nothing is forced (auto/none/absent). The forced
    output shape is exactly what :func:`parse_tool_calls` accepts:
    ``{"name": <tool>, "arguments": {...}}`` — ``name`` constrained to
    the allowed tool(s), ``arguments`` to the tool's declared parameter
    schema when this grammar can express it, else any JSON object (the
    caller downgrades on GuidedUnsupported).

    Raises ValueError on a tool_choice naming an undeclared function or
    demanding a call with no tools — a 400, matching OpenAI.
    """
    if tool_choice in (None, "auto", "none"):
        return None
    by_name: Dict[str, Dict[str, Any]] = {}
    for t in tools or ():
        fn = t.get("function") if isinstance(t, dict) else None
        if isinstance(fn, dict) and isinstance(fn.get("name"), str):
            by_name[fn["name"]] = fn
    if tool_choice == "required":
        names = list(by_name)
        if not names:
            raise ValueError("tool_choice='required' needs tools")
    elif (isinstance(tool_choice, dict)
          and isinstance(tool_choice.get("function"), dict)):
        name = tool_choice["function"].get("name")
        if name not in by_name:
            raise ValueError(
                f"tool_choice names unknown function {name!r}")
        names = [name]
    else:
        raise ValueError(f"unsupported tool_choice {tool_choice!r}")

    if len(names) == 1:
        params = by_name[names[0]].get("parameters")
        # only embed a schema that yields an OBJECT: parse_tool_calls
        # requires dict arguments, so a non-object parameters schema
        # (valid JSON Schema, but not a function signature) falls back to
        # any-object rather than forcing unparseable output
        is_obj = (isinstance(params, dict) and params
                  and (params.get("type") == "object"
                       or "properties" in params))
        args_schema = params if is_obj else {"type": "object"}
        name_schema: Dict[str, Any] = {"const": names[0]}
    else:
        # several candidates: our unions dispatch on the FIRST byte, and
        # every per-tool object starts with '{' — so constrain the name
        # to the declared set and leave arguments an open object
        args_schema = {"type": "object"}
        name_schema = {"enum": sorted(names)}
    return {"mode": "json_schema", "schema": {
        "type": "object",
        "properties": {"name": name_schema, "arguments": args_schema},
        "required": ["name", "arguments"],
    }}


def degrade_tool_spec(spec: Dict[str, Any]) -> Dict[str, Any]:
    """The fallback when a tool's own parameter schema uses keywords the
    grammar cannot enforce: same envelope, arguments open."""
    out = json.loads(json.dumps(spec))
    out["schema"]["properties"]["arguments"] = {"type": "object"}
    return out


__all__ = ["parse_tool_calls", "forced_tool_guided_spec",
           "degrade_tool_spec"]
