"""Tool-call extraction from generated text.

Parity: reference ``lib/llm/src/preprocessor/tools.rs`` —
``ToolCallingMatcher`` accepts a completed assistant message that IS a
JSON tool invocation and converts it to OpenAI ``tool_calls`` entries.
Accepted shapes (same as the reference's serde attempts, in order):

- ``{"name": ..., "parameters": {...}}``
- ``[{"name": ..., "parameters": {...}}, ...]``
- ``{"name": ..., "arguments": {...}}``
- ``[{"name": ..., "arguments": {...}}, ...]``

Extension beyond the reference (the models this framework serves
natively emit it): the qwen/hermes ``<tool_call> {...} </tool_call>``
wrapper — each wrapped block parses with the same shapes. A message that
parses as tool calls returns them and the HTTP layer reports
``finish_reason: "tool_calls"`` with ``content: null``; anything else
returns ``[]`` and the text passes through untouched.
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Any, Dict, List, Optional

_TOOL_CALL_RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>",
                           re.DOTALL)


def _one_call(obj: Any) -> Optional[Dict[str, Any]]:
    """A dict of {name, parameters|arguments} -> OpenAI tool_call entry."""
    if not isinstance(obj, dict) or not isinstance(obj.get("name"), str):
        return None
    args = obj.get("parameters", obj.get("arguments"))
    if not isinstance(args, dict):
        return None
    return {
        "id": f"call-{uuid.uuid4()}",
        "type": "function",
        "function": {"name": obj["name"],
                     "arguments": json.dumps(args)},
    }


def _from_json_text(text: str) -> List[Dict[str, Any]]:
    try:
        obj = json.loads(text)
    except (json.JSONDecodeError, ValueError):
        return []
    if isinstance(obj, dict):
        call = _one_call(obj)
        return [call] if call else []
    if isinstance(obj, list):
        calls = [_one_call(o) for o in obj]
        if calls and all(c is not None for c in calls):
            return calls  # type: ignore[return-value]
    return []


def parse_tool_calls(message: str,
                     tool_choice: Any = "auto") -> List[Dict[str, Any]]:
    """Extract OpenAI ``tool_calls`` from a completed message, or ``[]``.

    ``tool_choice="none"`` disables parsing (reference:
    ``ToolCallingMatcher::get_call``)."""
    if tool_choice == "none":
        return []
    text = message.strip()
    if not text:
        return []
    wrapped = _TOOL_CALL_RE.findall(text)
    if wrapped:
        # the whole message must be tool calls (modulo whitespace) — a
        # prose answer that merely MENTIONS the tag stays text
        remainder = _TOOL_CALL_RE.sub("", text).strip()
        if remainder:
            return []
        calls: List[Dict[str, Any]] = []
        for block in wrapped:
            got = _from_json_text(block)
            if not got:
                return []
            calls.extend(got)
        return calls
    return _from_json_text(text)


__all__ = ["parse_tool_calls"]
