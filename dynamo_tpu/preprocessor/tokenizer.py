"""Tokenizer wrapper: HF `tokenizers` fast tokenizer + incremental decoding.

Parity: reference ``lib/llm/src/tokenizers.rs`` (encode/decode wrappers,
lifetime-safe ``DecodeStream``).  The incremental decoder uses the
prefix-window technique (decode a sliding window, emit only once the new
suffix no longer ends in an incomplete UTF-8/byte-fallback sequence), which is
the standard approach for streaming detokenization with byte-level BPE.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from tokenizers import Tokenizer

# replacement char appears while a multi-byte sequence is still incomplete
_REPLACEMENT = "�"

_BYTE_DECODER: Optional[dict] = None


def _gpt2_byte_decoder() -> dict:
    """char -> byte unmapping of the standard byte-level BPE alphabet
    (the gpt2 ``bytes_to_unicode`` table, inverted)."""
    global _BYTE_DECODER
    if _BYTE_DECODER is None:
        bs = (list(range(0x21, 0x7F)) + list(range(0xA1, 0xAD))
              + list(range(0xAE, 0x100)))
        cs = bs[:]
        n = 0
        for b in range(256):
            if b not in bs:
                bs.append(b)
                cs.append(256 + n)
                n += 1
        _BYTE_DECODER = {chr(c): b for b, c in zip(bs, cs)}
    return _BYTE_DECODER


class HfTokenizer:
    """Thin wrapper over a `tokenizers.Tokenizer` (thread-safe encode/decode)."""

    def __init__(self, tokenizer: Tokenizer):
        self._tk = tokenizer
        self._lock = threading.Lock()

    @classmethod
    def from_file(cls, path: str) -> "HfTokenizer":
        return cls(Tokenizer.from_file(path))

    @classmethod
    def from_json(cls, json_str: str) -> "HfTokenizer":
        return cls(Tokenizer.from_str(json_str))

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        with self._lock:
            return self._tk.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: List[int], skip_special_tokens: bool = True) -> str:
        with self._lock:
            return self._tk.decode(ids, skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> Optional[int]:
        with self._lock:
            return self._tk.token_to_id(token)

    @property
    def vocab_size(self) -> int:
        return self._tk.get_vocab_size()

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens)

    def token_bytes(self) -> List[Optional[bytes]]:
        """The byte string each token id appends to the output (None for
        special/added tokens) — the vocabulary view guided decoding walks
        (``engine/guided.py``). Handles byte-level BPE (gpt2 char->byte
        unmapping), sentencepiece-style pieces (metaspace + <0xNN> byte
        fallback), and plain vocabularies."""
        with self._lock:
            size = self._tk.get_vocab_size()
            vocab = self._tk.get_vocab(with_added_tokens=True)
            byte_level = '"ByteLevel"' in (self._tk.to_str() or "")
            specials = set()
            try:
                for tid, at in self._tk.get_added_tokens_decoder().items():
                    if getattr(at, "special", True):
                        specials.add(int(tid))
            except AttributeError:
                # older tokenizers builds: anything present only in the
                # with-added vocab is an added token — treat ALL of them
                # as special (a literal b"<s>" walking a grammar while
                # the detokenizer drops it would desync text from walk)
                base = self._tk.get_vocab(with_added_tokens=False)
                specials = {tid for tok, tid in vocab.items()
                            if tok not in base}
        out: List[Optional[bytes]] = [None] * size
        dec = _gpt2_byte_decoder()
        for tok, tid in vocab.items():
            if not 0 <= tid < size or tid in specials:
                continue
            if byte_level and all(c in dec for c in tok):
                out[tid] = bytes(dec[c] for c in tok)
            elif (len(tok) == 6 and tok.startswith("<0x")
                  and tok.endswith(">")):
                out[tid] = bytes([int(tok[3:5], 16)])     # SP byte fallback
            else:
                out[tid] = tok.replace("▁", " ").encode("utf-8")
        return out


class DecodeStream:
    """Incremental detokenizer: feed token ids one at a time, get text deltas.

    Keeps ``prefix_offset``/``read_offset`` into the accumulated id list; each
    step decodes ``ids[prefix:]`` and emits the part beyond the previously read
    text, holding back output while it ends in an incomplete byte sequence.
    """

    def __init__(self, tokenizer: HfTokenizer, skip_special_tokens: bool = True):
        self._tk = tokenizer
        self._skip_special = skip_special_tokens
        self._ids: List[int] = []
        self._prefix_offset = 0
        self._read_offset = 0

    def step(self, token_id: int) -> str:
        self._ids.append(token_id)
        prefix_text = self._tk.decode(
            self._ids[self._prefix_offset:self._read_offset],
            skip_special_tokens=self._skip_special)
        new_text = self._tk.decode(
            self._ids[self._prefix_offset:],
            skip_special_tokens=self._skip_special)
        if new_text.endswith(_REPLACEMENT):
            # mid-multibyte: hold output until the sequence completes
            return ""
        delta = new_text[len(prefix_text):]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        return delta

    def extend(self, token_ids: List[int]) -> str:
        return "".join(self.step(t) for t in token_ids)


__all__ = ["HfTokenizer", "DecodeStream"]
