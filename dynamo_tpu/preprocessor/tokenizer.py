"""Tokenizer wrapper: HF `tokenizers` fast tokenizer + incremental decoding.

Parity: reference ``lib/llm/src/tokenizers.rs`` (encode/decode wrappers,
lifetime-safe ``DecodeStream``).  The incremental decoder uses the
prefix-window technique (decode a sliding window, emit only once the new
suffix no longer ends in an incomplete UTF-8/byte-fallback sequence), which is
the standard approach for streaming detokenization with byte-level BPE.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from tokenizers import Tokenizer

# replacement char appears while a multi-byte sequence is still incomplete
_REPLACEMENT = "�"


class HfTokenizer:
    """Thin wrapper over a `tokenizers.Tokenizer` (thread-safe encode/decode)."""

    def __init__(self, tokenizer: Tokenizer):
        self._tk = tokenizer
        self._lock = threading.Lock()

    @classmethod
    def from_file(cls, path: str) -> "HfTokenizer":
        return cls(Tokenizer.from_file(path))

    @classmethod
    def from_json(cls, json_str: str) -> "HfTokenizer":
        return cls(Tokenizer.from_str(json_str))

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        with self._lock:
            return self._tk.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: List[int], skip_special_tokens: bool = True) -> str:
        with self._lock:
            return self._tk.decode(ids, skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> Optional[int]:
        with self._lock:
            return self._tk.token_to_id(token)

    @property
    def vocab_size(self) -> int:
        return self._tk.get_vocab_size()

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens)


class DecodeStream:
    """Incremental detokenizer: feed token ids one at a time, get text deltas.

    Keeps ``prefix_offset``/``read_offset`` into the accumulated id list; each
    step decodes ``ids[prefix:]`` and emits the part beyond the previously read
    text, holding back output while it ends in an incomplete byte sequence.
    """

    def __init__(self, tokenizer: HfTokenizer, skip_special_tokens: bool = True):
        self._tk = tokenizer
        self._skip_special = skip_special_tokens
        self._ids: List[int] = []
        self._prefix_offset = 0
        self._read_offset = 0

    def step(self, token_id: int) -> str:
        self._ids.append(token_id)
        prefix_text = self._tk.decode(
            self._ids[self._prefix_offset:self._read_offset],
            skip_special_tokens=self._skip_special)
        new_text = self._tk.decode(
            self._ids[self._prefix_offset:],
            skip_special_tokens=self._skip_special)
        if new_text.endswith(_REPLACEMENT):
            # mid-multibyte: hold output until the sequence completes
            return ""
        delta = new_text[len(prefix_text):]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        return delta

    def extend(self, token_ids: List[int]) -> str:
        return "".join(self.step(t) for t in token_ids)


__all__ = ["HfTokenizer", "DecodeStream"]
