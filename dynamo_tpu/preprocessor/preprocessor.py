"""OpenAIPreprocessor: OpenAI request -> PreprocessedRequest (fwd) and
LLMEngineOutput/BackendOutput stream -> OpenAI deltas (bwd).

Parity: reference ``lib/llm/src/preprocessor.rs:92-424`` (forward:
template + tokenize + sampling/stop extraction + annotations) and the
``DeltaGenerator`` SSE backward pass (``preprocessor.rs:320-424``).
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple, Union

from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.preprocessor.template import PromptFormatter
from dynamo_tpu.preprocessor.tokenizer import HfTokenizer
from dynamo_tpu.protocols.common import (
    BackendOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.protocols.openai import (
    ChatChunkChoice,
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChoiceLogprobs,
    CompletionRequest,
    DeltaMessage,
    Usage,
    new_request_id,
    now_unix,
)

logger = logging.getLogger(__name__)

# frontend-side guided-spec validation cache: apps typically post the SAME
# json_schema on every request, and compiling a grammar per request on the
# service event loop would be pure waste — remember which canonical specs
# compiled cleanly (the worker keeps its own grammar cache for serving)
_GUIDED_OK: Dict[str, bool] = {}
_GUIDED_OK_CAP = 128


def _validate_guided_spec(spec: Dict[str, Any]) -> None:
    import json as _json

    key = _json.dumps(spec, sort_keys=True)
    if _GUIDED_OK.get(key):
        return
    from dynamo_tpu.engine.guided import compile_guided
    compile_guided(spec)   # raises GuidedUnsupported (a ValueError)
    if len(_GUIDED_OK) >= _GUIDED_OK_CAP:
        _GUIDED_OK.pop(next(iter(_GUIDED_OK)))
    _GUIDED_OK[key] = True

# annotation keys (parity: reference nvext annotations "formatted_prompt",
# "token_ids", "query_instance_id")
ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_TOKEN_IDS = "token_ids"
ANNOTATION_QUERY_INSTANCE_ID = "query_instance_id"


class OpenAIPreprocessor:
    """Stateless per-model request preprocessor."""

    def __init__(self, card: ModelDeploymentCard, tokenizer: Optional[HfTokenizer] = None):
        self.card = card
        self.tokenizer = tokenizer if tokenizer is not None else card.load_tokenizer()
        self.formatter = PromptFormatter(card.chat_template)

    # -- forward pass ------------------------------------------------------

    def preprocess_chat(self, req: ChatCompletionRequest,
                        request_id: Optional[str] = None) -> PreprocessedRequest:
        prompt = self.formatter.render(
            [m.model_dump(exclude_none=True) for m in req.messages],
            add_generation_prompt=True,
            tools=req.tools)
        token_ids = self.tokenizer.encode(prompt)
        out = self._build(req, token_ids, request_id)
        annotations = (req.nvext.annotations if req.nvext else None) or []
        out.annotations = list(annotations)
        if ANNOTATION_FORMATTED_PROMPT in annotations:
            out.annotations_payload[ANNOTATION_FORMATTED_PROMPT] = prompt
        if ANNOTATION_TOKEN_IDS in annotations:
            out.annotations_payload[ANNOTATION_TOKEN_IDS] = list(token_ids)
        return out

    def preprocess_completion(self, req: CompletionRequest,
                              request_id: Optional[str] = None) -> PreprocessedRequest:
        prompt = req.prompt
        if isinstance(prompt, str):
            token_ids = self.tokenizer.encode(prompt)
        elif prompt and isinstance(prompt[0], int):
            token_ids = list(prompt)  # pre-tokenized
        else:
            raise ValueError("batch prompts must be fanned out by the caller")
        out = self._build(req, token_ids, request_id)
        out.annotations = list((req.nvext.annotations if req.nvext else None) or [])
        return out

    # fallback when the card predates the field: the engine's default
    # sparse penalty window (JaxEngineConfig.penalty_window)
    MAX_LOGIT_BIAS = 32

    def _validate_logit_bias(self, lb):
        if not lb:
            return None
        # the SERVING engine's configured window (advertised on the model
        # card by the worker, like num_top_logprobs) — a deployment with a
        # narrower window must reject wide logit_bias instead of silently
        # dropping entries on device (ADVICE r4)
        limit = getattr(self.card, "penalty_window", self.MAX_LOGIT_BIAS)
        if len(lb) > limit:
            raise ValueError(
                f"logit_bias supports at most {limit} entries on this "
                f"model's serving engine, got {len(lb)}")
        vocab = self.tokenizer.vocab_size
        out = {}
        for k, v in lb.items():
            try:
                t = int(k)
            except (TypeError, ValueError):
                raise ValueError(f"logit_bias key {k!r} is not a token id")
            if not 0 <= t < vocab:
                raise ValueError(
                    f"logit_bias token id {t} outside the vocab "
                    f"(size {vocab})")
            out[t] = float(v)
        return out

    def _build(self, req: Union[ChatCompletionRequest, CompletionRequest],
               token_ids: List[int], request_id: Optional[str]) -> PreprocessedRequest:
        if len(token_ids) >= self.card.context_length:
            raise ValueError(
                f"prompt is {len(token_ids)} tokens but the model context "
                f"length is {self.card.context_length}")
        max_tokens = (req.effective_max_tokens()
                      if isinstance(req, ChatCompletionRequest) else req.max_tokens)
        budget = self.card.context_length - len(token_ids)
        max_tokens = min(max_tokens, budget) if max_tokens is not None else budget
        ignore_eos = bool(req.nvext.ignore_eos) if (
            req.nvext and req.nvext.ignore_eos is not None) else False
        stop_conditions = StopConditions(
            max_tokens=max_tokens,
            stop=req.stop_list(),
            min_tokens=req.min_tokens,
            ignore_eos=ignore_eos,
        )
        # OpenAI logprobs: chat gates a count behind a bool (logprobs=true +
        # top_logprobs=N); legacy completions passes the count directly.
        # sampling.logprobs None = off, 0 = sampled token only, N = +N tops.
        if isinstance(req, ChatCompletionRequest):
            logprobs = ((req.top_logprobs or 0) if req.logprobs else None)
        else:
            logprobs = req.logprobs
        if logprobs is not None:
            # OpenAI caps top_logprobs at 20; the serving engine computes
            # exactly card.num_top_logprobs alternatives per token, so the
            # accepted range is the min of the two — never silently fewer
            # than the request asked for
            engine_k = getattr(self.card, "num_top_logprobs", 20)
            logprobs = min(logprobs, 20, engine_k)
        # guided decoding. A FORCED tool call (tool_choice 'required' /
        # named) is the stronger contract and wins over response_format —
        # and its validation (unknown function, required-without-tools ->
        # 400) runs regardless. A tool's own parameter schema may use
        # keywords the grammar cannot enforce; degrade its arguments to
        # any-object rather than rejecting the user's tools (unlike
        # response_format, that schema is OURS, not the client's explicit
        # ask).
        guided = None
        if isinstance(req, ChatCompletionRequest):
            from dynamo_tpu.preprocessor.tools import (
                degrade_tool_spec, forced_tool_guided_spec)
            forced = forced_tool_guided_spec(req.tools, req.tool_choice)
            if forced is not None:
                try:
                    _validate_guided_spec(forced)
                except ValueError:
                    forced = degrade_tool_spec(forced)
                    _validate_guided_spec(forced)
                guided = forced
            else:
                # response_format: the client's own schema — bad specs
                # 400 here instead of erroring the worker stream
                guided = req.guided_spec()
                if guided is not None:
                    _validate_guided_spec(guided)
        sampling = SamplingOptions(
            temperature=req.temperature,
            top_p=req.top_p,
            top_k=req.top_k,
            frequency_penalty=req.frequency_penalty,
            presence_penalty=req.presence_penalty,
            repetition_penalty=req.repetition_penalty,
            logit_bias=self._validate_logit_bias(req.logit_bias),
            min_p=req.min_p,
            seed=req.seed,
            n=req.n,
            logprobs=logprobs,
            guided=guided,
        )
        return PreprocessedRequest(
            token_ids=token_ids,
            request_id=request_id or new_request_id("req"),
            model=req.model,
            stop_conditions=stop_conditions,
            sampling_options=sampling,
            eos_token_ids=list(self.card.eos_token_ids),
            mdc_sum=self.card.checksum(),
        )


class DeltaGenerator:
    """Backward pass: BackendOutput stream -> OpenAI chat-completion chunks.

    Parity: reference ``DeltaGenerator`` (``preprocessor.rs:320-424``).
    """

    def __init__(self, model: str, request_id: Optional[str] = None,
                 include_usage: bool = False):
        self.id = request_id or new_request_id()
        self.model = model
        self.created = now_unix()
        self.include_usage = include_usage
        self._first = True
        self.prompt_tokens = 0
        self.completion_tokens = 0
        self.cached_tokens: Optional[int] = None

    def chunk_from(self, out: BackendOutput) -> List[ChatCompletionChunk]:
        chunks: List[ChatCompletionChunk] = []
        self.completion_tokens += len(out.token_ids)
        if out.prompt_tokens is not None:
            self.prompt_tokens = out.prompt_tokens
        if out.completion_tokens is not None:
            self.completion_tokens = out.completion_tokens
        if out.cached_tokens is not None:
            self.cached_tokens = out.cached_tokens
        role = "assistant" if self._first else None
        self._first = False
        # emit on logprob entries too: a frame whose tokens decoded to no
        # text yet (partial UTF-8 held by the decode stream) still carries
        # per-token logprobs that must not be dropped
        if out.text or role is not None or out.logprobs_content:
            logprobs = (ChoiceLogprobs(content=out.logprobs_content)
                        if out.logprobs_content else None)
            chunks.append(ChatCompletionChunk(
                id=self.id, created=self.created, model=self.model,
                choices=[ChatChunkChoice(
                    delta=DeltaMessage(role=role, content=out.text or ""),
                    logprobs=logprobs)]))
        if out.finish_reason is not None:
            chunks.append(ChatCompletionChunk(
                id=self.id, created=self.created, model=self.model,
                choices=[ChatChunkChoice(
                    delta=DeltaMessage(),
                    finish_reason=out.finish_reason.to_openai())]))
        return chunks

    def usage_chunk(self) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id, created=self.created, model=self.model, choices=[],
            usage=Usage(
                prompt_tokens=self.prompt_tokens,
                completion_tokens=self.completion_tokens,
                total_tokens=self.prompt_tokens + self.completion_tokens,
                # OpenAI prompt-caching surface: how many prompt tokens
                # were served from the prefix cache
                prompt_tokens_details=(
                    {"cached_tokens": self.cached_tokens}
                    if self.cached_tokens is not None else None)))


__all__ = ["OpenAIPreprocessor", "DeltaGenerator",
           "ANNOTATION_FORMATTED_PROMPT", "ANNOTATION_TOKEN_IDS",
           "ANNOTATION_QUERY_INSTANCE_ID"]
