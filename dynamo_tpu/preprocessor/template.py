"""Chat prompt templating via the model's HF-style jinja2 ``chat_template``.

Parity: reference ``lib/llm/src/preprocessor/prompt/template/`` (~570 LoC,
minijinja).  HF chat templates rely on a few non-standard jinja behaviors
(``raise_exception``, ``tojson`` filter, loop variables); we provide those on
a sandboxed jinja2 environment.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jinja2
from jinja2.sandbox import ImmutableSandboxedEnvironment

DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "{{ message.role }}: {{ message.content }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}assistant:{% endif %}"
)


def _raise_exception(message: str) -> None:
    raise jinja2.exceptions.TemplateError(message)


class PromptFormatter:
    """Renders OpenAI `messages` into the model's prompt string."""

    def __init__(self, chat_template: Optional[str] = None,
                 bos_token: str = "", eos_token: str = ""):
        self._env = ImmutableSandboxedEnvironment(
            trim_blocks=True, lstrip_blocks=True, keep_trailing_newline=True)
        self._env.globals["raise_exception"] = _raise_exception
        self._template_src = chat_template or DEFAULT_CHAT_TEMPLATE
        self._template = self._env.from_string(self._template_src)
        self.bos_token = bos_token
        self.eos_token = eos_token

    def render(self, messages: List[Dict[str, Any]],
               add_generation_prompt: bool = True,
               tools: Optional[List[Dict[str, Any]]] = None,
               **extra: Any) -> str:
        ctx: Dict[str, Any] = {
            "messages": messages,
            "add_generation_prompt": add_generation_prompt,
            "bos_token": self.bos_token,
            "eos_token": self.eos_token,
        }
        if tools is not None:
            ctx["tools"] = tools
        ctx.update(extra)
        return self._template.render(**ctx)


__all__ = ["PromptFormatter", "DEFAULT_CHAT_TEMPLATE"]
