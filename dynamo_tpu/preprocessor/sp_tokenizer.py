"""Native SentencePiece tokenizer (``tokenizer.model`` files).

Parity: reference ``lib/llm/src/tokenizers/sp.rs`` (SentencePiece behind the
same tokenizer surface, selected by model-card file type,
``tokenizers.rs:586``). The ``sentencepiece`` wheel is not part of this
image, so the format is implemented natively:

- ``ModelProto`` is protobuf; the minimal wire-format reader below extracts
  the piece list (piece/score/type) and the trainer's model_type — nothing
  else is needed for inference-side encode/decode.
- **Unigram** encode is the standard Viterbi pass: best-scoring
  segmentation of the normalized text under per-piece log probabilities.
- **BPE** encode greedily merges the adjacent symbol pair whose
  concatenation is the best-scoring piece (SP stores merge priority as the
  score), which reproduces SP's order-of-merges semantics.
- Unknown characters byte-fallback to ``<0xNN>`` pieces when the model has
  them (llama-style), else the UNK id.

Normalization honors the model's ``NormalizerSpec`` flags
(add_dummy_prefix / escape_whitespaces / remove_extra_whitespaces) — the
full behavior of the identity normalizer the llama/mistral/gemma family
ships. A ``.model`` whose spec demands a precompiled charsmap or TSV rule
set (nmt_nfkc etc.) is REJECTED at load with a clear error instead of
silently mis-tokenizing (VERDICT r4 weak 7).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

_SPACE = "▁"  # ▁

# SentencePiece piece types (sentencepiece_model.proto)
_NORMAL, _UNKNOWN, _CONTROL, _USER_DEFINED, _UNUSED, _BYTE = 1, 2, 3, 4, 5, 6

_UNIGRAM, _BPE = 1, 2


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = v = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def _fields(buf: bytes):
    """Iterate (field_number, wire_type, value) over one protobuf message.
    Length-delimited values yield the raw bytes; varints the int; 32-bit
    the 4 raw bytes."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v, i = buf[i:i + 8], i + 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v, i = buf[i:i + ln], i + ln
        elif wt == 5:
            v, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield field, wt, v


class SpTokenizer:
    """SentencePiece model with the ``HfTokenizer`` call surface."""

    def __init__(self, pieces: List[Tuple[str, float, int]],
                 model_type: int = _UNIGRAM,
                 add_dummy_prefix: bool = True,
                 escape_whitespaces: bool = True,
                 remove_extra_whitespaces: bool = True):
        self._pieces = pieces
        self._model_type = model_type
        self._add_dummy_prefix = add_dummy_prefix
        self._escape_whitespaces = escape_whitespaces
        self._remove_extra_whitespaces = remove_extra_whitespaces
        # _id_of: full piece -> id map (token_to_id lookups, any type).
        # _match: pieces segmentation may produce from USER TEXT — control
        # and byte pieces excluded, or a prompt containing the literal
        # string "<s>"/"<0x41>" would encode to the special-token id
        # (prompt-boundary injection; real SentencePiece never matches
        # non-normal pieces from input).
        self._id_of: Dict[str, int] = {}
        self._match: Dict[str, int] = {}
        self._byte_id: Dict[int, int] = {}
        self.unk_id = 0
        for i, (piece, _score, ptype) in enumerate(pieces):
            if piece not in self._id_of:
                self._id_of[piece] = i
            if (ptype in (_NORMAL, _USER_DEFINED)
                    and piece not in self._match):
                self._match[piece] = i
            if ptype == _UNKNOWN:
                self.unk_id = i
            elif ptype == _BYTE and len(piece) == 6:  # "<0xNN>"
                self._byte_id[int(piece[3:5], 16)] = i
        self._max_piece_len = max((len(p) for p, _s, _t in pieces),
                                  default=1)
        # unknown-char fallback edge: scored below any real segmentation
        self._unk_penalty = min((s for _p, s, _t in pieces),
                                default=0.0) - 10.0

    # -- loading -----------------------------------------------------------

    @classmethod
    def from_file(cls, path: str) -> "SpTokenizer":
        with open(path, "rb") as f:
            return cls.from_bytes(f.read())

    @classmethod
    def from_bytes(cls, blob: bytes) -> "SpTokenizer":
        pieces: List[Tuple[str, float, int]] = []
        model_type = _UNIGRAM
        norm_name = ""
        charsmap = b""
        rule_tsv = b""
        # proto2 defaults from sentencepiece_model.proto: all three flags
        # [default = true] (llama-family models explicitly serialize
        # remove_extra_whitespaces = false)
        add_dummy_prefix = True
        escape_whitespaces = True
        remove_extra_whitespaces = True
        for field, _wt, v in _fields(blob):
            if field == 1:  # repeated SentencePiece
                piece, score, ptype = "", 0.0, _NORMAL
                for f2, wt2, v2 in _fields(v):
                    if f2 == 1:
                        piece = v2.decode("utf-8")
                    elif f2 == 2 and wt2 == 5:
                        (score,) = struct.unpack("<f", v2)
                    elif f2 == 3 and wt2 == 0:
                        ptype = v2
                pieces.append((piece, score, ptype))
            elif field == 2:  # TrainerSpec
                for f2, wt2, v2 in _fields(v):
                    if f2 == 3 and wt2 == 0:  # model_type
                        model_type = v2
            elif field == 3:  # NormalizerSpec
                for f2, wt2, v2 in _fields(v):
                    if f2 == 1 and wt2 == 2:
                        norm_name = v2.decode("utf-8", errors="replace")
                    elif f2 == 2 and wt2 == 2:
                        charsmap = v2
                    elif f2 == 3 and wt2 == 0:
                        add_dummy_prefix = bool(v2)
                    elif f2 == 4 and wt2 == 0:
                        remove_extra_whitespaces = bool(v2)
                    elif f2 == 5 and wt2 == 0:
                        escape_whitespaces = bool(v2)
                    elif f2 == 6 and wt2 == 2:
                        rule_tsv = v2
        if not pieces:
            raise ValueError("no pieces in SentencePiece model")
        # fail LOUDLY on normalizers this implementation cannot reproduce:
        # a precompiled charsmap (nmt_nfkc etc.) or a custom TSV rule set
        # rewrites input text before segmentation, so ignoring it would
        # silently mis-tokenize (VERDICT r4 weak 7). The llama/mistral/
        # gemma family ships name="identity" with no charsmap.
        if charsmap or rule_tsv or "nfkc" in norm_name.lower():
            raise ValueError(
                f"SentencePiece model requires the {norm_name or 'unknown'!r}"
                f" normalizer (precompiled charsmap: {len(charsmap)} bytes,"
                f" rule tsv: {len(rule_tsv)} bytes), which this native"
                f" backend does not implement — only identity-normalizer"
                f" models (llama/mistral/gemma family) are supported")
        return cls(pieces, model_type, add_dummy_prefix=add_dummy_prefix,
                   escape_whitespaces=escape_whitespaces,
                   remove_extra_whitespaces=remove_extra_whitespaces)

    # -- encode ------------------------------------------------------------

    def _normalize(self, text: str) -> str:
        # identity-normalizer semantics driven by the model's
        # NormalizerSpec flags (charsmap models were rejected at load):
        # escape_whitespaces turns U+0020 into ▁ (tabs/newlines/unicode
        # spaces intentionally stay — real SP byte-fallbacks them under
        # the identity normalizer, and so do we); remove_extra_whitespaces
        # strips leading/trailing spaces and collapses runs.
        if self._remove_extra_whitespaces:
            import re
            text = re.sub(" +", " ", text.strip(" "))
        if self._escape_whitespaces:
            text = text.replace(" ", _SPACE)
        if self._add_dummy_prefix and not text.startswith(_SPACE):
            text = _SPACE + text
        return text

    def _symbol_ids(self, sym: str) -> List[int]:
        """Map one unsegmentable symbol to ids (byte fallback / UNK)."""
        sid = self._match.get(sym)
        if sid is not None:
            return [sid]
        if self._byte_id:
            ids = []
            for b in sym.encode("utf-8"):
                ids.append(self._byte_id.get(b, self.unk_id))
            return ids
        return [self.unk_id]

    def _encode_unigram(self, text: str) -> List[int]:
        """Viterbi best segmentation under piece log-probs."""
        n = len(text)
        NEG = -1e18
        best = [NEG] * (n + 1)
        back: List[Optional[Tuple[int, Optional[int]]]] = [None] * (n + 1)
        best[0] = 0.0
        unk_penalty = self._unk_penalty
        for i in range(n):
            if best[i] <= NEG / 2:
                continue
            hi = min(n, i + self._max_piece_len)
            for j in range(i + 1, hi + 1):
                pid = self._match.get(text[i:j])
                if pid is None:
                    continue
                s = best[i] + self._pieces[pid][1]
                if s > best[j]:
                    best[j] = s
                    back[j] = (i, pid)
            # unknown-char fallback edge
            j = i + 1
            s = best[i] + unk_penalty
            if s > best[j]:
                best[j] = s
                back[j] = (i, None)
        out: List[int] = []
        j = n
        while j > 0:
            i, pid = back[j]  # type: ignore[misc]
            if pid is None:
                out[:0] = self._symbol_ids(text[i:j])
            else:
                out.insert(0, pid)
            j = i
        return out

    def _encode_bpe(self, text: str) -> List[int]:
        """Greedy best-scoring adjacent merges (SP BPE semantics)."""
        syms = list(text)
        while len(syms) > 1:
            best_score, best_i = None, -1
            for i in range(len(syms) - 1):
                pid = self._match.get(syms[i] + syms[i + 1])
                if pid is None:
                    continue
                s = self._pieces[pid][1]
                if best_score is None or s > best_score:
                    best_score, best_i = s, i
            if best_i < 0:
                break
            syms[best_i:best_i + 2] = [syms[best_i] + syms[best_i + 1]]
        out: List[int] = []
        for sym in syms:
            out.extend(self._symbol_ids(sym))
        return out

    def encode(self, text: str, add_special_tokens: bool = False
               ) -> List[int]:
        # BOS/EOS handling lives in the chat template; encode/decode read
        # only immutable state, so no lock (unlike the HF-object wrapper)
        del add_special_tokens
        norm = self._normalize(text)
        if self._model_type == _BPE:
            return self._encode_bpe(norm)
        return self._encode_unigram(norm)

    # -- decode ------------------------------------------------------------

    def decode(self, ids: List[int], skip_special_tokens: bool = True) -> str:
        chunks: List[bytes] = []
        for i in ids:
            if not 0 <= i < len(self._pieces):
                continue
            piece, _score, ptype = self._pieces[i]
            if ptype == _BYTE:
                chunks.append(bytes([int(piece[3:5], 16)]))
                continue
            if ptype in (_CONTROL, _UNKNOWN) and skip_special_tokens:
                continue
            chunks.append(piece.encode("utf-8"))
        text = b"".join(chunks).decode("utf-8", errors="replace")
        text = text.replace(_SPACE, " ")
        return text[1:] if text.startswith(" ") else text

    def token_to_id(self, token: str) -> Optional[int]:
        return self._id_of.get(token)

    @property
    def vocab_size(self) -> int:
        return len(self._pieces)

    def decode_stream(self, skip_special_tokens: bool = True):
        from dynamo_tpu.preprocessor.tokenizer import DecodeStream
        return DecodeStream(self, skip_special_tokens)

    def token_bytes(self) -> List[Optional[bytes]]:
        """Byte string per token id for guided decoding (None for
        control/unknown pieces) — the ``HfTokenizer.token_bytes``
        counterpart: metaspace becomes a space, ``<0xNN>`` byte-fallback
        pieces become their byte."""
        out: List[Optional[bytes]] = [None] * len(self._pieces)
        for i, (piece, _score, ptype) in enumerate(self._pieces):
            if ptype == _BYTE and len(piece) == 6:
                out[i] = bytes([int(piece[3:5], 16)])
            elif ptype in (_NORMAL, _USER_DEFINED):
                out[i] = piece.replace(_SPACE, " ").encode("utf-8")
        return out


__all__ = ["SpTokenizer"]
