"""OpenAI-request preprocessor: templating + tokenization -> PreprocessedRequest.

Parity: reference ``lib/llm/src/preprocessor.rs:92-424``
(``OpenAIPreprocessor::{new, preprocess_request}``) and
``preprocessor/prompt/template/*`` (minijinja chat templating from HF
``chat_template``).
"""

from dynamo_tpu.preprocessor.preprocessor import OpenAIPreprocessor
from dynamo_tpu.preprocessor.template import PromptFormatter
from dynamo_tpu.preprocessor.tokenizer import DecodeStream, HfTokenizer

__all__ = ["OpenAIPreprocessor", "PromptFormatter", "HfTokenizer", "DecodeStream"]
