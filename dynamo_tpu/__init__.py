"""dynamo_tpu — a TPU-native distributed LLM inference serving framework.

Capabilities (mirroring NVIDIA Dynamo, reference at /root/reference — see SURVEY.md):

- OpenAI-compatible HTTP frontend with prompt templating, tokenization and SSE
  streaming (``dynamo_tpu.http``, ``dynamo_tpu.preprocessor``).
- A distributed runtime with service discovery, leases/liveness, prefix watches,
  streaming RPC and load-aware request routing (``dynamo_tpu.runtime``).  The
  reference uses etcd + NATS + raw TCP (reference ``lib/runtime/``); we ship a
  self-contained coordinator + direct-TCP data plane with the same semantics.
- KV-cache-aware routing: radix-tree prefix indexer, event planes, load-aware
  scheduler (``dynamo_tpu.kv_router``; reference ``lib/llm/src/kv_router/``).
- A TPU model engine that owns the model loop natively via jax/XLA/Pallas:
  continuous batching, paged attention kernels, pjit/GSPMD sharding for
  TP/DP/EP/SP (``dynamo_tpu.engine``, ``dynamo_tpu.models``, ``dynamo_tpu.ops``,
  ``dynamo_tpu.parallel``).  The reference delegates this to vLLM/SGLang/TRT-LLM.
- Multi-tier KV block management (HBM -> host RAM -> disk) replacing the
  reference's KVBM + NIXL (``dynamo_tpu.block_manager``).
- Disaggregated prefill/decode, request migration, mock engine, planner.
"""

__version__ = "0.1.0"

from dynamo_tpu.tokens import TokenBlock, TokenBlockSequence, compute_block_hash_for_seq

__all__ = [
    "__version__",
    "TokenBlock",
    "TokenBlockSequence",
    "compute_block_hash_for_seq",
]
