"""TPU compute ops: RoPE, paged attention, sampling, norms.

These are the building blocks of the jax worker's model forward. Everything is
jit-compatible (static shapes, no Python control flow on traced values).
"""

from dynamo_tpu.ops.rope import apply_rope, rope_cos_sin
from dynamo_tpu.ops.attention import paged_attention, write_kv
from dynamo_tpu.ops.sampling import SamplingParamsBatch, sample_tokens

__all__ = [
    "apply_rope",
    "rope_cos_sin",
    "paged_attention",
    "write_kv",
    "SamplingParamsBatch",
    "sample_tokens",
]
