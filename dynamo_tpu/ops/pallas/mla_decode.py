"""Decode-step latent (MLA) paged attention on TPU — Pallas kernel.

DeepSeek V2/V3 decode in the **absorbed** MLA form (``models/deepseek.py``):
the paged cache stores, per token, only the compressed latent ``c_kv``
(slot 0) and the shared roped key ``k_pe`` zero-padded to the latent width
(slot 1) — ``[L, N, 2, 1, ps, dkv]`` with ``dkv = kv_lora_rank``. Scores are

    s[t] = q_lat . c_kv[t]  +  q_pe . k_pe[t]

and the attention value IS the latent itself (``out = softmax(s) . c_kv``;
the per-head ``W_UV`` re-expansion happens OUTSIDE the kernel, once, as a
dense einsum the MXU loves). The reference has no in-house MLA kernel at
all — it serves DeepSeek-R1 through SGLang's CUDA MLA path
(``components/backends/sglang/docs/dsr1-wideep-h100.md:8``); this kernel is
that role, TPU-native.

Design notes (shared with ``ops/pallas/decode.py`` — same page-DMA
machinery, same SMEM-scalar layer index so the kernel runs under the
engine's ``lax.scan`` over layers):

- One grid program per sequence; pages stream HBM -> double-buffered VMEM
  slabs in chunks of ``PAGES_PER_CHUNK``, one DMA descriptor per page (a
  page's ``[2, 1, ps, dkv]`` slab is contiguous, K-rope and latent
  together).
- The two query parts enter pre-scaled and stacked as ``q2 [B, 2, nh,
  dkv]`` (``q_pe`` zero-padded to ``dkv``): the slot axis of the cache
  (latent / padded rope key) batches against the slot axis of the query, so
  the score is ONE batched ``dot_general`` over the slab plus an add —
  zero-padding makes ``q_pe_pad . k_pe_pad == q_pe . k_pe`` exactly.
- Flash-style online softmax in f32; the PV product contracts the
  positions against the SLOT-0 slab only (the latent is the value).
- GQA degenerates to Hkv=1 here, so the head axis (nh up to 128 on V3) is
  the matmul M dim — MXU-shaped without transposes.

Alignment: ``dkv % 128 == 0`` (V2/V3 real checkpoints: 512) and
``page_size % 8 == 0``; tests run interpret mode on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.pallas.decode import (
    NEG_INF,
    PAGES_PER_CHUNK,
    _resolve_interpret,
)


def supports(kv_lora_rank: int, page_size: int) -> bool:
    """Geometries this kernel can lower for (else use the XLA path)."""
    return kv_lora_rank % 128 == 0 and page_size % 8 == 0


def _mla_decode_kernel(q2_ref, kv_hbm, layer_ref, table_ref, lens_ref,
                       out_ref, buf, sem, *, page_size: int, chunk: int):
    """One program per sequence: stream latent page chunks, online-softmax
    attend in latent space.

    q2_ref:  [1, 2, nh, dkv] — slot 0 = absorbed latent query, slot 1 =
             roped query zero-padded to dkv; both pre-scaled by sm_scale.
    kv_hbm:  [L, N, 2, 1, ps, dkv] stacked latent cache (memory_space ANY).
    buf:     [2, 2, 1, chunk*ps, dkv] double-buffered slabs (cache slot
             axis kept: 0 = latent, 1 = padded rope key; same slab DMA
             pattern as decode.py with Hkv == 1).
    sem:     [2, chunk] DMA semaphores (slot, page-in-chunk).
    """
    b = pl.program_id(0)
    layer = layer_ref[0]
    ctx = lens_ref[b]
    num_pages = jax.lax.div(ctx + page_size - 1, page_size)
    num_chunks = jax.lax.div(num_pages + chunk - 1, chunk)

    nh, dkv = q2_ref.shape[2], q2_ref.shape[3]
    q2 = q2_ref[0]                                         # [2, nh, dkv]

    P = table_ref.shape[1]

    def page_dma(slot, i, j):
        # One descriptor per page: the [2, 1, ps, dkv] slab lands in both
        # slot rows of the chunk buffer at this page's position range.
        # Pad pages of a partial last chunk clamp to a real table entry
        # (masked to zero weight later; see decode.py's rationale).
        jj = jnp.minimum(j, P - 1)
        return pltpu.make_async_copy(
            kv_hbm.at[layer, table_ref[b, jj]],
            buf.at[slot, :, :, pl.ds(i * page_size, page_size)],
            sem.at[slot, i])

    def start_chunk(slot, c):
        def start_one(i, _):
            page_dma(slot, i, c * chunk + i).start()
            return 0

        jax.lax.fori_loop(0, chunk, start_one, 0, unroll=True)

    def wait_chunk(slot, c):
        def wait_one(i, _):
            page_dma(slot, i, c * chunk + i).wait()
            return 0

        jax.lax.fori_loop(0, chunk, wait_one, 0, unroll=True)

    span = chunk * page_size
    start_chunk(0, 0)

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < num_chunks)
        def _():
            start_chunk(jax.lax.rem(c + 1, 2), c + 1)

        wait_chunk(slot, c)
        kv = buf[slot, :, 0]                               # [2, span, dkv]

        # scores [2, nh, span]: batch the slot axis, contract dkv — slot 0
        # is q_lat . c_kv, slot 1 is q_pe_pad . k_pe_pad (== q_pe . k_pe)
        s2 = jax.lax.dot_general(
            q2, kv, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        s = s2[0] + s2[1]                                  # [nh, span]
        pos = c * span + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < ctx, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                             # [nh, span]
        scale = jnp.exp(m - m_new)                         # [nh, 1]
        l = l * scale + jnp.sum(p, axis=-1, keepdims=True)
        # PV [nh, dkv]: the latent slab IS the value
        pv = jax.lax.dot_general(
            p.astype(kv.dtype), kv[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc = acc * scale + pv
        return m_new, l, acc

    # chunk 0 always holds position 0 (no sliding window in MLA models),
    # so m never stays at -inf and needs no fully-masked-row guard
    m0 = jnp.full((nh, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((nh, 1), jnp.float32)
    acc0 = jnp.zeros((nh, dkv), jnp.float32)
    _m, l, acc = jax.lax.fori_loop(0, num_chunks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-20)
    out_ref[0] = out.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _mla_paged_decode(q2, kv_pages, layer_idx, page_table, total_lens,
                      sm_scale: float, interpret: bool = False):
    B, _two, nh, dkv = q2.shape
    _L, _N, _2, _one, page_size, _ = kv_pages.shape
    P = page_table.shape[1]
    chunk = min(PAGES_PER_CHUNK, P)

    kernel = functools.partial(_mla_decode_kernel, page_size=page_size,
                               chunk=chunk)
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, 2, nh, dkv), lambda b: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, nh, dkv), lambda b: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, 2, 1, chunk * page_size, dkv), kv_pages.dtype),
            pltpu.SemaphoreType.DMA((2, chunk)),
        ],
        out_shape=jax.ShapeDtypeStruct((B, nh, dkv), jnp.float32),
        interpret=interpret,
    )((q2 * sm_scale).astype(kv_pages.dtype), kv_pages, layer_idx,
      page_table, total_lens)


def mla_paged_decode_stacked(q_lat: jnp.ndarray, q_pe: jnp.ndarray,
                             pages: jnp.ndarray, layer_idx,
                             page_table: jnp.ndarray,
                             total_lens: jnp.ndarray, sm_scale: float,
                             interpret: bool | None = None) -> jnp.ndarray:
    """Latent paged decode attention over the stacked MLA cache.

    q_lat:      [B, 1, nh, dkv] absorbed latent queries (f32 ok; cast in)
    q_pe:       [B, 1, nh, dr] roped queries
    pages:      [L, N, 2, 1, ps, dkv] latent cache (slot 0 = c_kv, slot 1
                = k_pe zero-padded to dkv)
    layer_idx:  scalar int (python int or traced scan index)
    page_table: [B, P]; total_lens: [B] (context incl. the query token)

    Returns the latent attention output [B, 1, nh, dkv] in f32 — feed it
    to ``models.deepseek._expand_and_project`` for the W_UV re-expansion.
    """
    B, S, nh, dkv = q_lat.shape
    if S != 1:
        raise ValueError(f"MLA decode kernel requires S=1, got S={S}")
    dr = q_pe.shape[-1]
    q_pe_pad = jnp.pad(q_pe, ((0, 0), (0, 0), (0, 0), (0, dkv - dr)))
    q2 = jnp.stack([q_lat[:, 0], q_pe_pad[:, 0]], axis=1)  # [B, 2, nh, dkv]
    layer = jnp.asarray(layer_idx, jnp.int32).reshape(1)
    out = _mla_paged_decode(q2, pages, layer,
                            page_table.astype(jnp.int32),
                            total_lens.astype(jnp.int32), sm_scale,
                            interpret=_resolve_interpret(interpret))
    return out[:, None]                                    # [B, 1, nh, dkv]


def mla_paged_decode_layer(q_lat: jnp.ndarray, q_pe: jnp.ndarray,
                           kv_layer: jnp.ndarray, page_table: jnp.ndarray,
                           total_lens: jnp.ndarray, sm_scale: float,
                           interpret: bool | None = None) -> jnp.ndarray:
    """Per-layer-buffer variant (the ``pallas_unrolled`` engine path):
    ``kv_layer`` is one layer's ``[N, 2, 1, ps, dkv]`` buffer."""
    return mla_paged_decode_stacked(q_lat, q_pe, kv_layer[None], 0,
                                    page_table, total_lens, sm_scale,
                                    interpret=interpret)


__all__ = ["mla_paged_decode_stacked", "mla_paged_decode_layer", "supports"]
