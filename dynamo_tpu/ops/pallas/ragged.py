"""Ragged mixed-batch paged attention on TPU — one kernel for prefill
chunks AND decode steps (the Ragged Paged Attention kernel shape,
PAPERS.md).

The engine's mixed step packs prefill chunks and single-token decode rows
into one ``[B, S]`` dispatch (``engine/scheduler.MixedStepBatch``). The
prefill kernel (``ops/pallas/prefill.py``) already computes such a batch
correctly — pad rows mask out causally — but it pays the FULL query-block
grid for every row: a decode row (1 real query token) costs the same
``ceil(S/SB)`` programs as a 512-token chunk, each streaming the row's
whole paged context. This kernel is the prefill kernel plus the ragged
row descriptors:

- Per row, ``q_len = ctx - q_start`` (positions are row-contiguous and end
  at ``ctx - 1``, so the descriptor rides the arrays the engine already
  ships — no new operands).
- Grid programs wholly past their row's real queries
  (``j*SB >= q_len``) SKIP everything — no page DMAs, no matmuls. On the
  sequential TPU grid a decode row costs ONE program streaming its own
  context instead of ``ceil(S/SB)``; mixed batches run at ~ragged cost,
  not padded cost.
- Everything else (page-streaming double buffer, SMEM layer index for the
  ``lax.scan`` forward, causal online softmax in f32, window/softcap) is
  the prefill kernel's machinery unchanged.

The pure-JAX flattened-layout reference lives in
``ops.attention.ragged_paged_attention`` (the CPU-test oracle); CPU tests
of this kernel run in interpreter mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.pallas.decode import _resolve_interpret, supports  # noqa: F401
from dynamo_tpu.ops.pallas.prefill import (
    PAGES_PER_CHUNK,
    _fit_query_block,
)

NEG_INF = -1e30


def _ragged_kernel(q_ref, kv_hbm, layer_ref, window_ref, table_ref,
                   qstart_ref, lens_ref, out_ref, buf, sem, *,
                   page_size: int, n_kv: int, chunk: int, q_block: int,
                   softcap: float):
    """One program per (row, query-block); blocks wholly past the row's
    ragged ``q_len`` degenerate to near no-ops: the chunk loop's trip
    count collapses to ZERO (so no page DMAs are armed — nothing for the
    next program's semaphores to trip over — and no matmuls run), leaving
    only the cheap vector-unit epilogue writing zeros into the pad block.
    Mosaic cannot lower the layout transposes inside a ``pl.when``
    branch, so the skip is expressed through the loop bounds instead of a
    guarded body."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    layer = layer_ref[0]
    win = window_ref[0]
    ctx = lens_ref[b]
    q_start = qstart_ref[b]
    # the ragged descriptor: row b contributes q_len real query tokens at
    # positions q_start .. ctx-1 (a decode row is q_len == 1)
    q_len = ctx - q_start
    active = j * q_block < q_len

    SB = q_block
    Hq, Dh = q_ref.shape[2], q_ref.shape[3]
    G = Hq // n_kv
    span = chunk * page_size

    # kv this block can see: causal bound clamped to the live context
    block_last = q_start + (j + 1) * SB - 1
    visible = jnp.minimum(ctx, block_last + 1)
    num_chunks = jnp.maximum(jax.lax.div(visible + span - 1, span), 1)
    block_first = q_start + j * SB
    first_pos = jnp.where(win > 0,
                          jnp.maximum(block_first - win + 1, 0), 0)

    P = table_ref.shape[1]

    def page_dma(slot, i, c):
        jj = jnp.minimum(c * chunk + i, P - 1)
        return pltpu.make_async_copy(
            kv_hbm.at[layer, table_ref[b, jj]],
            buf.at[slot, :, :, pl.ds(i * page_size, page_size)],
            sem.at[slot, i])

    def start_chunk(slot, c):
        def start_one(i, _):
            page_dma(slot, i, c).start()
            return 0

        jax.lax.fori_loop(0, chunk, start_one, 0, unroll=True)

    def wait_chunk(slot, c):
        def wait_one(i, _):
            page_dma(slot, i, c).wait()
            return 0

        jax.lax.fori_loop(0, chunk, wait_one, 0, unroll=True)

    c0 = jnp.minimum(jax.lax.div(first_pos, span), num_chunks - 1)
    # THE ragged skip: an inactive block runs the chunk loop zero times
    n_end = jnp.where(active, num_chunks, c0)

    @pl.when(active)
    def _():
        start_chunk(jax.lax.rem(c0, 2), c0)

    q = q_ref[0].reshape(SB, n_kv, G, Dh).transpose(1, 2, 0, 3) \
        .reshape(n_kv, G * SB, Dh)
    qpos = q_start + j * SB + jax.lax.broadcasted_iota(
        jnp.int32, (1, G, SB, 1), 2)                       # [1, G, SB, 1]

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < n_end)
        def _():
            start_chunk(jax.lax.rem(c + 1, 2), c + 1)

        wait_chunk(slot, c)
        k = buf[slot, 0]                                   # [Hkv, span, Dh]
        v = buf[slot, 1]

        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [Hkv, G*SB, span]
        if softcap:
            s = jnp.tanh(s / softcap) * softcap
        s4 = s.reshape(n_kv, G, SB, span)
        t_pos = c * span + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, 1, span), 3)
        # pad rows of the block (local row >= q_len - j*SB) carry
        # qpos >= ctx; the `t_pos < ctx` bound keeps their work finite
        # and their outputs are never read downstream (the engine
        # samples at each row's last REAL token only)
        mask = (t_pos <= qpos) & (t_pos < ctx)             # [1, G, SB, span]
        mask &= (win <= 0) | (t_pos > qpos - win)
        s4 = jnp.where(mask, s4, NEG_INF)
        s = s4.reshape(n_kv, G * SB, span)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))        # [Hkv, G*SB]
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where((m_new > NEG_INF / 2)[..., None], p, 0.0)
        scale = jnp.where(m > NEG_INF / 2, jnp.exp(m - m_new), 0.0)
        l_new = l * scale + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [Hkv, G*SB, Dh]
        acc = acc * scale[..., None] + pv
        return m_new, l_new, acc

    m0 = jnp.full((n_kv, G * SB), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n_kv, G * SB), jnp.float32)
    acc0 = jnp.zeros((n_kv, G * SB, Dh), jnp.float32)
    _m, l, acc = jax.lax.fori_loop(c0, n_end, body, (m0, l0, acc0))
    # inactive blocks kept acc == 0, l == 0: the epilogue writes zeros
    # into the pad block — deterministic output for the parity oracle
    out = acc / jnp.maximum(l, 1e-20)[..., None]           # [Hkv, G*SB, Dh]
    out = out.reshape(n_kv, G, SB, Dh).transpose(2, 0, 1, 3) \
        .reshape(SB, Hq, Dh)
    out_ref[0] = out.astype(out_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("sm_scale", "softcap", "interpret"))
def _ragged_mixed(q, kv_pages, layer_idx, window, page_table, q_start,
                  total_lens, sm_scale: float, softcap: float = 0.0,
                  interpret: bool = False):
    B, S, Hq, Dh = q.shape
    _L, _N, _two, Hkv, page_size, _ = kv_pages.shape
    P = page_table.shape[1]
    chunk = min(PAGES_PER_CHUNK, P)
    span = chunk * page_size
    slab_bytes = 2 * 2 * Hkv * span * Dh * kv_pages.dtype.itemsize
    SB = _fit_query_block(S, Hq, Dh, span, slab_bytes)
    n_q_blocks = -(-S // SB)

    kernel = functools.partial(_ragged_kernel, page_size=page_size,
                               n_kv=Hkv, chunk=chunk, q_block=SB,
                               softcap=softcap)
    return pl.pallas_call(
        kernel,
        grid=(B, n_q_blocks),
        in_specs=[
            pl.BlockSpec((1, SB, Hq, Dh), lambda b, j: (b, j, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, SB, Hq, Dh), lambda b, j: (b, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, 2, Hkv, chunk * page_size, Dh), kv_pages.dtype),
            pltpu.SemaphoreType.DMA((2, chunk)),
        ],
        out_shape=jax.ShapeDtypeStruct((B, S, Hq, Dh), q.dtype),
        interpret=interpret,
    )((q * sm_scale).astype(q.dtype), kv_pages, layer_idx, window,
      page_table, q_start, total_lens)


def ragged_mixed_attention_stacked(q: jnp.ndarray, pages: jnp.ndarray,
                                   layer_idx, page_table: jnp.ndarray,
                                   positions: jnp.ndarray,
                                   total_lens: jnp.ndarray, sm_scale: float,
                                   window=None, softcap=None,
                                   interpret: bool | None = None
                                   ) -> jnp.ndarray:
    """Drop-in for ``ops.attention.paged_attention`` on MIXED steps
    (S > 1, rows ragged: each row's real query tokens are its leading
    ``total_lens[b] - positions[b, 0]`` slots — a prefill chunk, or a
    single decode token).

    q:          [B, S, Hq, Dh] (S = padded widest chunk in the batch)
    pages:      [L, N, 2, Hkv, page_size, Dh]
    layer_idx:  scalar int (python int or traced scan index)
    page_table: [B, P]
    positions:  [B, S] absolute positions (row-contiguous; only column 0
                enters the kernel — the ragged length is derived as
                ``total_lens - positions[:, 0]``)
    total_lens: [B] context length including the new tokens
    window:     optional scalar (python int or traced, 0 = unlimited)
    softcap:    optional STATIC float (gemma logit soft-capping)
    """
    layer = jnp.asarray(layer_idx, jnp.int32).reshape(1)
    win = (jnp.zeros((1,), jnp.int32) if window is None
           else jnp.asarray(window, jnp.int32).reshape(1))
    return _ragged_mixed(q, pages, layer, win,
                         page_table.astype(jnp.int32),
                         positions[:, 0].astype(jnp.int32),
                         total_lens.astype(jnp.int32), sm_scale,
                         softcap=float(softcap or 0.0),
                         interpret=_resolve_interpret(interpret))


# the family forwards consult these markers before handing an impl their
# per-layer window/softcap kwargs (see ops/pallas/prefill.py)
ragged_mixed_attention_stacked.supports_window_softcap = True
ragged_mixed_attention_stacked.pallas_paged_kernel = True


__all__ = ["ragged_mixed_attention_stacked", "supports"]
