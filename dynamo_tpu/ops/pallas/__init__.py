"""Pallas TPU kernels for the serving hot loops.

- ``decode.paged_decode_attention`` — decode-step attention that reads KV
  pages directly from HBM (fuses away the XLA path's [B, T, Hkv, Dh] gather; page-major slabs, one DMA per page).

The XLA implementations in ``dynamo_tpu.ops.attention`` remain the portable
reference (CPU tests) and the prefill path.
"""

from dynamo_tpu.ops.pallas.decode import paged_decode_attention

__all__ = ["paged_decode_attention"]
