"""Pallas TPU kernels for the serving hot loops.

- ``decode.paged_decode_attention`` — decode-step attention that reads KV
  pages directly from HBM (fuses away the XLA path's [B, T, Hkv, Dh]
  gather; page-major slabs, one DMA per page), per-layer cache buffers.
- ``decode.paged_decode_attention_stacked`` — same kernel over the STACKED
  cache with an SMEM layer index: usable inside a ``lax.scan`` over layers,
  so the TPU decode step compiles one layer body instead of L.

The XLA implementations in ``dynamo_tpu.ops.attention`` remain the portable
reference (CPU tests).
"""

from dynamo_tpu.ops.pallas.decode import (
    paged_decode_attention,
    paged_decode_attention_stacked,
)
from dynamo_tpu.ops.pallas.mla_decode import (
    mla_paged_decode_layer,
    mla_paged_decode_stacked,
)
from dynamo_tpu.ops.pallas.mla_prefill import mla_paged_prefill_stacked
from dynamo_tpu.ops.pallas.ragged import ragged_mixed_attention_stacked

__all__ = ["paged_decode_attention", "paged_decode_attention_stacked",
           "mla_paged_decode_layer", "mla_paged_decode_stacked",
           "mla_paged_prefill_stacked", "ragged_mixed_attention_stacked"]
