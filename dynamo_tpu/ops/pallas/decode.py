"""Decode-step paged attention on TPU.

Replaces the reference's CUDA paged-attention kernels (vLLM's, reached via
``components/backends/vllm``) with the TPU-native equivalent: jax's public
Pallas paged-attention kernel
(``jax.experimental.pallas.ops.tpu.paged_attention``), which DMAs exactly the
pages named in the page table from HBM into VMEM and runs flash-style online
softmax per KV head — no [B, T, Hkv, Dh] materialization, HBM traffic is the
live context only.

Our cache layout ``[2, Hkv, N, page_size, Dh]`` is the kernel's native
``k_pages``/``v_pages`` layout, so the call is zero-copy.
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def _pick_block(pages_per_seq: int, want: int = 8) -> int:
    """Largest divisor of pages_per_seq that is <= want (kernel requires the
    compute block to divide the page-table width)."""
    for b in range(min(want, pages_per_seq), 0, -1):
        if pages_per_seq % b == 0:
            return b
    return 1


def paged_decode_attention(q: jnp.ndarray, kv_layer: jnp.ndarray,
                           page_table: jnp.ndarray, positions: jnp.ndarray,
                           total_lens: jnp.ndarray, sm_scale: float
                           ) -> jnp.ndarray:
    """Drop-in for ``ops.attention.paged_attention_layer`` when S == 1.

    q:          [B, 1, Hq, Dh]
    kv_layer:   [2, Hkv, N, page_size, Dh]
    page_table: [B, P]
    total_lens: [B] context length including the query token
    """
    B, S, Hq, Dh = q.shape
    if S != 1:
        raise ValueError(f"decode kernel requires S=1, got S={S}")
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention as kernel,
    )
    qs = (q[:, 0] * sm_scale).astype(q.dtype)          # [B, Hq, Dh]
    block = _pick_block(page_table.shape[1])
    out = kernel(qs, kv_layer[0], kv_layer[1], total_lens, page_table,
                 pages_per_compute_block=block)
    return out[:, None].astype(q.dtype)                # [B, 1, Hq, Dh]


__all__ = ["paged_decode_attention"]
