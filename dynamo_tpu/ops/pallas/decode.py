"""Decode-step paged attention on TPU — our own Pallas kernel.

Replaces the reference's CUDA paged-attention kernels (vLLM's, reached via
``components/backends/vllm``) with a TPU-native Pallas kernel. (jax ships a
paged-attention kernel under ``jax.experimental``, but its output block
specs fail Mosaic's tiling checks under jax 0.9 — and owning the kernel
lets us fuse exactly our cache layout.)

Design (one grid program per sequence, chunked page streaming):

- The page table and context lengths enter as plain SMEM-resident inputs.
  NOT ``PrefetchScalarGridSpec``: on this toolchain the scalar-prefetch
  grid machinery costs ~1.7 ms per invocation (measured 80x slowdown on an
  otherwise identical kernel); plain SMEM inputs issue dynamic-index DMAs
  at sub-microsecond cost.
- K/V pages stay in HBM (``memory_space=ANY``) in the page-major per-layer
  layout ``[N, 2, Hkv, ps, Dh]`` — one page is one contiguous slab with K
  and V for all heads, so each page is fetched by ONE DMA descriptor.
  (Per-layer buffers, not a layer-slice of a stacked cache: XLA
  defensively copies a stacked cache around the opaque custom call, ~10x.)
  Pages are
  streamed in chunks of ``PAGES_PER_CHUNK`` into a double-buffered VMEM
  slab, the next chunk's burst issued while the current chunk computes.
- Flash-style online softmax in f32 over a ``lax.fori_loop`` whose trip
  count is the sequence's true chunk count (short sequences stop early).
  Pad pages of the last chunk / stale slab contents are masked to -inf
  before the softmax update, so they contribute zero.
- GQA without transposes: scores and the PV product are batched
  ``dot_general``s over the kv-head axis with the chunk/slot dims left in
  place (``[Hkv,G,Dh] x [C,Hkv,ps,Dh] -> [Hkv,G,C,ps]``), bf16 in, f32
  accumulation on the MXU.

Alignment: Mosaic tiles the two minor dims to (8, 128) — the kernel
requires ``head_dim % 128 == 0`` (Llama-3-8B / 3.2-3B class; the engine
falls back to the XLA gather path otherwise) and ``page_size % 8 == 0``.

CPU tests run the same kernel in interpreter mode against the XLA path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# pages per streamed chunk: with 16-token pages this is 128 positions per
# burst — one chunk's matmul fills the MXU's 128 lanes
PAGES_PER_CHUNK = 8


def supports(head_dim: int, page_size: int) -> bool:
    """Geometries this kernel can lower for (else use the XLA path)."""
    return head_dim % 128 == 0 and page_size % 8 == 0


def _resolve_interpret(interpret) -> bool:
    """``None`` -> interpreter mode off-TPU (so CPU tests exercise the
    engine's exact TPU code path), native Mosaic on TPU."""
    if interpret is not None:
        return interpret
    return jax.default_backend() not in ("tpu", "axon")


def _decode_kernel(q_ref, kv_hbm, layer_ref, window_ref, table_ref,
                   lens_ref, out_ref, buf, sem, *, page_size: int,
                   n_kv: int, chunk: int, softcap: float):
    """One program per sequence: stream page chunks, online-softmax attend.

    kv_hbm is the STACKED cache ``[L, N, 2, Hkv, ps, Dh]`` and ``layer_ref``
    an SMEM scalar selecting the layer — the dynamic layer index rides the
    DMA descriptor, so the same compiled kernel serves every layer. That is
    what lets the engine run decode under ``lax.scan`` over layers (one
    compiled layer body, ~L× cheaper cold compile) instead of a python
    unroll: the kernel receives the WHOLE cache array (no layer slicing at
    the XLA level — slicing a stacked cache outside an opaque custom call
    is what forced the defensive whole-cache copies, measured ~10x).

    buf: [2, 2, Hkv, chunk*page_size, Dh] double-buffered slabs — pages DMA
    straight into their position range, so the chunk is ALREADY in the
    merged [Hkv, span, Dh] layout the matmuls want (no in-kernel transpose,
    and Mosaic's matmul only takes a single contracting dim).
    sem: [2, chunk] DMA semaphores (slot, page-in-chunk).

    ``window_ref`` (SMEM scalar, 0 = unlimited) restricts the query to the
    last ``window`` kv positions (gemma-2 alternating sliding-window
    layers) — chunks wholly before the window are never even DMA'd.
    ``softcap`` (static; 0 = disabled) applies gemma-style logit
    soft-capping ``cap * tanh(s / cap)`` before the softmax.
    """
    b = pl.program_id(0)
    layer = layer_ref[0]
    win = window_ref[0]
    ctx = lens_ref[b]
    num_pages = jax.lax.div(ctx + page_size - 1, page_size)
    num_chunks = jax.lax.div(num_pages + chunk - 1, chunk)
    # first kv position the (single, at ctx-1) query can see
    first_pos = jnp.where(win > 0, jnp.maximum(ctx - win, 0), 0)

    Hq, Dh = q_ref.shape[1], q_ref.shape[2]
    G = Hq // n_kv
    q = q_ref[0].reshape(n_kv, G, Dh)                      # [Hkv, G, Dh]

    P = table_ref.shape[1]

    def page_dma(slot, i, j):
        # One descriptor fetches the page's full slab (K+V, all heads) into
        # the chunk slab's position range for this page. Pad pages of a
        # partial last chunk DMA a clamped (real) table entry instead of
        # branching: conditionals cost more than the extra ~page of
        # bandwidth, and the slab must hold FINITE memory everywhere — the
        # softmax masks pad positions to weight 0, but 0 x garbage-NaN
        # would still poison the PV matmul.
        jj = jnp.minimum(j, P - 1)
        return pltpu.make_async_copy(
            kv_hbm.at[layer, table_ref[b, jj]],
            buf.at[slot, :, :, pl.ds(i * page_size, page_size)],
            sem.at[slot, i])

    def start_chunk(slot, c):
        def start_one(i, _):
            page_dma(slot, i, c * chunk + i).start()
            return 0

        jax.lax.fori_loop(0, chunk, start_one, 0, unroll=True)

    def wait_chunk(slot, c):
        def wait_one(i, _):
            page_dma(slot, i, c * chunk + i).wait()
            return 0

        jax.lax.fori_loop(0, chunk, wait_one, 0, unroll=True)

    span = chunk * page_size
    c0 = jax.lax.div(first_pos, span)  # skip chunks before the window
    start_chunk(jax.lax.rem(c0, 2), c0)

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < num_chunks)
        def _():
            start_chunk(jax.lax.rem(c + 1, 2), c + 1)

        wait_chunk(slot, c)
        k = buf[slot, 0]                                   # [Hkv, span, Dh]
        v = buf[slot, 1]

        # scores [Hkv, G, span]: batch Hkv, contract Dh
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        if softcap:
            s = jnp.tanh(s / softcap) * softcap
        pos = c * span + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where((pos < ctx) & (pos >= first_pos), s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))        # [Hkv, G]
        p = jnp.exp(s - m_new[..., None])
        # a fully-masked first chunk would leave m at -inf and leak
        # exp(0)=1 weights — zero those rows (cannot happen without a
        # window, where chunk c0=0 always holds position 0)
        p = jnp.where((m_new > NEG_INF / 2)[..., None], p, 0.0)
        scale = jnp.where(m > NEG_INF / 2, jnp.exp(m - m_new), 0.0)
        l = l * scale + jnp.sum(p, axis=-1)
        # PV [Hkv, G, Dh]: batch Hkv, contract span
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc = acc * scale[..., None] + pv
        return m_new, l, acc

    m0 = jnp.full((n_kv, G), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n_kv, G), jnp.float32)
    acc0 = jnp.zeros((n_kv, G, Dh), jnp.float32)
    _m, l, acc = jax.lax.fori_loop(c0, num_chunks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[0] = out.reshape(Hq, Dh).astype(out_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("sm_scale", "softcap", "interpret"))
def _paged_decode(q, kv_pages, layer_idx, window, page_table, total_lens,
                  sm_scale: float, softcap: float = 0.0,
                  interpret: bool = False):
    B, Hq, Dh = q.shape
    _L, _N, _two, Hkv, page_size, _ = kv_pages.shape
    P = page_table.shape[1]
    chunk = min(PAGES_PER_CHUNK, P)

    kernel = functools.partial(_decode_kernel, page_size=page_size,
                               n_kv=Hkv, chunk=chunk, softcap=softcap)
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, Dh), lambda b: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, Hq, Dh), lambda b: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, 2, Hkv, chunk * page_size, Dh), kv_pages.dtype),
            pltpu.SemaphoreType.DMA((2, chunk)),
        ],
        out_shape=jax.ShapeDtypeStruct((B, Hq, Dh), q.dtype),
        interpret=interpret,
    )((q * sm_scale).astype(q.dtype), kv_pages, layer_idx, window,
      page_table, total_lens)


def paged_decode_attention(q: jnp.ndarray, kv_layer: jnp.ndarray,
                           page_table: jnp.ndarray, positions: jnp.ndarray,
                           total_lens: jnp.ndarray, sm_scale: float,
                           interpret: bool | None = None) -> jnp.ndarray:
    """Drop-in for ``ops.attention.paged_attention_layer`` when S == 1.

    q:          [B, 1, Hq, Dh]
    kv_layer:   [N, 2, Hkv, page_size, Dh] (page-major slabs)
    page_table: [B, P]
    total_lens: [B] context length including the query token
    """
    B, S, Hq, Dh = q.shape
    if S != 1:
        raise ValueError(f"decode kernel requires S=1, got S={S}")
    out = _paged_decode(q[:, 0], kv_layer[None],
                        jnp.zeros((1,), jnp.int32),
                        jnp.zeros((1,), jnp.int32),
                        page_table.astype(jnp.int32),
                        total_lens.astype(jnp.int32), sm_scale,
                        interpret=_resolve_interpret(interpret))
    return out[:, None]                                    # [B, 1, Hq, Dh]


def paged_decode_attention_stacked(q: jnp.ndarray, pages: jnp.ndarray,
                                   layer_idx, page_table: jnp.ndarray,
                                   positions: jnp.ndarray,
                                   total_lens: jnp.ndarray, sm_scale: float,
                                   window=None, softcap=None,
                                   interpret: bool | None = None
                                   ) -> jnp.ndarray:
    """Drop-in for ``ops.attention.paged_attention`` when S == 1: the whole
    stacked cache enters the kernel and the (possibly TRACED) ``layer_idx``
    selects the layer inside the DMA — usable as the attention op inside a
    ``lax.scan`` over layers, giving one compiled decode layer body.

    q:          [B, 1, Hq, Dh]
    pages:      [L, N, 2, Hkv, page_size, Dh] (page-major slabs)
    layer_idx:  scalar int (python int or traced scan index)
    page_table: [B, P]
    total_lens: [B] context length including the query token
    window:     optional scalar (python int or traced, 0 = unlimited) —
                gemma-2 alternating sliding-window layers
    softcap:    optional STATIC float (gemma logit soft-capping)
    """
    B, S, Hq, Dh = q.shape
    if S != 1:
        raise ValueError(f"decode kernel requires S=1, got S={S}")
    layer = jnp.asarray(layer_idx, jnp.int32).reshape(1)
    win = (jnp.zeros((1,), jnp.int32) if window is None
           else jnp.asarray(window, jnp.int32).reshape(1))
    out = _paged_decode(q[:, 0], pages, layer, win,
                        page_table.astype(jnp.int32),
                        total_lens.astype(jnp.int32), sm_scale,
                        softcap=float(softcap or 0.0),
                        interpret=_resolve_interpret(interpret))
    return out[:, None]                                    # [B, 1, Hq, Dh]


# marker the gemma forward checks before handing this impl its per-layer
# window / softcap kwargs
paged_decode_attention_stacked.supports_window_softcap = True
# marker for families whose attention the GQA kernels cannot run directly
# (deepseek MLA): a passed impl carrying it opts the family into its own
# Pallas kernels (ops/pallas/mla_decode.py) instead of being called
paged_decode_attention.pallas_paged_kernel = True
paged_decode_attention_stacked.pallas_paged_kernel = True


__all__ = ["paged_decode_attention", "paged_decode_attention_stacked",
           "supports"]
