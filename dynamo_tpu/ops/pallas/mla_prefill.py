"""Chunked-prefill latent (MLA) paged attention on TPU — Pallas kernel.

The S>1 counterpart of ``ops/pallas/mla_decode.py``: DeepSeek V2/V3
prefill in the absorbed MLA form against the 2-slot latent page cache
``[L, N, 2, 1, ps, dkv]``. Same flash structure as the GQA prefill kernel
(``ops/pallas/prefill.py`` — page-chunk streaming into double-buffered
VMEM slabs, causal online softmax over absolute positions, SMEM layer
index so the kernel runs under the engine's layer scan), with the MLA
score/value substitution:

    s[q, t] = q_lat[q] . c_kv[t]  +  q_pe[q] . k_pe[t]   (slot-batched dot)
    out[q]  = softmax(s)[q] . c_kv                        (value = latent)

Shape strategy: MLA has ONE kv head but many query heads against a WIDE
latent (V3: nh=128, dkv=512), so the per-program working set scales with
``nh * SB * dkv`` — the query block SB adapts (``_query_block``) to keep
q2 + f32 accumulator + kv slabs inside VMEM while the matmul M dim
(``nh*SB`` rows) stays MXU-wide. No sliding window / softcap: no MLA
family uses them.

Reference role: SGLang's CUDA MLA prefill kernels behind the DSR1 recipe
(``components/backends/sglang/docs/dsr1-wideep-h100.md``); the XLA
blockwise latent path (``models/deepseek._mla_attend_blockwise``) remains
the portable fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.pallas.decode import _resolve_interpret
from dynamo_tpu.ops.pallas.mla_decode import supports  # noqa: F401
from dynamo_tpu.ops.pallas.prefill import shrink_query_block

NEG_INF = -1e30

PAGES_PER_CHUNK = 8

# target query rows per program: chosen so nh * SB stays a wide matmul M
# dim while the f32 accumulator [nh*SB, dkv] (the dominant buffer at V3
# geometry) stays a few MB of VMEM
_TARGET_M_ROWS = 2048


def _query_block(S: int, nh: int, dkv: int, span: int,
                 slab_bytes: int) -> int:
    """Query block bounded by MXU row target AND the scoped-VMEM stack.

    The stack estimator mirrors ``prefill._fit_query_block``'s on-chip
    calibration (v5e measured Mosaic temporaries at ~2× the naive
    accounting): per query row, f32 score/prob/exp temporaries cost
    ``~22*span`` bytes (the slot-batched s2 is [2, rows, span]) and the
    f32 accumulator chain + q2/out copies cost ``~32*dkv`` bytes. At V3
    geometry (nh=128, dkv=512) the old fixed 2048-row target estimated
    ~39 MiB — far past the 16 MiB scoped limit the chip enforces."""
    sb = max(1, min(S, max(8, _TARGET_M_ROWS // nh)))
    return shrink_query_block(sb, 1, nh, 22 * span + 32 * dkv, slab_bytes)


def _mla_prefill_kernel(q2_ref, kv_hbm, layer_ref, table_ref, qstart_ref,
                        lens_ref, out_ref, buf, sem, *, page_size: int,
                        chunk: int, q_block: int):
    """One program per (sequence, query-block).

    q2_ref:  [1, 2, SB, nh, dkv] — slot 0 = absorbed latent queries,
             slot 1 = roped queries zero-padded to dkv; pre-scaled.
    kv_hbm:  [L, N, 2, 1, ps, dkv] stacked latent cache (ANY).
    buf:     [2, 2, 1, chunk*ps, dkv] double-buffered slabs.
    sem:     [2, chunk] DMA semaphores.
    out_ref: [1, SB, nh, dkv] latent attention output (f32 downstream
             re-expansion through W_UV happens outside).
    """
    b = pl.program_id(0)
    j = pl.program_id(1)
    layer = layer_ref[0]
    ctx = lens_ref[b]
    q_start = qstart_ref[b]

    SB = q_block
    nh, dkv = q2_ref.shape[3], q2_ref.shape[4]
    span = chunk * page_size

    block_last = q_start + (j + 1) * SB - 1
    visible = jnp.minimum(ctx, block_last + 1)
    num_chunks = jnp.maximum(jax.lax.div(visible + span - 1, span), 1)

    P = table_ref.shape[1]

    def page_dma(slot, i, c):
        jj = jnp.minimum(c * chunk + i, P - 1)
        return pltpu.make_async_copy(
            kv_hbm.at[layer, table_ref[b, jj]],
            buf.at[slot, :, :, pl.ds(i * page_size, page_size)],
            sem.at[slot, i])

    def start_chunk(slot, c):
        def start_one(i, _):
            page_dma(slot, i, c).start()
            return 0

        jax.lax.fori_loop(0, chunk, start_one, 0, unroll=True)

    def wait_chunk(slot, c):
        def wait_one(i, _):
            page_dma(slot, i, c).wait()
            return 0

        jax.lax.fori_loop(0, chunk, wait_one, 0, unroll=True)

    start_chunk(0, 0)

    # [2, nh*SB, dkv]: heads-major rows so the slot-batched dot has one
    # contracting dim (Mosaic) and M = nh*SB fills the MXU
    q2 = q2_ref[0].transpose(0, 2, 1, 3).reshape(2, nh * SB, dkv)
    qpos = q_start + j * SB + jax.lax.broadcasted_iota(
        jnp.int32, (1, SB, 1), 1)                          # [1, SB, 1]

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < num_chunks)
        def _():
            start_chunk(jax.lax.rem(c + 1, 2), c + 1)

        wait_chunk(slot, c)
        kv = buf[slot, :, 0]                               # [2, span, dkv]

        s2 = jax.lax.dot_general(
            q2, kv, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [2, nh*SB, span]
        s = s2[0] + s2[1]
        s3 = s.reshape(nh, SB, span)
        t_pos = c * span + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, span), 2)
        mask = (t_pos <= qpos) & (t_pos < ctx)             # [1, SB, span]
        # chunk 0 always holds position 0, which every row's causal mask
        # admits (ctx >= 1) — no fully-masked-row guard needed
        s3 = jnp.where(mask, s3, NEG_INF)
        s = s3.reshape(nh * SB, span)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                             # [nh*SB, span]
        scale = jnp.exp(m - m_new)
        l = l * scale + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(kv.dtype), kv[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [nh*SB, dkv]
        acc = acc * scale + pv
        return m_new, l, acc

    m0 = jnp.full((nh * SB, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((nh * SB, 1), jnp.float32)
    acc0 = jnp.zeros((nh * SB, dkv), jnp.float32)
    _m, l, acc = jax.lax.fori_loop(0, num_chunks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-20)                      # [nh*SB, dkv]
    out_ref[0] = out.reshape(nh, SB, dkv).transpose(1, 0, 2) \
        .astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _mla_paged_prefill(q2, kv_pages, layer_idx, page_table, q_start,
                       total_lens, sm_scale: float,
                       interpret: bool = False):
    B, _two, S, nh, dkv = q2.shape
    _L, _N, _2, _one, page_size, _ = kv_pages.shape
    P = page_table.shape[1]
    chunk = min(PAGES_PER_CHUNK, P)
    span = chunk * page_size
    slab_bytes = 2 * 2 * span * dkv * kv_pages.dtype.itemsize
    SB = _query_block(S, nh, dkv, span, slab_bytes)
    n_q_blocks = -(-S // SB)

    kernel = functools.partial(_mla_prefill_kernel, page_size=page_size,
                               chunk=chunk, q_block=SB)
    return pl.pallas_call(
        kernel,
        grid=(B, n_q_blocks),
        in_specs=[
            pl.BlockSpec((1, 2, SB, nh, dkv),
                         lambda b, j: (b, 0, j, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, SB, nh, dkv),
                               lambda b, j: (b, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, 2, 1, chunk * page_size, dkv), kv_pages.dtype),
            pltpu.SemaphoreType.DMA((2, chunk)),
        ],
        out_shape=jax.ShapeDtypeStruct((B, S, nh, dkv), jnp.float32),
        interpret=interpret,
    )((q2 * sm_scale).astype(kv_pages.dtype), kv_pages, layer_idx,
      page_table, q_start, total_lens)


def mla_paged_prefill_stacked(q_lat: jnp.ndarray, q_pe: jnp.ndarray,
                              pages: jnp.ndarray, layer_idx,
                              page_table: jnp.ndarray,
                              positions: jnp.ndarray,
                              total_lens: jnp.ndarray, sm_scale: float,
                              interpret: bool | None = None
                              ) -> jnp.ndarray:
    """Latent paged PREFILL attention over the stacked MLA cache.

    q_lat:      [B, S, nh, dkv] absorbed latent queries (f32 ok; cast in)
    q_pe:       [B, S, nh, dr] roped queries
    pages:      [L, N, 2, 1, ps, dkv] latent cache
    layer_idx:  scalar int (python int or traced scan index)
    page_table: [B, P]
    positions:  [B, S] absolute positions (row-contiguous; column 0 is
                the block base — the engine's chunk batches)
    total_lens: [B] context length including the new tokens

    Returns the latent attention output [B, S, nh, dkv] in f32 — feed to
    ``models.deepseek._expand_and_project``.
    """
    B, S, nh, dkv = q_lat.shape
    dr = q_pe.shape[-1]
    q_pe_pad = jnp.pad(q_pe, ((0, 0), (0, 0), (0, 0), (0, dkv - dr)))
    q2 = jnp.stack([q_lat, q_pe_pad], axis=1)      # [B, 2, S, nh, dkv]
    layer = jnp.asarray(layer_idx, jnp.int32).reshape(1)
    return _mla_paged_prefill(q2, pages, layer,
                              page_table.astype(jnp.int32),
                              positions[:, 0].astype(jnp.int32),
                              total_lens.astype(jnp.int32), sm_scale,
                              interpret=_resolve_interpret(interpret))


__all__ = ["mla_paged_prefill_stacked", "supports"]
