"""Chunked-prefill paged attention on TPU — flash-style Pallas kernel.

Covers the serving engine's prefill steps (S = chunk of new tokens per
sequence) against the paged KV cache, the shape class where the XLA
blockwise path (``ops/attention._attend_blockwise``) still materializes a
``[B, Hkv, S, G, span]`` score block per chunk in XLA-managed buffers. Here
the whole layer runs as one kernel per (sequence, query-block):

- Same page-streaming machinery as the decode kernel
  (``ops/pallas/decode.py``): pages stay in HBM (``memory_space=ANY``) in
  the page-major slab layout ``[L, N, 2, Hkv, ps, Dh]``, an SMEM layer
  index rides the DMA descriptors (so the kernel works under ``lax.scan``
  over layers), and chunks of ``PAGES_PER_CHUNK`` pages double-buffer into
  VMEM — the next chunk's burst issued while the current chunk computes.
- Flash-style online softmax in f32 with a CAUSAL mask on absolute
  positions: query row ``s`` of the block attends to kv positions
  ``t <= q_start + j*SB + s`` and ``t < ctx``. Prefix-cache hits fall out:
  queries attend to whatever the page table already holds.
- The query block is ``[SB, Hq, Dh]`` with SB = 256 (or S when shorter):
  large enough to fill the MXU via the grouped ``[Hkv, G*SB, span]``
  matmuls, small enough that scores + accumulator + kv slabs fit VMEM at
  Llama-3-class geometry (~11 MB at Hkv=8, G=3, Dh=128).
- Each (b, j) program streams only the chunks its queries can SEE
  (``ceil(min(ctx, block_end+1) / span)``) — early query blocks of a long
  context skip the tail, and queries past ``ctx`` cost nothing.

Alignment: ``head_dim % 128 == 0`` and ``page_size % 8 == 0`` (same
``supports`` predicate as decode). CPU tests run in interpreter mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.pallas.decode import _resolve_interpret, supports  # noqa: F401

NEG_INF = -1e30

PAGES_PER_CHUNK = 8

# query rows per grid program: SB * Hq * Dh bf16 + f32 scores/acc must fit
# scoped VMEM next to the double-buffered kv slabs. The ceiling is the
# 16 MiB scoped-vmem stack limit, and Mosaic's materialized temporaries
# (exp input, p cast, acc update, the q/out transposes) roughly DOUBLE the
# naive scores+acc accounting: on a real v5e, SB=128 at Llama-3B geometry
# (Hq=24, Dh=128, span=128) measured 16.79 MiB of stack — 804 KiB OVER.
# ``_fit_query_block`` shrinks SB per-geometry with an estimator
# calibrated against that measurement; QUERY_BLOCK is only the upper bound.
QUERY_BLOCK = 128

# scoped-vmem stack budget the estimator targets: the hardware limit is
# 16 MiB; 14 MiB leaves margin for the ~5% the calibrated estimator
# underpredicts plus Mosaic's small fixed overheads
VMEM_STACK_BUDGET = 14 * 2**20


def shrink_query_block(sb: int, floor: int, row_heads: int,
                       bytes_per_row: int, slab_bytes: int) -> int:
    """Halve ``sb`` until ``row_heads * sb * bytes_per_row + slab_bytes``
    fits ``VMEM_STACK_BUDGET`` (never below ``floor``). Shared by this
    kernel and the MLA prefill kernel — each supplies its own calibrated
    per-row byte cost."""
    while sb > floor and row_heads * sb * bytes_per_row + slab_bytes \
            > VMEM_STACK_BUDGET:
        # clamp the halving so a non-power-of-two start (sb seeds from the
        # prompt length S) cannot step BELOW the floor: 12 -> 6 would
        # violate the kernel's minimum-rows contract
        sb = max(floor, sb // 2)
    return sb


def _fit_query_block(S: int, Hq: int, Dh: int, span: int,
                     slab_bytes: int) -> int:
    """Largest query block (power-of-two rows ≥ 8) whose estimated scoped
    VMEM stack fits the budget.

    Estimator: the f32 score/prob/exp temporaries are ``Hq*SB*span`` (≈3
    copies live) and the f32 accumulator chain is ``Hq*SB*Dh`` (≈4 copies),
    plus bf16 q/out copies — ``Hq*SB*(14*span + 24*Dh)`` bytes total.
    Calibrated on v5e: predicts 15.9 MiB where the chip measured 16.79 MiB
    (Hq=24, SB=128, span=128, Dh=128), hence the conservative budget.
    """
    return shrink_query_block(min(QUERY_BLOCK, S), 8, Hq,
                              14 * span + 24 * Dh, slab_bytes)


def _prefill_kernel(q_ref, kv_hbm, layer_ref, window_ref, table_ref,
                    qstart_ref, lens_ref, out_ref, buf, sem, *,
                    page_size: int, n_kv: int, chunk: int, q_block: int,
                    softcap: float):
    """One program per (sequence, query-block): stream visible page chunks,
    causal online-softmax attend.

    q_ref/out_ref: [1, SB, Hq, Dh] block of the padded chunk batch.
    buf: [2, 2, Hkv, chunk*page_size, Dh] double-buffered kv slabs.
    sem: [2, chunk] DMA semaphores.

    ``window_ref`` (SMEM scalar, 0 = unlimited) restricts each query row to
    the last ``window`` kv positions (gemma-2 alternating sliding-window
    layers) — chunks wholly before the BLOCK's earliest window are never
    DMA'd. ``softcap`` (static; 0 = disabled) applies gemma-style logit
    soft-capping ``cap * tanh(s / cap)`` before the mask, matching the XLA
    paths.
    """
    b = pl.program_id(0)
    j = pl.program_id(1)
    layer = layer_ref[0]
    win = window_ref[0]
    ctx = lens_ref[b]
    q_start = qstart_ref[b]

    SB = q_block
    Hq, Dh = q_ref.shape[2], q_ref.shape[3]
    G = Hq // n_kv
    span = chunk * page_size

    # kv this block can see: causal bound (its last query's position + 1)
    # clamped to the live context
    block_last = q_start + (j + 1) * SB - 1
    visible = jnp.minimum(ctx, block_last + 1)
    num_chunks = jnp.maximum(jax.lax.div(visible + span - 1, span), 1)
    # first kv position the block's EARLIEST query can see (the window
    # mask is per-row below; this only bounds the chunk range)
    block_first = q_start + j * SB
    first_pos = jnp.where(win > 0,
                          jnp.maximum(block_first - win + 1, 0), 0)

    P = table_ref.shape[1]

    def page_dma(slot, i, c):
        jj = jnp.minimum(c * chunk + i, P - 1)
        return pltpu.make_async_copy(
            kv_hbm.at[layer, table_ref[b, jj]],
            buf.at[slot, :, :, pl.ds(i * page_size, page_size)],
            sem.at[slot, i])

    def start_chunk(slot, c):
        def start_one(i, _):
            page_dma(slot, i, c).start()
            return 0

        jax.lax.fori_loop(0, chunk, start_one, 0, unroll=True)

    def wait_chunk(slot, c):
        def wait_one(i, _):
            page_dma(slot, i, c).wait()
            return 0

        jax.lax.fori_loop(0, chunk, wait_one, 0, unroll=True)

    # skip chunks before the window, clamped so at least one loop
    # iteration consumes the unconditional start_chunk below — an
    # unconsumed DMA would leave its semaphores armed for the NEXT grid
    # program's wait (scratch persists across the sequential grid); the
    # clamped chunk is fully masked and the m_new guard zeroes it
    c0 = jnp.minimum(jax.lax.div(first_pos, span), num_chunks - 1)
    start_chunk(jax.lax.rem(c0, 2), c0)

    # queries in [Hkv, G*SB, Dh] so scores/PV are single-contraction
    # batched matmuls (Mosaic takes one contracting dim)
    q = q_ref[0].reshape(SB, n_kv, G, Dh).transpose(1, 2, 0, 3) \
        .reshape(n_kv, G * SB, Dh)
    qpos = q_start + j * SB + jax.lax.broadcasted_iota(
        jnp.int32, (1, G, SB, 1), 2)                       # [1, G, SB, 1]

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < num_chunks)
        def _():
            start_chunk(jax.lax.rem(c + 1, 2), c + 1)

        wait_chunk(slot, c)
        k = buf[slot, 0]                                   # [Hkv, span, Dh]
        v = buf[slot, 1]

        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [Hkv, G*SB, span]
        if softcap:
            s = jnp.tanh(s / softcap) * softcap
        s4 = s.reshape(n_kv, G, SB, span)
        t_pos = c * span + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, 1, span), 3)
        mask = (t_pos <= qpos) & (t_pos < ctx)             # [1, G, SB, span]
        # per-row sliding window: row at position p sees t > p - win
        mask &= (win <= 0) | (t_pos > qpos - win)
        s4 = jnp.where(mask, s4, NEG_INF)
        s = s4.reshape(n_kv, G * SB, span)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))        # [Hkv, G*SB]
        p = jnp.exp(s - m_new[..., None])
        # a block whose first chunks are all-masked keeps m at -inf:
        # exp(-inf - -inf) = 1 would leak weight — zero those rows
        p = jnp.where((m_new > NEG_INF / 2)[..., None], p, 0.0)
        scale = jnp.where(m > NEG_INF / 2, jnp.exp(m - m_new), 0.0)
        l = l * scale + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [Hkv, G*SB, Dh]
        acc = acc * scale[..., None] + pv
        return m_new, l, acc

    m0 = jnp.full((n_kv, G * SB), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n_kv, G * SB), jnp.float32)
    acc0 = jnp.zeros((n_kv, G * SB, Dh), jnp.float32)
    _m, l, acc = jax.lax.fori_loop(c0, num_chunks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-20)[..., None]           # [Hkv, G*SB, Dh]
    out = out.reshape(n_kv, G, SB, Dh).transpose(2, 0, 1, 3) \
        .reshape(SB, Hq, Dh)
    out_ref[0] = out.astype(out_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("sm_scale", "softcap", "interpret"))
def _paged_prefill(q, kv_pages, layer_idx, window, page_table, q_start,
                   total_lens, sm_scale: float, softcap: float = 0.0,
                   interpret: bool = False):
    B, S, Hq, Dh = q.shape
    _L, _N, _two, Hkv, page_size, _ = kv_pages.shape
    P = page_table.shape[1]
    chunk = min(PAGES_PER_CHUNK, P)
    span = chunk * page_size
    slab_bytes = 2 * 2 * Hkv * span * Dh * kv_pages.dtype.itemsize
    SB = _fit_query_block(S, Hq, Dh, span, slab_bytes)
    # S need not divide SB: pallas pads the ragged last block (its garbage
    # query rows attend to finite clamped pages and their outputs land in
    # the discarded pad region of out_ref)
    n_q_blocks = -(-S // SB)

    kernel = functools.partial(_prefill_kernel, page_size=page_size,
                               n_kv=Hkv, chunk=chunk, q_block=SB,
                               softcap=softcap)
    return pl.pallas_call(
        kernel,
        grid=(B, n_q_blocks),
        in_specs=[
            pl.BlockSpec((1, SB, Hq, Dh), lambda b, j: (b, j, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, SB, Hq, Dh), lambda b, j: (b, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, 2, Hkv, chunk * page_size, Dh), kv_pages.dtype),
            pltpu.SemaphoreType.DMA((2, chunk)),
        ],
        out_shape=jax.ShapeDtypeStruct((B, S, Hq, Dh), q.dtype),
        interpret=interpret,
    )((q * sm_scale).astype(q.dtype), kv_pages, layer_idx, window,
      page_table, q_start, total_lens)


def paged_prefill_attention_stacked(q: jnp.ndarray, pages: jnp.ndarray,
                                    layer_idx, page_table: jnp.ndarray,
                                    positions: jnp.ndarray,
                                    total_lens: jnp.ndarray, sm_scale: float,
                                    window=None, softcap=None,
                                    interpret: bool | None = None
                                    ) -> jnp.ndarray:
    """Drop-in for ``ops.attention.paged_attention`` on prefill steps
    (S > 1, positions contiguous per row — the engine's chunk batches).

    q:          [B, S, Hq, Dh] (S = padded chunk length)
    pages:      [L, N, 2, Hkv, page_size, Dh]
    layer_idx:  scalar int (python int or traced scan index)
    page_table: [B, P]
    positions:  [B, S] absolute positions (row-contiguous; only column 0
                enters the kernel — pad rows/slots mask out downstream)
    total_lens: [B] context length including the new tokens
    window:     optional scalar (python int or traced, 0 = unlimited) —
                gemma-2 alternating sliding-window layers
    softcap:    optional STATIC float (gemma logit soft-capping)
    """
    layer = jnp.asarray(layer_idx, jnp.int32).reshape(1)
    win = (jnp.zeros((1,), jnp.int32) if window is None
           else jnp.asarray(window, jnp.int32).reshape(1))
    out = _paged_prefill(q, pages, layer, win,
                         page_table.astype(jnp.int32),
                         positions[:, 0].astype(jnp.int32),
                         total_lens.astype(jnp.int32), sm_scale,
                         softcap=float(softcap or 0.0),
                         interpret=_resolve_interpret(interpret))
    return out


# gemma's forward checks this marker before handing the impl its per-layer
# window / softcap kwargs (closes VERDICT r4 item 4: gemma-2 prefill now
# rides the Pallas kernel instead of falling back to the XLA path)
paged_prefill_attention_stacked.supports_window_softcap = True
# see ops/pallas/decode.py: deepseek's MLA opt-in marker
paged_prefill_attention_stacked.pallas_paged_kernel = True


__all__ = ["paged_prefill_attention_stacked", "supports"]
