"""Paged attention over a block-paged KV cache — unified prefill/decode path.

Capability parity with the reference's engine-internal paged attention (the
reference delegates this to vLLM/SGLang CUDA kernels; here it is native).
Design is TPU-first:

- Cache layout is PAGE-MAJOR: per layer ``[N, 2, Hkv, page_size, Dh]``
  (page, k/v, kv-head), stacked to ``[L, N, 2, Hkv, page_size, Dh]`` for
  the ``lax.scan`` forward. One page is one contiguous slab holding BOTH
  K and V for every kv head — so the Pallas decode kernel
  (``ops/pallas/decode.py``) fetches a page's entire contribution with ONE
  DMA descriptor, and device-to-device block transfers (disagg prefill →
  decode) move whole pages with unit-stride copies. (A head-major layout
  fragments every page into per-head 4 KB strips — measured ~10× worse on
  both the DMA and the XLA-gather paths.)
- Page 0 is a reserved garbage page: padded token positions write there, which
  makes every scatter shape-static and mask-free.
- One code path serves prefill (S = chunk length) and decode (S = 1): new K/V
  is scattered into the cache first, then the full context is gathered from the
  page table and attended with a causal mask on absolute positions. Chunked
  prefill with a prefix-cache hit falls out for free — queries attend to
  whatever the page table already holds.

The XLA gather path materializes ``[B, T, Hkv, Dh]`` per layer; the Pallas
decode kernel fuses that gather away on TPU. This XLA path is the portable
reference implementation and the CPU-test path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def write_kv_layer(kv_layer: jnp.ndarray, k_new: jnp.ndarray,
                   v_new: jnp.ndarray, page_table: jnp.ndarray,
                   positions: jnp.ndarray, new_lens: jnp.ndarray) -> jnp.ndarray:
    """Scatter new K/V into one layer's paged cache.

    kv_layer:   [N, 2, Hkv, page_size, Dh]
    k_new/v_new:[B, S, Hkv, Dh]
    page_table: [B, P] logical-page -> physical-page map (int32)
    positions:  [B, S] absolute token positions of the new tokens
    new_lens:   [B] number of real (non-pad) new tokens per sequence
    """
    page_size = kv_layer.shape[3]
    B, S = positions.shape
    logical = positions // page_size                       # [B, S]
    slot = positions % page_size                           # [B, S]
    phys = jnp.take_along_axis(page_table, logical, axis=1)  # [B, S]
    # Padded tokens (s >= new_lens[b]) go to the reserved garbage page 0.
    pad = jnp.arange(S)[None, :] >= new_lens[:, None]
    phys = jnp.where(pad, 0, phys)
    slot = jnp.where(pad, 0, slot)
    # advanced indices (phys, slot) are separated by slices, so their
    # broadcast dims move to the FRONT: the scatter value is [B, S, 2, Hkv, Dh]
    new = jnp.stack([k_new, v_new], axis=2)
    return kv_layer.at[phys, :, :, slot].set(new.astype(kv_layer.dtype),
                                             mode="drop")


def write_kv(pages: jnp.ndarray, layer_idx, k_new: jnp.ndarray,
             v_new: jnp.ndarray, page_table: jnp.ndarray,
             positions: jnp.ndarray, new_lens: jnp.ndarray) -> jnp.ndarray:
    """Scatter new K/V into the stacked cache ``[L, N, 2, Hkv, ps, Dh]``."""
    page_size = pages.shape[4]
    B, S = positions.shape
    logical = positions // page_size
    slot = positions % page_size
    phys = jnp.take_along_axis(page_table, logical, axis=1)
    pad = jnp.arange(S)[None, :] >= new_lens[:, None]
    phys = jnp.where(pad, 0, phys)
    slot = jnp.where(pad, 0, slot)
    new = jnp.stack([k_new, v_new], axis=2)                # [B, S, 2, Hkv, Dh]
    return pages.at[layer_idx, phys, :, :, slot].set(
        new.astype(pages.dtype), mode="drop")


def _softcap(scores: jnp.ndarray, cap) -> jnp.ndarray:
    """gemma-style logit soft-capping: cap * tanh(scores / cap). Callers
    pass ``cap=None`` when disabled (never a zero scalar), so the enabled
    path is a bare tanh — no masking over the score tensor."""
    if cap is None:
        return scores
    return jnp.tanh(scores / cap) * cap


def _attend(qg: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
            positions: jnp.ndarray, total_lens: jnp.ndarray,
            sm_scale: float, window=None, softcap=None) -> jnp.ndarray:
    """qg [B,S,Hkv,G,Dh]; k/v [B,Hkv,T,Dh] -> [B,S,Hkv*G,Dh].

    ``window`` (traced int32, 0 = unlimited) restricts each query to the
    last ``window`` kv positions — gemma-2 alternating sliding-window
    layers; ``softcap`` applies attention-logit soft-capping."""
    B, S, Hkv, G, Dh = qg.shape
    T = k.shape[2]
    scores = jnp.einsum("bsngd,bntd->bnsgt", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale  # [B,Hkv,S,G,T]
    scores = _softcap(scores, softcap)
    t_pos = jnp.arange(T)[None, None, :]                   # [1, 1, T]
    causal = t_pos <= positions[:, :, None]                # [B, S, T]
    valid = t_pos < total_lens[:, None, None]              # [B, 1, T]
    if window is not None:
        in_win = (window <= 0) | (t_pos > positions[:, :, None] - window)
        causal = causal & in_win
    mask = (causal & valid)[:, None, :, None, :]           # [B, 1, S, 1, T]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bnsgt,bntd->bsngd", probs, v.astype(jnp.float32))
    return out.reshape(B, S, Hkv * G, Dh)


# pages per streamed chunk on the blockwise path; 8 pages x 16-token pages
# = 128 kv positions per chunk — one chunk's matmul fills the MXU's lanes
PAGES_PER_CHUNK = 8


def _attend_blockwise(qg: jnp.ndarray, gather_chunk, num_table_pages: int,
                      page_size: int, chunk_pages: int,
                      positions: jnp.ndarray, total_lens: jnp.ndarray,
                      sm_scale: float, window=None, softcap=None,
                      return_partials: bool = False) -> jnp.ndarray:
    """Flash-style chunked attention over the paged context.

    The full-gather path above materializes ``[B,Hkv,S,G,T]`` scores — at
    serving shapes (B=8, S=512, T=704, 3B model) that is ~250 MB of f32 per
    layer, which is what made round 2's real-config prefill bench blow its
    budget. Here the kv context is consumed in chunks of ``chunk_pages``
    pages with the same online-softmax (running max + rescaled accumulators)
    the ring/Pallas paths use, so peak intermediate size is
    ``[B,Hkv,S,G,chunk_span]`` regardless of context length, and the
    ``fori_loop`` bound is dynamic — chunks beyond the longest live context
    are never touched, even though the page table is padded to
    ``max_context``.

    qg: [B, S, Hkv, G, Dh] queries (grouped);
    gather_chunk(c) -> (k, v) each [B, Hkv, span, Dh] for pages
    ``[c*chunk_pages, (c+1)*chunk_pages)`` of the (padded) page table.
    Matmuls run in the cache dtype with f32 accumulation (MXU-friendly;
    same numerics as the Pallas decode kernel).
    """
    B, S, Hkv, G, Dh = qg.shape
    span = chunk_pages * page_size
    n_static = -(-num_table_pages // chunk_pages)
    max_t = jnp.max(total_lens)
    n_chunks = jnp.minimum((max_t + span - 1) // span, n_static)

    def body(c, carry):
        num, den, mx = carry
        k, v = gather_chunk(c)
        s = jnp.einsum("bsngd,bntd->bnsgt", qg, k,
                       preferred_element_type=jnp.float32) * sm_scale
        s = _softcap(s, softcap)
        t_pos = c * span + jnp.arange(span)
        causal = t_pos[None, None, :] <= positions[:, :, None]   # [B,S,span]
        if window is not None:
            in_win = ((window <= 0)
                      | (t_pos[None, None, :] > positions[:, :, None]
                         - window))
            causal = causal & in_win
        valid = t_pos[None, None, :] < total_lens[:, None, None]
        mask = (causal & valid)[:, None, :, None, :]
        s = jnp.where(mask, s, NEG_INF)
        mx_new = jnp.maximum(mx, jnp.max(s, axis=-1))            # [B,Hkv,S,G]
        p = jnp.exp(s - mx_new[..., None])
        # rows with no visible kv yet (mx_new still -inf): exp(-inf - -inf)
        # is exp(0)=1 in floats — zero those rows explicitly
        p = jnp.where((mx_new > NEG_INF / 2)[..., None], p, 0.0)
        scale = jnp.where(mx > NEG_INF / 2, jnp.exp(mx - mx_new), 0.0)
        pv = jnp.einsum("bnsgt,bntd->bnsgd", p.astype(v.dtype), v,
                        preferred_element_type=jnp.float32)
        num = num * scale[..., None] + pv
        den = den * scale + jnp.sum(p, axis=-1)
        return num, den, mx_new

    num0 = jnp.zeros((B, Hkv, S, G, Dh), jnp.float32)
    den0 = jnp.zeros((B, Hkv, S, G), jnp.float32)
    mx0 = jnp.full((B, Hkv, S, G), NEG_INF, jnp.float32)
    num, den, mx = jax.lax.fori_loop(0, n_chunks, body, (num0, den0, mx0))
    if return_partials:
        # [B,Hq,S,...] layout (grouped heads folded), matching the ring
        # path's partials so the two contexts merge elementwise
        Hq = Hkv * G
        num_p = num.transpose(0, 1, 3, 2, 4).reshape(B, Hq, S, Dh)
        den_p = den.transpose(0, 1, 3, 2).reshape(B, Hq, S)
        mx_p = mx.transpose(0, 1, 3, 2).reshape(B, Hq, S)
        return num_p, den_p, mx_p
    out = num / jnp.maximum(den, 1e-20)[..., None]               # [B,Hkv,S,G,Dh]
    return out.transpose(0, 2, 1, 3, 4).reshape(B, S, Hkv * G, Dh)


def merge_softmax_partials(a, b):
    """Combine two un-normalized online-softmax states over DISJOINT kv
    contexts (e.g. ring self-attention over new tokens + blockwise
    attention over cached pages). Each is (num [..., D], den [...],
    mx [...]); dead states (mx == -inf: that context had no visible kv)
    contribute zero. Returns the same triple."""
    num_a, den_a, mx_a = a
    num_b, den_b, mx_b = b
    mx = jnp.maximum(mx_a, mx_b)
    sa = jnp.where(mx_a > NEG_INF / 2, jnp.exp(mx_a - mx), 0.0)
    sb = jnp.where(mx_b > NEG_INF / 2, jnp.exp(mx_b - mx), 0.0)
    num = num_a * sa[..., None] + num_b * sb[..., None]
    den = den_a * sa + den_b * sb
    return num, den, mx


def normalize_softmax_partials(num, den):
    """(num, den) -> attention output; all-dead rows produce zeros."""
    return num / jnp.maximum(den, 1e-20)[..., None]


def _pad_table(page_table: jnp.ndarray, chunk_pages: int) -> jnp.ndarray:
    """Pad the page-table width to a multiple of ``chunk_pages`` with page 0
    (the reserved garbage page) so chunk slices are always full-width."""
    P = page_table.shape[1]
    rem = P % chunk_pages
    if rem:
        page_table = jnp.pad(page_table, ((0, 0), (0, chunk_pages - rem)))
    return page_table


def _gathered_to_bhtd(g: jnp.ndarray) -> jnp.ndarray:
    """[B, P, Hkv, ps, Dh] gathered pages -> [B, Hkv, T, Dh]."""
    B, P, Hkv, ps, Dh = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, P * ps, Dh)


def ragged_paged_attention(q: jnp.ndarray, pages: jnp.ndarray, layer_idx,
                           page_table: jnp.ndarray, q_starts: jnp.ndarray,
                           q_lens: jnp.ndarray, kv_lens: jnp.ndarray,
                           sm_scale: float, window=None,
                           softcap=None) -> jnp.ndarray:
    """Ragged paged attention over a FLATTENED mixed batch — the reference
    lowering of the kernel shape continuous batching needs (Ragged Paged
    Attention, PAPERS.md): one dispatch where each row contributes an
    arbitrary number of query tokens (a prefill chunk, or a single decode
    token) against its own paged KV context.

    q:          [T, Hq, Dh] — every row's query tokens packed back to back
                (row i occupies ``q_starts[i] .. q_starts[i]+q_lens[i]``);
                slots past the last row's end are pad.
    pages:      [L, N, 2, Hkv, page_size, Dh] stacked cache
    page_table: [B, P] per-ROW page table
    q_starts:   [B] row offsets into the flat axis (ascending, packed)
    q_lens:     [B] real query tokens per row (a decode row is 1)
    kv_lens:    [B] total context per row INCLUDING its new tokens — row
                i's token j sits at absolute position
                ``kv_lens[i] - q_lens[i] + j``
    returns     [T, Hq, Dh]; pad slots are zeroed.

    Built on the same blockwise online-softmax machinery as the chunked
    paths (``_attend_blockwise``): each flat token attends to its row's
    pages as a [T, 1]-query batch, so peak intermediates stay bounded by
    the chunk span regardless of context length. The Pallas kernel
    (``ops/pallas/ragged.py``) fuses the per-token gather away on TPU;
    this is the portable reference and the CPU-test oracle.
    """
    T, Hq, Dh = q.shape
    B, P = page_table.shape
    Hkv = pages.shape[3]
    ps = pages.shape[4]
    t_idx = jnp.arange(T)
    ends = q_starts + q_lens
    # packed rows: token t belongs to the first row whose end exceeds t
    row = jnp.sum(t_idx[:, None] >= ends[None, :], axis=1)
    row = jnp.minimum(row, B - 1)
    valid = (t_idx >= q_starts[row]) & (t_idx < ends[row])
    pos = kv_lens[row] - q_lens[row] + (t_idx - q_starts[row])
    pos = jnp.where(valid, pos, 0)
    # pad tokens attend the garbage page with a 1-token context: finite
    # work, masked result discarded below
    tok_table = jnp.where(valid[:, None], page_table[row], 0)
    tok_total = jnp.where(valid, kv_lens[row], 1)
    qg = q.reshape(T, 1, Hkv, Hq // Hkv, Dh)
    chunk_pages = min(PAGES_PER_CHUNK, P)
    table = _pad_table(tok_table, chunk_pages)

    def gather_chunk(c):
        tbl = jax.lax.dynamic_slice(
            table, (0, c * chunk_pages), (T, chunk_pages))
        g = pages[layer_idx, tbl]          # [T, C, 2, Hkv, ps, Dh]
        return _gathered_to_bhtd(g[:, :, 0]), _gathered_to_bhtd(g[:, :, 1])

    out = _attend_blockwise(qg, gather_chunk, P, ps, chunk_pages,
                            pos[:, None], tok_total, sm_scale,
                            window=window, softcap=softcap)
    out = out.reshape(T, Hq, Dh)
    return jnp.where(valid[:, None, None], out, 0.0).astype(q.dtype)


def paged_attention_layer(q: jnp.ndarray, kv_layer: jnp.ndarray,
                          page_table: jnp.ndarray, positions: jnp.ndarray,
                          total_lens: jnp.ndarray, sm_scale: float,
                          window=None, softcap=None) -> jnp.ndarray:
    """XLA-path attention against one layer's cache.

    q: [B, S, Hq, Dh]; kv_layer: [N, 2, Hkv, ps, Dh] -> [B, S, Hq, Dh]

    Prefill steps (S > 1) with a context wider than one chunk take the
    blockwise online-softmax path; small shapes keep the direct gather.
    """
    B, S, Hq, Dh = q.shape
    Hkv = kv_layer.shape[2]
    ps = kv_layer.shape[3]
    P = page_table.shape[1]
    qg = q.reshape(B, S, Hkv, Hq // Hkv, Dh)
    if S > 1 and P > PAGES_PER_CHUNK:
        table = _pad_table(page_table, PAGES_PER_CHUNK)

        def gather_chunk(c):
            tbl = jax.lax.dynamic_slice(
                table, (0, c * PAGES_PER_CHUNK), (B, PAGES_PER_CHUNK))
            g = kv_layer[tbl]              # [B, C, 2, Hkv, ps, Dh]
            return _gathered_to_bhtd(g[:, :, 0]), _gathered_to_bhtd(g[:, :, 1])

        return _attend_blockwise(qg, gather_chunk, P, ps, PAGES_PER_CHUNK,
                                 positions, total_lens, sm_scale,
                                 window=window,
                                 softcap=softcap).astype(q.dtype)
    gathered = kv_layer[page_table]        # [B, P, 2, Hkv, ps, Dh]
    k = _gathered_to_bhtd(gathered[:, :, 0])
    v = _gathered_to_bhtd(gathered[:, :, 1])
    return _attend(qg, k, v, positions, total_lens, sm_scale,
                   window=window, softcap=softcap).astype(q.dtype)


def paged_attention(q: jnp.ndarray, pages: jnp.ndarray, layer_idx,
                    page_table: jnp.ndarray, positions: jnp.ndarray,
                    total_lens: jnp.ndarray, sm_scale: float,
                    window=None, softcap=None) -> jnp.ndarray:
    """Attend queries to the stacked paged context (scan path).

    q:          [B, S, Hq, Dh]
    pages:      [L, N, 2, Hkv, page_size, Dh]
    page_table: [B, P]
    positions:  [B, S] absolute positions of the queries
    total_lens: [B] total context length (cached + new)
    returns     [B, S, Hq, Dh]
    """
    B, S, Hq, Dh = q.shape
    Hkv = pages.shape[3]
    ps = pages.shape[4]
    P = page_table.shape[1]
    qg = q.reshape(B, S, Hkv, Hq // Hkv, Dh)
    if S > 1 and P > PAGES_PER_CHUNK:
        table = _pad_table(page_table, PAGES_PER_CHUNK)

        def gather_chunk(c):
            tbl = jax.lax.dynamic_slice(
                table, (0, c * PAGES_PER_CHUNK), (B, PAGES_PER_CHUNK))
            # traced layer_idx rides the advanced index (see below)
            g = pages[layer_idx, tbl]      # [B, C, 2, Hkv, ps, Dh]
            return _gathered_to_bhtd(g[:, :, 0]), _gathered_to_bhtd(g[:, :, 1])

        return _attend_blockwise(qg, gather_chunk, P, ps, PAGES_PER_CHUNK,
                                 positions, total_lens, sm_scale,
                                 window=window,
                                 softcap=softcap).astype(q.dtype)

    # Single fused gather: the traced layer_idx participates as an advanced
    # index so XLA reads only the gathered pages (slicing pages[layer_idx]
    # first would dynamic-slice-copy the whole layer's cache).
    gathered = pages[layer_idx, page_table]  # [B, P, 2, Hkv, ps, Dh]
    k = _gathered_to_bhtd(gathered[:, :, 0])
    v = _gathered_to_bhtd(gathered[:, :, 1])
    return _attend(qg, k, v, positions, total_lens, sm_scale,
                   window=window, softcap=softcap).astype(q.dtype)


__all__ = ["write_kv", "write_kv_layer", "paged_attention",
           "paged_attention_layer", "ragged_paged_attention",
           "merge_softmax_partials", "normalize_softmax_partials",
           "NEG_INF"]
