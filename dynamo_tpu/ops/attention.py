"""Paged attention over a block-paged KV cache — unified prefill/decode path.

Capability parity with the reference's engine-internal paged attention (the
reference delegates this to vLLM/SGLang CUDA kernels; here it is native).
Design is TPU-first:

- Cache layout is PAGE-MAJOR: per layer ``[N, 2, Hkv, page_size, Dh]``
  (page, k/v, kv-head), stacked to ``[L, N, 2, Hkv, page_size, Dh]`` for
  the ``lax.scan`` forward. One page is one contiguous slab holding BOTH
  K and V for every kv head — so the Pallas decode kernel
  (``ops/pallas/decode.py``) fetches a page's entire contribution with ONE
  DMA descriptor, and device-to-device block transfers (disagg prefill →
  decode) move whole pages with unit-stride copies. (A head-major layout
  fragments every page into per-head 4 KB strips — measured ~10× worse on
  both the DMA and the XLA-gather paths.)
- Page 0 is a reserved garbage page: padded token positions write there, which
  makes every scatter shape-static and mask-free.
- One code path serves prefill (S = chunk length) and decode (S = 1): new K/V
  is scattered into the cache first, then the full context is gathered from the
  page table and attended with a causal mask on absolute positions. Chunked
  prefill with a prefix-cache hit falls out for free — queries attend to
  whatever the page table already holds.

The XLA gather path materializes ``[B, T, Hkv, Dh]`` per layer; the Pallas
decode kernel fuses that gather away on TPU. This XLA path is the portable
reference implementation and the CPU-test path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def write_kv_layer(kv_layer: jnp.ndarray, k_new: jnp.ndarray,
                   v_new: jnp.ndarray, page_table: jnp.ndarray,
                   positions: jnp.ndarray, new_lens: jnp.ndarray) -> jnp.ndarray:
    """Scatter new K/V into one layer's paged cache.

    kv_layer:   [N, 2, Hkv, page_size, Dh]
    k_new/v_new:[B, S, Hkv, Dh]
    page_table: [B, P] logical-page -> physical-page map (int32)
    positions:  [B, S] absolute token positions of the new tokens
    new_lens:   [B] number of real (non-pad) new tokens per sequence
    """
    page_size = kv_layer.shape[3]
    B, S = positions.shape
    logical = positions // page_size                       # [B, S]
    slot = positions % page_size                           # [B, S]
    phys = jnp.take_along_axis(page_table, logical, axis=1)  # [B, S]
    # Padded tokens (s >= new_lens[b]) go to the reserved garbage page 0.
    pad = jnp.arange(S)[None, :] >= new_lens[:, None]
    phys = jnp.where(pad, 0, phys)
    slot = jnp.where(pad, 0, slot)
    # advanced indices (phys, slot) are separated by slices, so their
    # broadcast dims move to the FRONT: the scatter value is [B, S, 2, Hkv, Dh]
    new = jnp.stack([k_new, v_new], axis=2)
    return kv_layer.at[phys, :, :, slot].set(new.astype(kv_layer.dtype),
                                             mode="drop")


def write_kv(pages: jnp.ndarray, layer_idx, k_new: jnp.ndarray,
             v_new: jnp.ndarray, page_table: jnp.ndarray,
             positions: jnp.ndarray, new_lens: jnp.ndarray) -> jnp.ndarray:
    """Scatter new K/V into the stacked cache ``[L, N, 2, Hkv, ps, Dh]``."""
    page_size = pages.shape[4]
    B, S = positions.shape
    logical = positions // page_size
    slot = positions % page_size
    phys = jnp.take_along_axis(page_table, logical, axis=1)
    pad = jnp.arange(S)[None, :] >= new_lens[:, None]
    phys = jnp.where(pad, 0, phys)
    slot = jnp.where(pad, 0, slot)
    new = jnp.stack([k_new, v_new], axis=2)                # [B, S, 2, Hkv, Dh]
    return pages.at[layer_idx, phys, :, :, slot].set(
        new.astype(pages.dtype), mode="drop")


def _attend(qg: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
            positions: jnp.ndarray, total_lens: jnp.ndarray,
            sm_scale: float) -> jnp.ndarray:
    """qg [B,S,Hkv,G,Dh]; k/v [B,Hkv,T,Dh] -> [B,S,Hkv*G,Dh]."""
    B, S, Hkv, G, Dh = qg.shape
    T = k.shape[2]
    scores = jnp.einsum("bsngd,bntd->bnsgt", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale  # [B,Hkv,S,G,T]
    t_pos = jnp.arange(T)[None, None, :]                   # [1, 1, T]
    causal = t_pos <= positions[:, :, None]                # [B, S, T]
    valid = t_pos < total_lens[:, None, None]              # [B, 1, T]
    mask = (causal & valid)[:, None, :, None, :]           # [B, 1, S, 1, T]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bnsgt,bntd->bsngd", probs, v.astype(jnp.float32))
    return out.reshape(B, S, Hkv * G, Dh)


def _gathered_to_bhtd(g: jnp.ndarray) -> jnp.ndarray:
    """[B, P, Hkv, ps, Dh] gathered pages -> [B, Hkv, T, Dh]."""
    B, P, Hkv, ps, Dh = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, P * ps, Dh)


def paged_attention_layer(q: jnp.ndarray, kv_layer: jnp.ndarray,
                          page_table: jnp.ndarray, positions: jnp.ndarray,
                          total_lens: jnp.ndarray, sm_scale: float
                          ) -> jnp.ndarray:
    """XLA-path attention against one layer's cache.

    q: [B, S, Hq, Dh]; kv_layer: [N, 2, Hkv, ps, Dh] -> [B, S, Hq, Dh]
    """
    B, S, Hq, Dh = q.shape
    Hkv = kv_layer.shape[2]
    gathered = kv_layer[page_table]        # [B, P, 2, Hkv, ps, Dh]
    k = _gathered_to_bhtd(gathered[:, :, 0])
    v = _gathered_to_bhtd(gathered[:, :, 1])
    qg = q.reshape(B, S, Hkv, Hq // Hkv, Dh)
    return _attend(qg, k, v, positions, total_lens,
                   sm_scale).astype(q.dtype)


def paged_attention(q: jnp.ndarray, pages: jnp.ndarray, layer_idx,
                    page_table: jnp.ndarray, positions: jnp.ndarray,
                    total_lens: jnp.ndarray, sm_scale: float) -> jnp.ndarray:
    """Attend queries to the stacked paged context (scan path).

    q:          [B, S, Hq, Dh]
    pages:      [L, N, 2, Hkv, page_size, Dh]
    page_table: [B, P]
    positions:  [B, S] absolute positions of the queries
    total_lens: [B] total context length (cached + new)
    returns     [B, S, Hq, Dh]
    """
    B, S, Hq, Dh = q.shape
    Hkv = pages.shape[3]

    # Single fused gather: the traced layer_idx participates as an advanced
    # index so XLA reads only the gathered pages (slicing pages[layer_idx]
    # first would dynamic-slice-copy the whole layer's cache).
    gathered = pages[layer_idx, page_table]  # [B, P, 2, Hkv, ps, Dh]
    k = _gathered_to_bhtd(gathered[:, :, 0])
    v = _gathered_to_bhtd(gathered[:, :, 1])
    qg = q.reshape(B, S, Hkv, Hq // Hkv, Dh)
    return _attend(qg, k, v, positions, total_lens,
                   sm_scale).astype(q.dtype)


__all__ = ["write_kv", "write_kv_layer", "paged_attention",
           "paged_attention_layer", "NEG_INF"]
