"""Paged attention over a block-paged KV cache — unified prefill/decode path.

Capability parity with the reference's engine-internal paged attention (the
reference delegates this to vLLM/SGLang CUDA kernels; here it is native).
Design is TPU-first:

- The KV cache is ONE stacked array ``pages[L, 2, N, page_size, Hkv, Dh]``
  carried through a ``lax.scan`` over layers, so XLA's while-loop buffer
  aliasing keeps every per-layer scatter in place (no cache copies per step).
- Page 0 is a reserved garbage page: padded token positions write there, which
  makes every scatter shape-static and mask-free.
- One code path serves prefill (S = chunk length) and decode (S = 1): new K/V
  is scattered into the cache first, then the full context is gathered from the
  page table and attended with a causal mask on absolute positions. Chunked
  prefill with a prefix-cache hit falls out for free — queries attend to
  whatever the page table already holds.

The gather materializes ``[B, T, Hkv, Dh]`` per layer; the Pallas decode kernel
(``dynamo_tpu.ops.pallas.paged_decode``) fuses that gather away on TPU. This
XLA path is the portable reference implementation and the CPU-test path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def write_kv(pages: jnp.ndarray, layer_idx, k_new: jnp.ndarray,
             v_new: jnp.ndarray, page_table: jnp.ndarray,
             positions: jnp.ndarray, new_lens: jnp.ndarray) -> jnp.ndarray:
    """Scatter new K/V into the paged cache.

    pages:      [L, 2, N, page_size, Hkv, Dh]
    k_new/v_new:[B, S, Hkv, Dh]
    page_table: [B, P] logical-page -> physical-page map (int32)
    positions:  [B, S] absolute token positions of the new tokens
    new_lens:   [B] number of real (non-pad) new tokens per sequence
    """
    page_size = pages.shape[3]
    B, S = positions.shape
    logical = positions // page_size                       # [B, S]
    slot = positions % page_size                           # [B, S]
    phys = jnp.take_along_axis(page_table, logical, axis=1)  # [B, S]
    # Padded tokens (s >= new_lens[b]) go to the reserved garbage page 0.
    pad = jnp.arange(S)[None, :] >= new_lens[:, None]
    phys = jnp.where(pad, 0, phys)
    slot = jnp.where(pad, 0, slot)
    pages = pages.at[layer_idx, 0, phys, slot].set(
        k_new.astype(pages.dtype), mode="drop")
    pages = pages.at[layer_idx, 1, phys, slot].set(
        v_new.astype(pages.dtype), mode="drop")
    return pages


def paged_attention(q: jnp.ndarray, pages: jnp.ndarray, layer_idx,
                    page_table: jnp.ndarray, positions: jnp.ndarray,
                    total_lens: jnp.ndarray, sm_scale: float) -> jnp.ndarray:
    """Attend queries to the paged context (new K/V must already be written).

    q:          [B, S, Hq, Dh]
    page_table: [B, P]
    positions:  [B, S] absolute positions of the queries
    total_lens: [B] total context length (cached + new)
    returns     [B, S, Hq, Dh]
    """
    B, S, Hq, Dh = q.shape
    page_size = pages.shape[3]
    Hkv = pages.shape[4]
    G = Hq // Hkv
    P = page_table.shape[1]
    T = P * page_size

    # Single fused gather: a traced layer_idx participates as an advanced
    # index, so XLA reads only the gathered pages (indexing pages[layer_idx]
    # first would dynamic-slice-copy the whole layer's cache).
    k = pages[layer_idx, 0, page_table]  # [B, P, page_size, Hkv, Dh]
    v = pages[layer_idx, 1, page_table]
    k = k.reshape(B, T, Hkv, Dh)
    v = v.reshape(B, T, Hkv, Dh)

    qg = q.reshape(B, S, Hkv, G, Dh)
    scores = jnp.einsum("bsngd,btnd->bnsgt", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale  # [B,Hkv,S,G,T]

    t_pos = jnp.arange(T)[None, None, :]                   # [1, 1, T]
    causal = t_pos <= positions[:, :, None]                # [B, S, T]
    valid = t_pos < total_lens[:, None, None]              # [B, 1, T]
    mask = (causal & valid)[:, None, :, None, :]           # [B, 1, S, 1, T]
    scores = jnp.where(mask, scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bnsgt,btnd->bsngd", probs, v.astype(jnp.float32))
    return out.reshape(B, S, Hq, Dh).astype(q.dtype)


__all__ = ["write_kv", "paged_attention", "NEG_INF"]
