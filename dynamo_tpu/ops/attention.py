"""Paged attention over a block-paged KV cache — unified prefill/decode path.

Capability parity with the reference's engine-internal paged attention (the
reference delegates this to vLLM/SGLang CUDA kernels; here it is native).
Design is TPU-first:

- Cache layout is kernel-native: per layer ``[2, Hkv, N, page_size, Dh]``
  (k/v, kv-head-major) — exactly what the Pallas paged decode kernel
  (``ops/pallas/decode.py``) consumes with zero reshuffling, and stacked to
  ``pages[L, 2, Hkv, N, page_size, Dh]`` for the ``lax.scan`` forward where
  XLA's while-loop buffer aliasing keeps every per-layer scatter in place.
- Page 0 is a reserved garbage page: padded token positions write there, which
  makes every scatter shape-static and mask-free.
- One code path serves prefill (S = chunk length) and decode (S = 1): new K/V
  is scattered into the cache first, then the full context is gathered from the
  page table and attended with a causal mask on absolute positions. Chunked
  prefill with a prefix-cache hit falls out for free — queries attend to
  whatever the page table already holds.

The XLA gather path materializes ``[B, T, Hkv, Dh]`` per layer; the Pallas
decode kernel fuses that gather away on TPU. This XLA path is the portable
reference implementation and the CPU-test path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def write_kv_layer(kv_layer: jnp.ndarray, k_new: jnp.ndarray,
                   v_new: jnp.ndarray, page_table: jnp.ndarray,
                   positions: jnp.ndarray, new_lens: jnp.ndarray) -> jnp.ndarray:
    """Scatter new K/V into one layer's paged cache.

    kv_layer:   [2, Hkv, N, page_size, Dh]
    k_new/v_new:[B, S, Hkv, Dh]
    page_table: [B, P] logical-page -> physical-page map (int32)
    positions:  [B, S] absolute token positions of the new tokens
    new_lens:   [B] number of real (non-pad) new tokens per sequence
    """
    page_size = kv_layer.shape[3]
    B, S = positions.shape
    logical = positions // page_size                       # [B, S]
    slot = positions % page_size                           # [B, S]
    phys = jnp.take_along_axis(page_table, logical, axis=1)  # [B, S]
    # Padded tokens (s >= new_lens[b]) go to the reserved garbage page 0.
    pad = jnp.arange(S)[None, :] >= new_lens[:, None]
    phys = jnp.where(pad, 0, phys)
    slot = jnp.where(pad, 0, slot)
    # (phys, slot) are contiguous advanced indices, so their broadcast dims
    # stay in place: the scatter slice is [2, Hkv, B, S, Dh]
    new = jnp.stack([k_new, v_new]).transpose(0, 3, 1, 2, 4)
    return kv_layer.at[:, :, phys, slot].set(new.astype(kv_layer.dtype),
                                             mode="drop")


def write_kv(pages: jnp.ndarray, layer_idx, k_new: jnp.ndarray,
             v_new: jnp.ndarray, page_table: jnp.ndarray,
             positions: jnp.ndarray, new_lens: jnp.ndarray) -> jnp.ndarray:
    """Scatter new K/V into the stacked cache ``[L, 2, Hkv, N, ps, Dh]``."""
    page_size = pages.shape[4]
    B, S = positions.shape
    logical = positions // page_size
    slot = positions % page_size
    phys = jnp.take_along_axis(page_table, logical, axis=1)
    pad = jnp.arange(S)[None, :] >= new_lens[:, None]
    phys = jnp.where(pad, 0, phys)
    slot = jnp.where(pad, 0, slot)
    # layer_idx and (phys, slot) are separated by slices, so the advanced
    # dims [B, S] move to the FRONT of the scatter slice: value layout is
    # [B, S, 2, Hkv, Dh]
    new = jnp.stack([k_new, v_new]).transpose(1, 2, 0, 3, 4)
    return pages.at[layer_idx, :, :, phys, slot].set(
        new.astype(pages.dtype), mode="drop")


def _attend(qg: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
            positions: jnp.ndarray, total_lens: jnp.ndarray,
            sm_scale: float) -> jnp.ndarray:
    """qg [B,S,Hkv,G,Dh]; k/v [B,Hkv,T,Dh] -> [B,S,Hkv*G,Dh]."""
    B, S, Hkv, G, Dh = qg.shape
    T = k.shape[2]
    scores = jnp.einsum("bsngd,bntd->bnsgt", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale  # [B,Hkv,S,G,T]
    t_pos = jnp.arange(T)[None, None, :]                   # [1, 1, T]
    causal = t_pos <= positions[:, :, None]                # [B, S, T]
    valid = t_pos < total_lens[:, None, None]              # [B, 1, T]
    mask = (causal & valid)[:, None, :, None, :]           # [B, 1, S, 1, T]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bnsgt,bntd->bsngd", probs, v.astype(jnp.float32))
    return out.reshape(B, S, Hkv * G, Dh)


def paged_attention_layer(q: jnp.ndarray, kv_layer: jnp.ndarray,
                          page_table: jnp.ndarray, positions: jnp.ndarray,
                          total_lens: jnp.ndarray, sm_scale: float
                          ) -> jnp.ndarray:
    """XLA-path attention against one layer's cache.

    q: [B, S, Hq, Dh]; kv_layer: [2, Hkv, N, ps, Dh] -> [B, S, Hq, Dh]
    """
    B, S, Hq, Dh = q.shape
    Hkv, _N, page_size, _ = kv_layer.shape[1:]
    P = page_table.shape[1]
    T = P * page_size
    k = kv_layer[0][:, page_table]  # [Hkv, B, P, ps, Dh]
    v = kv_layer[1][:, page_table]
    k = k.transpose(1, 0, 2, 3, 4).reshape(B, Hkv, T, Dh)
    v = v.transpose(1, 0, 2, 3, 4).reshape(B, Hkv, T, Dh)
    qg = q.reshape(B, S, Hkv, Hq // Hkv, Dh)
    return _attend(qg, k, v, positions, total_lens,
                   sm_scale).astype(q.dtype)


def paged_attention(q: jnp.ndarray, pages: jnp.ndarray, layer_idx,
                    page_table: jnp.ndarray, positions: jnp.ndarray,
                    total_lens: jnp.ndarray, sm_scale: float) -> jnp.ndarray:
    """Attend queries to the stacked paged context (scan path).

    q:          [B, S, Hq, Dh]
    pages:      [L, 2, Hkv, N, page_size, Dh]
    page_table: [B, P]
    positions:  [B, S] absolute positions of the queries
    total_lens: [B] total context length (cached + new)
    returns     [B, S, Hq, Dh]
    """
    B, S, Hq, Dh = q.shape
    Hkv = pages.shape[2]
    page_size = pages.shape[4]
    P = page_table.shape[1]
    T = P * page_size

    # Single fused gather: the traced layer_idx participates as an advanced
    # index so XLA reads only the gathered pages (slicing pages[layer_idx]
    # first would dynamic-slice-copy the whole layer's cache).
    # Advanced-index result: [B, P, ps, Dh] per k/v with Hkv slicing -> use
    # explicit gather over (layer, kv, head, page).
    k = pages[layer_idx, 0, :, page_table]  # [B, P, Hkv, ps, Dh]
    v = pages[layer_idx, 1, :, page_table]
    k = k.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, T, Dh)
    v = v.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, T, Dh)
    qg = q.reshape(B, S, Hkv, Hq // Hkv, Dh)
    return _attend(qg, k, v, positions, total_lens,
                   sm_scale).astype(q.dtype)


__all__ = ["write_kv", "write_kv_layer", "paged_attention",
           "paged_attention_layer", "NEG_INF"]
