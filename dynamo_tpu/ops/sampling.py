"""Vectorized on-device token sampling.

One jittable ``sample_tokens`` handles a whole decode batch with *per-request*
temperature / top-k / top-p (the reference forwards these to vLLM's sampler;
here they run natively on TPU).

Strategy: gather the static ``TOPK_MAX`` highest logits once (``lax.top_k``),
then apply per-request top-k and top-p masks inside that candidate set and draw
via Gumbel-max. Greedy requests (temperature == 0) take candidate 0. Restricting
sampling to the top ``TOPK_MAX=64`` candidates is exact for any top_k <= 64 and
an excellent approximation otherwise (tail mass beyond the top 64 is noise for
served models); it keeps the sampler free of full-vocab sorts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

TOPK_MAX = 64


@dataclass
class SamplingParamsBatch:
    """Host-side batch of per-request sampling parameters (device-ready)."""

    temperature: np.ndarray  # [B] f32, 0 => greedy
    top_k: np.ndarray        # [B] i32, 0 => disabled
    top_p: np.ndarray        # [B] f32, 1.0 => disabled

    @classmethod
    def build(cls, temps: List[float], top_ks: List[Optional[int]],
              top_ps: List[Optional[float]]) -> "SamplingParamsBatch":
        return cls(
            temperature=np.asarray(temps, dtype=np.float32),
            top_k=np.asarray([k if k and k > 0 else 0 for k in top_ks],
                             dtype=np.int32),
            top_p=np.asarray([p if p is not None else 1.0 for p in top_ps],
                             dtype=np.float32),
        )

    @classmethod
    def greedy(cls, batch: int) -> "SamplingParamsBatch":
        return cls(temperature=np.zeros(batch, np.float32),
                   top_k=np.zeros(batch, np.int32),
                   top_p=np.ones(batch, np.float32))


def apply_penalties(logits: jnp.ndarray, pen_ids: jnp.ndarray,
                    pen_counts: jnp.ndarray, pen_in_ctx: jnp.ndarray,
                    freq_pen: jnp.ndarray, pres_pen: jnp.ndarray,
                    rep_pen: jnp.ndarray,
                    pen_bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Frequency / presence / repetition penalties on device.

    The host ships each row's penalized token ids as a SPARSE window
    (ids unique per row, zero-padded with count 0 / in_ctx 0 so pad
    entries contribute a zero delta — scatter-ADD makes duplicate pad
    writes safe):

    pen_ids:    [B, W] i32 token ids
    pen_counts: [B, W] f32 occurrences among GENERATED tokens
                (frequency/presence semantics, vLLM/OpenAI)
    pen_in_ctx: [B, W] f32 1.0 if the token appears in prompt+generated
                (repetition-penalty semantics, HF: divide positive /
                multiply negative logits)
    freq_pen/pres_pen: [B] f32 (0 = off); rep_pen: [B] f32 (1 = off)
    pen_bias:   optional [B, W] f32 OpenAI logit_bias, added
                unconditionally per entry (0 on pads)
    """
    if pen_ids.shape[1] == 0:
        return logits
    logits = logits.astype(jnp.float32)
    sel = jnp.take_along_axis(logits, pen_ids, axis=1)     # [B, W]
    rp = jnp.where(rep_pen[:, None] <= 0, 1.0, rep_pen[:, None])
    adj = jnp.where(pen_in_ctx > 0,
                    jnp.where(sel > 0, sel / rp, sel * rp), sel)
    adj = adj - freq_pen[:, None] * pen_counts
    adj = adj - pres_pen[:, None] * (pen_counts > 0)
    if pen_bias is not None:
        adj = adj + pen_bias
    delta = adj - sel                                      # 0 on pads
    rows = jnp.arange(logits.shape[0])[:, None]
    return logits.at[rows, pen_ids].add(delta)


def update_penalty_window(pen_ids: jnp.ndarray, pen_counts: jnp.ndarray,
                          pen_in_ctx: jnp.ndarray, pen_n: jnp.ndarray,
                          tokens: jnp.ndarray, active: jnp.ndarray):
    """One fused-decode step of the device-resident penalty window.

    The fused multistep block keeps each row's penalty entries as a
    fixed-capacity window riding the scan carry; after a token is
    sampled this folds it in without leaving the device:

      - a token already in the row's window (first ``pen_n`` slots) gets
        its count bumped and is marked in-context;
      - a new token is appended at slot ``pen_n`` (count 1, in-context)
        when capacity remains — the scheduler's width gate guarantees a
        fused block never sees the window fill mid-block, so the
        saturation branch is unreachable on planned traffic.

    Inserts never touch the bias column: new slots keep the zero pad,
    and all logit-bias entries are preloaded before the block starts, so
    an insert can never collide with a biased slot.

    pen_ids/pen_counts/pen_in_ctx: [B, W] as ``apply_penalties``
    pen_n:  [B] i32 occupied slots per row
    tokens: [B] i32 tokens just sampled
    active: [B] bool rows whose window should absorb the token
            (alive AND carrying penalties/bias)
    Returns the four updated window arrays.
    """
    W = pen_ids.shape[1]
    if W == 0:
        return pen_ids, pen_counts, pen_in_ctx, pen_n
    occ = jnp.arange(W)[None, :] < pen_n[:, None]            # [B, W]
    match = (pen_ids == tokens[:, None]) & occ
    bump = match & active[:, None]
    pen_counts = pen_counts + bump.astype(pen_counts.dtype)
    pen_in_ctx = jnp.maximum(pen_in_ctx, bump.astype(pen_in_ctx.dtype))
    can_ins = active & ~jnp.any(match, axis=1) & (pen_n < W)
    slot = (jnp.arange(W)[None, :] == pen_n[:, None]) & can_ins[:, None]
    pen_ids = jnp.where(slot, tokens[:, None], pen_ids)
    pen_counts = jnp.where(slot, jnp.ones_like(pen_counts), pen_counts)
    pen_in_ctx = jnp.where(slot, jnp.ones_like(pen_in_ctx), pen_in_ctx)
    pen_n = pen_n + can_ins.astype(pen_n.dtype)
    return pen_ids, pen_counts, pen_in_ctx, pen_n


def penalty_window_entries(prompt_ids: jnp.ndarray, prompt_valid: jnp.ndarray,
                           pen_ids: jnp.ndarray,
                           pen_n: jnp.ndarray) -> jnp.ndarray:
    """Which static prompt entries the fused penalty step should include.

    The per-step host builder backfills a penalized row's window with
    distinct prompt tokens (repetition-penalty context) after the
    generated/bias entries, up to capacity ``W``. On device the prompt
    side is a STATIC list shipped once per batch composition
    (``prompt_ids``/``prompt_valid``, deduped reverse-prompt order, 2W
    entries — enough that at least W survive any overlap with the
    dynamic window); each step this recomputes which of them the host
    would have kept: not already in the dynamic window's first ``pen_n``
    slots, and within the ``W - pen_n`` remaining capacity, first come
    first served.

    Returns an [B, S] bool include mask; included entries are applied
    with count 0 / in-context 1 / bias 0, excluded ones pad to a zero
    delta under ``apply_penalties``.
    """
    W = pen_ids.shape[1]
    occ = jnp.arange(W)[None, None, :] < pen_n[:, None, None]
    in_dyn = jnp.any(
        (prompt_ids[:, :, None] == pen_ids[:, None, :]) & occ, axis=2)
    eligible = prompt_valid & ~in_dyn                        # [B, S]
    rank = jnp.cumsum(eligible.astype(jnp.int32), axis=1) \
        - eligible.astype(jnp.int32)                         # exclusive
    return eligible & (pen_n[:, None] + rank < W)


def _masked_candidates(logits: jnp.ndarray, temperature: jnp.ndarray,
                       top_k: jnp.ndarray, top_p: jnp.ndarray,
                       min_p: Optional[jnp.ndarray] = None):
    """Shared candidate filter of every sampling path.

    logits: [R, V] f32; per-row temperature/top_k/top_p ([R]).
    Returns (scaled [R, k], top_idx [R, k]) where ``scaled`` is the
    temperature-scaled logits over the top ``k`` candidates with the
    per-row top-k / top-p / min-p rejects set to -inf — ``softmax(scaled)``
    is the exact distribution sampling draws from, and Gumbel-argmax over
    ``scaled`` draws from it without materializing the softmax.
    """
    R, V = logits.shape
    k = min(TOPK_MAX, V)
    top_vals, top_idx = jax.lax.top_k(logits, k)          # [R, k]

    ranks = jnp.arange(k)[None, :]                        # [1, k]
    eff_k = jnp.where(top_k > 0, jnp.minimum(top_k, k), k)  # [R]
    keep = ranks < eff_k[:, None]

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = top_vals / temp
    scaled = jnp.where(keep, scaled, -jnp.inf)
    probs = jax.nn.softmax(scaled, axis=-1)
    # top-p: keep the smallest prefix of candidates whose cumulative
    # probability reaches top_p (always keep the first).
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (cum - probs) < top_p[:, None]
    if min_p is not None:
        # min_p (vLLM semantics): drop candidates whose post-temperature
        # probability falls below min_p x the best candidate's (0 = off;
        # candidate 0 always survives: probs[...,0] is the max)
        keep_p &= probs >= min_p[:, None] * probs[:, :1]
    return jnp.where(keep_p, scaled, -jnp.inf), top_idx


def apply_vocab_mask(logits: jnp.ndarray,
                     mask_words: jnp.ndarray) -> jnp.ndarray:
    """Guided-decoding allow-mask, unpacked on device.

    The host ships each row's allowed-token set as a uint32 bitfield
    ``[B, ceil(V/32)]`` (~4 KB/row at 32k vocab — vs 128 KB for a f32
    mask); the bits are expanded with a gather + shift/and here, inside
    the jitted step. An all-ones row (0xFFFFFFFF words) is the compiled-in
    no-op for unconstrained rows sharing a batch with constrained ones.
    """
    B, V = logits.shape
    idx = jnp.arange(V, dtype=jnp.int32)
    words = mask_words[:, idx // 32]                      # [B, V] u32
    bits = (words >> (idx % 32).astype(jnp.uint32)) & jnp.uint32(1)
    return jnp.where(bits.astype(bool), logits.astype(jnp.float32),
                     -jnp.inf)


def sample_tokens(logits: jnp.ndarray, rng: jax.Array,
                  temperature: jnp.ndarray, top_k: jnp.ndarray,
                  top_p: jnp.ndarray, seeds: Optional[jnp.ndarray] = None,
                  seed_rng: Optional[jax.Array] = None,
                  seed_pos: Optional[jnp.ndarray] = None,
                  min_p: Optional[jnp.ndarray] = None):
    """Sample next tokens.

    logits: [B, V] (any float dtype; promoted to f32)
    seeds:  optional [B] i32 per-request seeds (0 = unseeded). A seeded
            row's randomness depends only on (base engine rng, seed, the
            row's TOKEN POSITION ``seed_pos``) — not on its batch position,
            the global step counter, or what it was batched with — so a
            seeded request replays deterministically under any concurrency.
    seed_rng: the engine's BASE key (pre step-fold); required with seeds.
    seed_pos: [B] i32 position of the token being sampled per row.
    returns (tokens [B] i32, logprobs [B] f32 — logprob of the chosen
    token under the GIVEN logits before temperature/top-k/top-p (matching
    OpenAI logprobs semantics; when the engine applies penalties upstream,
    the reported logprobs reflect that penalized distribution — the one
    actually sampled from).
    """
    logits = logits.astype(jnp.float32)
    B, V = logits.shape
    k = min(TOPK_MAX, V)
    scaled, top_idx = _masked_candidates(logits, temperature, top_k, top_p,
                                         min_p)

    if seeds is None:
        gumbel = jax.random.gumbel(rng, (B, k), dtype=jnp.float32)
    else:
        # per-row keys: unseeded rows fold their batch position (rows stay
        # independent), seeded rows fold ONLY the seed (batch-invariant)
        def draw(key):
            return jax.random.gumbel(key, (k,), dtype=jnp.float32)

        g_row = jax.vmap(lambda r: draw(
            jax.random.fold_in(jax.random.fold_in(rng, 7), r)))(
            jnp.arange(B))
        base = rng if seed_rng is None else seed_rng
        pos = (jnp.zeros(B, jnp.uint32) if seed_pos is None
               else seed_pos.astype(jnp.uint32))
        g_seed = jax.vmap(lambda s, p: draw(jax.random.fold_in(
            jax.random.fold_in(base, s), p)))(
            seeds.astype(jnp.uint32), pos)
        gumbel = jnp.where((seeds != 0)[:, None], g_seed, g_row)
    choice = jnp.argmax(scaled + gumbel, axis=-1)          # [B]
    greedy = temperature <= 0.0
    choice = jnp.where(greedy, 0, choice)
    tokens = jnp.take_along_axis(top_idx, choice[:, None], axis=1)[:, 0]

    logz = jax.nn.logsumexp(logits, axis=-1)
    chosen_logit = jnp.take_along_axis(logits, tokens[:, None], axis=1)[:, 0]
    return tokens.astype(jnp.int32), chosen_logit - logz


def spec_verify(logits: jnp.ndarray, tokens: jnp.ndarray, rng: jax.Array,
                temperature: jnp.ndarray, top_k: jnp.ndarray,
                top_p: jnp.ndarray,
                mask_words: Optional[jnp.ndarray] = None):
    """Exact rejection-sampling verification of drafted tokens, one pass.

    The speculative-decode acceptance rule (Leviathan et al.) with a
    DETERMINISTIC proposal (the n-gram draft is a point mass): draft ``d``
    at a position with target distribution ``p`` is accepted with
    probability ``p(d)``, and on rejection the replacement is drawn from
    ``p`` with ``d`` excluded, renormalized — together these sample exactly
    from ``p``. Greedy rows (temperature 0) degenerate to "accept while the
    draft equals the argmax", so greedy output is bit-identical with
    speculation on or off. ``p`` here is the FILTERED distribution
    (temperature/top-k/top-p via ``_masked_candidates``) — the same one
    ``sample_tokens`` draws from.

    logits: [B, S, V] — logits[:, j] is the next-token distribution after
            consuming chunk slot j (predicts the token at slot j+1)
    tokens: [B, S] the fed tokens; tokens[:, 0] is the last accepted
            context token, tokens[:, j] (j >= 1) is draft j
    mask_words: optional [B, S, ceil(V/32)] uint32 guided-decoding
            allow-masks, one PER CHUNK SLOT (the host walks the grammar
            automaton along the draft path, so slot j's mask reflects the
            state after drafts 1..j). Applied to the logits before
            filtering, exactly like the plain path — a mask-illegal draft
            gets probability 0 and is rejected, and the replacement /
            bonus draw is masked by its own slot's state. Reported
            logprobs are then under the MASKED distribution (the one
            actually sampled from), matching the plain guided path.
    returns (n_acc [B] i32 accepted drafts in [0, K],
             final_tok [B] i32 — the rejection replacement, or the bonus
             token sampled after all K drafts accepted,
             final_lp [B] f32 logprob of final_tok under its UNfiltered
             row logits (OpenAI logprob semantics, as sample_tokens),
             draft_lps [B, K] f32 logprobs of each draft at its position)
    """
    lf = logits.astype(jnp.float32)
    B, S, V = lf.shape
    K = S - 1
    if mask_words is not None:
        lf = apply_vocab_mask(
            lf.reshape(B * S, V),
            mask_words.reshape(B * S, -1)).reshape(B, S, V)
    k = min(TOPK_MAX, V)
    rep = lambda a: jnp.repeat(a, S, axis=0)  # noqa: E731  [B] -> [B*S]
    scaled, top_idx = _masked_candidates(
        lf.reshape(B * S, V), rep(temperature), rep(top_k), rep(top_p))
    scaled = scaled.reshape(B, S, k)
    top_idx = top_idx.reshape(B, S, k)
    q = jax.nn.softmax(scaled, axis=-1)                   # filtered probs

    drafts = tokens[:, 1:]                                # [B, K]
    in_cand = top_idx[:, :K] == drafts[..., None]         # [B, K, k]
    p_draft = jnp.sum(jnp.where(in_cand, q[:, :K], 0.0), axis=-1)

    k_u, k_g = jax.random.split(jax.random.fold_in(rng, 0x5bec))
    u = jax.random.uniform(k_u, (B, K), dtype=jnp.float32)
    greedy = (temperature <= 0.0)[:, None]
    acc = jnp.where(greedy, drafts == top_idx[:, :K, 0], u < p_draft)
    n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1),
                    axis=1).astype(jnp.int32)             # [B] in [0, K]

    # final token from chunk slot n_acc: the rejection position, or slot K
    # (the bonus draw) when everything was accepted
    sel = n_acc[:, None, None]
    scaled_a = jnp.take_along_axis(scaled, sel, axis=1)[:, 0]   # [B, k]
    idx_a = jnp.take_along_axis(top_idx, sel, axis=1)[:, 0]     # [B, k]
    d_rej = jnp.take_along_axis(drafts, jnp.minimum(n_acc, K - 1)[:, None],
                                axis=1)[:, 0] if K > 0 else None
    if d_rej is not None:
        # residual of a rejection excludes the draft; a bonus draw does not
        excl = (idx_a == d_rej[:, None]) & (n_acc < K)[:, None]
        scaled_a = jnp.where(excl, -jnp.inf, scaled_a)
    gumbel = jax.random.gumbel(k_g, (B, k), dtype=jnp.float32)
    choice = jnp.argmax(scaled_a + gumbel, axis=-1)
    # greedy: candidate 0 is correct for both cases — a greedy rejection
    # means the draft was NOT candidate 0, so the exclusion never hides it
    choice = jnp.where(temperature <= 0.0, 0, choice)
    final_tok = jnp.take_along_axis(idx_a, choice[:, None], axis=1)[:, 0]

    logz = jax.nn.logsumexp(lf, axis=-1)                  # [B, S]
    if K > 0:
        d_logit = jnp.take_along_axis(lf[:, :K], drafts[..., None],
                                      axis=2)[..., 0]     # [B, K]
        draft_lps = d_logit - logz[:, :K]
    else:
        draft_lps = jnp.zeros((B, 0), jnp.float32)
    lf_a = jnp.take_along_axis(lf, sel, axis=1)[:, 0]     # [B, V]
    logz_a = jnp.take_along_axis(logz, n_acc[:, None], axis=1)[:, 0]
    f_logit = jnp.take_along_axis(lf_a, final_tok[:, None], axis=1)[:, 0]
    return (n_acc, final_tok.astype(jnp.int32), f_logit - logz_a, draft_lps)


__all__ = ["SamplingParamsBatch", "sample_tokens", "apply_penalties",
           "apply_vocab_mask", "update_penalty_window",
           "penalty_window_entries", "spec_verify", "TOPK_MAX"]
