"""Vectorized on-device token sampling.

One jittable ``sample_tokens`` handles a whole decode batch with *per-request*
temperature / top-k / top-p (the reference forwards these to vLLM's sampler;
here they run natively on TPU).

Strategy: gather the static ``TOPK_MAX`` highest logits once (``lax.top_k``),
then apply per-request top-k and top-p masks inside that candidate set and draw
via Gumbel-max. Greedy requests (temperature == 0) take candidate 0. Restricting
sampling to the top ``TOPK_MAX=64`` candidates is exact for any top_k <= 64 and
an excellent approximation otherwise (tail mass beyond the top 64 is noise for
served models); it keeps the sampler free of full-vocab sorts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

TOPK_MAX = 64


@dataclass
class SamplingParamsBatch:
    """Host-side batch of per-request sampling parameters (device-ready)."""

    temperature: np.ndarray  # [B] f32, 0 => greedy
    top_k: np.ndarray        # [B] i32, 0 => disabled
    top_p: np.ndarray        # [B] f32, 1.0 => disabled

    @classmethod
    def build(cls, temps: List[float], top_ks: List[Optional[int]],
              top_ps: List[Optional[float]]) -> "SamplingParamsBatch":
        return cls(
            temperature=np.asarray(temps, dtype=np.float32),
            top_k=np.asarray([k if k and k > 0 else 0 for k in top_ks],
                             dtype=np.int32),
            top_p=np.asarray([p if p is not None else 1.0 for p in top_ps],
                             dtype=np.float32),
        )

    @classmethod
    def greedy(cls, batch: int) -> "SamplingParamsBatch":
        return cls(temperature=np.zeros(batch, np.float32),
                   top_k=np.zeros(batch, np.int32),
                   top_p=np.ones(batch, np.float32))


def sample_tokens(logits: jnp.ndarray, rng: jax.Array,
                  temperature: jnp.ndarray, top_k: jnp.ndarray,
                  top_p: jnp.ndarray):
    """Sample next tokens.

    logits: [B, V] (any float dtype; promoted to f32)
    returns (tokens [B] i32, logprobs [B] f32 — logprob of the chosen token
    under the *unmodified* distribution, matching OpenAI logprobs semantics).
    """
    logits = logits.astype(jnp.float32)
    B, V = logits.shape
    k = min(TOPK_MAX, V)
    top_vals, top_idx = jax.lax.top_k(logits, k)          # [B, k]

    ranks = jnp.arange(k)[None, :]                        # [1, k]
    eff_k = jnp.where(top_k > 0, jnp.minimum(top_k, k), k)  # [B]
    keep = ranks < eff_k[:, None]

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = top_vals / temp
    scaled = jnp.where(keep, scaled, -jnp.inf)
    probs = jax.nn.softmax(scaled, axis=-1)
    # top-p: keep the smallest prefix of candidates whose cumulative
    # probability reaches top_p (always keep the first).
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (cum - probs) < top_p[:, None]
    scaled = jnp.where(keep_p, scaled, -jnp.inf)

    gumbel = jax.random.gumbel(rng, (B, k), dtype=jnp.float32)
    choice = jnp.argmax(scaled + gumbel, axis=-1)          # [B]
    greedy = temperature <= 0.0
    choice = jnp.where(greedy, 0, choice)
    tokens = jnp.take_along_axis(top_idx, choice[:, None], axis=1)[:, 0]

    logz = jax.nn.logsumexp(logits, axis=-1)
    chosen_logit = jnp.take_along_axis(logits, tokens[:, None], axis=1)[:, 0]
    return tokens.astype(jnp.int32), chosen_logit - logz


__all__ = ["SamplingParamsBatch", "sample_tokens", "TOPK_MAX"]
