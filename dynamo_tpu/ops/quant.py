"""Int8 quantized serving (W8A8 dynamic) for the decode-bound hot path.

Decode throughput on TPU is HBM-bandwidth-bound: every step streams the
full parameter set (BASELINE.md roofline model; at the bench's Llama-3B
config the params are ~6.4 GB of the ~7.5 GB step traffic). Symmetric
int8 weights halve that stream, and int8×int8 ``lax.dot_general`` with
``preferred_element_type=int32`` lowers onto the MXU's double-rate int8
path on v5e — bandwidth AND compute both improve, which is why this is
the standard TPU serving quantization (the reference's H100 recipes lean
on FP8 for the same reason: ``docs/architecture/architecture.md``'s
R1-Distill-Llama-70B **FP8** baselines, served by vLLM/SGLang quantized
engines; TPU MXUs have no FP8, int8 is the native equivalent).

Scheme (calibration-free, load-time):
- **Weights**: symmetric per-out-channel absmax over the contraction
  axis: ``w8[k, n] = round(w[k, n] / s_w[n])``, ``s_w = absmax_k / 127``.
  Stored stacked ``[L, K, N] int8`` + ``[L, N] f32`` — the layer dicts
  scan exactly like the bf16 ones.
- **Activations**: dynamic symmetric per-token absmax (computed inside
  the step, fused by XLA; no calibration pass): W8A8-dynamic, the same
  trade vLLM ships as "w8a8 dynamic" int8.
- Accumulation in int32, rescale ``y * s_x * s_w`` in f32, cast back.

Tied embeddings are NOT quantized (the embed table doubles as lm_head;
the gather path wants the bf16 rows), and norms/biases stay bf16 — they
are O(H) a step, noise next to the matmuls.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# stacked [L, in, out] layer weights that quantize (llama family tree —
# llama 2/3, mistral, qwen2/3 — which shares these exact names; the MoE
# and MLA families keep bf16 until their expert/latent paths opt in)
LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

_EPS = 1e-30


def quantize_weight(w: jnp.ndarray, axis: int):
    """Symmetric per-channel int8: absmax over ``axis`` (the contraction
    dim), one f32 scale per remaining channel. Returns ``(w8, scale)``
    with ``scale`` shaped like ``w`` minus ``axis``."""
    a = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis)
    scale = jnp.maximum(a, _EPS) / 127.0
    w8 = jnp.round(w.astype(jnp.float32)
                   / jnp.expand_dims(scale, axis)).astype(jnp.int8)
    return w8, scale


def qdot(x: jnp.ndarray, w8: jnp.ndarray, w_scale: jnp.ndarray,
         out_dtype=None) -> jnp.ndarray:
    """``x @ w`` with int8 weights and dynamic per-token int8 activations.

    x: [..., K] (any float dtype); w8: [K, N] int8; w_scale: [N] f32.
    The int8×int8 contraction accumulates in int32 on the MXU; the two
    scales re-enter in f32 and the result is cast to ``out_dtype``
    (default ``x.dtype``). The logits call sites pass f32 so the final
    projection keeps full-precision accumulation like the bf16 path.
    """
    xf = x.astype(jnp.float32)
    s_x = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                      _EPS) / 127.0                       # [..., 1]
    x8 = jnp.round(xf / s_x).clip(-127, 127).astype(jnp.int8)
    y = jax.lax.dot_general(
        x8, w8, (((x8.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                 # [..., N] i32
    return (y.astype(jnp.float32) * s_x * w_scale).astype(
        out_dtype or x.dtype)


def quantize_params(params: Params) -> Params:
    """Load-time transform of a llama-family param tree to int8 weights.

    Each stacked layer matrix ``name [L, K, N]`` is replaced by
    ``name+"_q" [L, K, N] int8`` and ``name+"_scale" [L, N] f32``; the
    bf16 original is dropped (that is the memory/bandwidth win). An
    untied ``lm_head [K, N]`` quantizes the same way. Norms, biases,
    qk-norms and the embedding table pass through unchanged — the
    forward helpers dispatch on the ``_q`` suffix per weight, so partial
    trees (e.g. tied embeddings) stay correct.
    """
    out = dict(params)
    layers = dict(params["layers"])
    for name in LAYER_WEIGHTS:
        w = layers.pop(name, None)
        if w is None:
            continue
        w8, scale = quantize_weight(w, axis=1)            # [L, K, N] over K
        layers[name + "_q"] = w8
        layers[name + "_scale"] = scale
    out["layers"] = layers
    lm = params.get("lm_head")
    if lm is not None:
        w8, scale = quantize_weight(lm, axis=0)           # [K, N] over K
        out.pop("lm_head")
        out["lm_head_q"] = w8
        out["lm_head_scale"] = scale
    return out


def mm(lp: Dict[str, jnp.ndarray], name: str, x: jnp.ndarray
       ) -> jnp.ndarray:
    """``x @ lp[name]``, transparently using the int8 pair when the tree
    was quantized. The single call site shape the llama-family forwards
    share (``models/llama.py``)."""
    w8 = lp.get(name + "_q")
    if w8 is not None:
        return qdot(x, w8, lp[name + "_scale"])
    return x @ lp[name]


__all__ = ["LAYER_WEIGHTS", "mm", "qdot", "quantize_params",
           "quantize_weight"]
