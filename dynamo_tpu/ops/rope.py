"""Rotary position embeddings (HF half-split convention).

Uses the ``rotate_half`` formulation so weights loaded from HF checkpoints
(llama/mistral/qwen) produce identical activations: for a head vector split
into halves ``[x1, x2]``, ``rope(x) = x * cos + [-x2, x1] * sin`` with
``cos/sin`` built from ``inv_freq = theta^(-2i/d)`` and tiled twice.

Computed on the fly from positions (no precomputed table): a decode step's
positions are dynamic, and the trig is negligible next to the matmuls.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(positions: jnp.ndarray, head_dim: int, theta: float,
                 dtype=jnp.float32):
    """cos/sin tables for absolute ``positions`` (any shape), returned with a
    trailing ``head_dim`` axis: shape ``positions.shape + (head_dim,)``."""
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # (..., half)
    angles = jnp.concatenate([angles, angles], axis=-1)  # (..., head_dim)
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotate ``x`` of shape ``[B, S, num_heads, head_dim]`` by per-token
    absolute ``positions`` of shape ``[B, S]``."""
    cos, sin = rope_cos_sin(positions, x.shape[-1], theta)
    cos = cos[:, :, None, :]  # broadcast over heads
    sin = sin[:, :, None, :]
    out = x.astype(jnp.float32) * cos + _rotate_half(x.astype(jnp.float32)) * sin
    return out.astype(x.dtype)


__all__ = ["rope_cos_sin", "apply_rope"]
