"""GSPMD shardings for the Llama-family pytree and the paged KV cache.

Tensor parallelism the XLA way (reference: ``--tensor-parallel-size`` handed
to vLLM's NCCL Megatron kernels, SURVEY §2.7): annotate the weight shardings,
keep activations replicated-per-``dp``-shard, and let the partitioner insert
the two all-reduces per layer (after attention out-proj and after mlp
down-proj) on ICI.

Layout (params carry a leading ``L`` layer axis from the ``lax.scan`` stack):

- ``wq/wk/wv`` ``[L, H, out]``  — shard ``out`` (head) dim over ``tp``
- ``wo``       ``[L, q, H]``    — shard ``q`` (head) dim over ``tp``
- ``w_gate/w_up`` ``[L, H, I]`` — shard ``I`` over ``tp``
- ``w_down``   ``[L, I, H]``    — shard ``I`` over ``tp``
- ``embed``    ``[V, H]``       — replicated (all-gather-free lookup)
- ``lm_head``  ``[H, V]``       — shard ``V`` over ``tp`` (logits sharded,
  top-k/sampling runs fine on sharded logits)
- KV pages ``[L, N, 2, Hkv, page, Dh]`` (stacked) or per-layer
  ``[N, 2, Hkv, page, Dh]`` — shard ``Hkv`` over ``tp``; each chip holds its
  own heads' slice of every page, so paged writes/gathers (and the Pallas
  decode kernel's page DMAs) are chip-local.

``num_kv_heads`` must be divisible by ``tp`` (e.g. Llama-3-8B: 8 KV heads →
tp ∈ {1,2,4,8}); for tp > Hkv one would replicate KV heads — rejected for
now with a clear error.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshSpec, make_mesh


class ModelSharding:
    """Sharding specs bound to a mesh for one model configuration."""

    def __init__(self, cfg: ModelConfig, mesh: Mesh):
        self.cfg = cfg
        self.mesh = mesh
        tp = mesh.shape.get("tp", 1)
        ep = mesh.shape.get("ep", 1)
        if tp > 1:
            if cfg.kv_lora_rank:
                # MLA: tp splits the QUERY heads (the latent cache is
                # shared/replicated), so num_heads is the constraint
                if cfg.num_heads % tp:
                    raise ValueError(
                        f"num_heads={cfg.num_heads} not divisible by "
                        f"tp={tp}")
            elif cfg.num_kv_heads % tp:
                raise ValueError(
                    f"num_kv_heads={cfg.num_kv_heads} not divisible by tp={tp}")
            if cfg.intermediate_size % tp:
                raise ValueError(
                    f"intermediate_size={cfg.intermediate_size} not divisible "
                    f"by tp={tp}")
            if cfg.num_experts:
                # both MoE spec families shard the expert FFN width over tp
                moe_i = cfg.moe_intermediate_size or cfg.intermediate_size
                if moe_i % tp:
                    raise ValueError(
                        f"moe_intermediate_size={moe_i} not divisible "
                        f"by tp={tp}")
        if ep > 1 and cfg.num_experts % ep:
            raise ValueError(
                f"num_experts={cfg.num_experts} not divisible by ep={ep}")

    # -- specs -------------------------------------------------------------

    def param_specs(self) -> Dict[str, Any]:
        if self.cfg.kv_lora_rank:
            return self._deepseek_specs()
        layers = {
            "attn_norm": P(),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "mlp_norm": P(),
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
        }
        if self.cfg.num_experts:
            # MoE: experts over ep, expert-FFN width over tp; the dense
            # routed-compute einsums then run expert-local per chip with one
            # combine all-reduce inserted by the partitioner
            layers.update(
                w_router=P(),
                w_gate=P(None, "ep", None, "tp"),
                w_up=P(None, "ep", None, "tp"),
                w_down=P(None, "ep", "tp", None),
            )
        if self.cfg.attention_bias:
            layers.update(bq=P(None, "tp"), bk=P(None, "tp"), bv=P(None, "tp"))
        if self.cfg.qk_norm:
            layers.update(q_norm=P(), k_norm=P())
        specs: Dict[str, Any] = {
            "embed": P(),
            "layers": layers,
            "final_norm": P(),
        }
        if not self.cfg.tie_word_embeddings:
            # logits shard cleanly for real vocabs (128256, 32000, ...);
            # replicate as a fallback for odd-sized vocabs (toy models)
            tp = self.mesh.shape.get("tp", 1)
            specs["lm_head"] = (P(None, "tp")
                                if self.cfg.vocab_size % tp == 0 else P())
        return self._add_quant_specs(specs)

    def _add_quant_specs(self, specs: Dict[str, Any]) -> Dict[str, Any]:
        """Specs for int8-quantized trees (``ops/quant.quantize_params``).

        The int8 tensor shards exactly like the bf16 original; the
        per-out-channel scale keeps the layer and out dims and drops the
        contraction axis (axis 1 of a stacked ``[L, K, N]``, axis 0 of
        ``lm_head``). Correctness under a SHARDED contraction (wo/w_down:
        ``P(None, "tp", None)``): the scale multiply distributes over the
        sum, so GSPMD may psum the int32 partials before or after the
        rescale — both orders are exact. Extra spec keys are inert for
        unquantized trees (``shard_params`` walks the tree's keys).
        """
        from dynamo_tpu.ops.quant import LAYER_WEIGHTS
        layers = specs["layers"]
        for name in LAYER_WEIGHTS:
            spec = layers.get(name)
            if spec is None or len(spec) != 3:
                continue  # MoE 4-d expert stacks don't quantize yet
            layers[name + "_q"] = spec
            layers[name + "_scale"] = P(spec[0], spec[2])
        lm = specs.get("lm_head")
        if lm is not None:
            specs["lm_head_q"] = lm
            specs["lm_head_scale"] = P(lm[1]) if len(lm) == 2 else P()
        return specs

    def _deepseek_specs(self) -> Dict[str, Any]:
        """MLA (deepseek) pytree: HEAD-carrying projections shard their
        head-packed dim over tp (wq/wq_b/wkv_b outputs, wo input) — under
        GSPMD the whole latent attention then runs head-local per chip
        with one psum after wo; the latent path (wkv_a/kv_a_norm) and the
        shared-per-token cache replicate over tp. Routed experts shard
        over ep, shared experts' ffn width over tp."""
        attn = {
            "attn_norm": P(),
            "wkv_a": P(),
            "kv_a_norm": P(),
            "wkv_b": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "mlp_norm": P(),
            "wq": P(None, None, "tp"),
            "wq_a": P(),
            "q_a_norm": P(),
            "wq_b": P(None, None, "tp"),
        }
        dense = dict(attn)
        dense.update(w_gate=P(None, None, "tp"), w_up=P(None, None, "tp"),
                     w_down=P(None, "tp", None))
        moe = dict(attn)
        moe.update(
            w_router=P(),
            router_bias=P(),
            w_gate=P(None, "ep", None, "tp"),
            w_up=P(None, "ep", None, "tp"),
            w_down=P(None, "ep", "tp", None),
            ws_gate=P(None, None, "tp"),
            ws_up=P(None, None, "tp"),
            ws_down=P(None, "tp", None),
        )
        specs: Dict[str, Any] = {
            "embed": P(),
            "final_norm": P(),
            "dense_layers": dense,
            "moe_layers": moe,
        }
        if not self.cfg.tie_word_embeddings:
            tp = self.mesh.shape.get("tp", 1)
            specs["lm_head"] = (P(None, "tp")
                                if self.cfg.vocab_size % tp == 0 else P())
        return specs

    def pages_spec(self) -> P:
        """Stacked cache [L, N, 2, Hkv, page, Dh]: Hkv over tp (MLA: the
        latent is shared across heads — replicated)."""
        if self.cfg.kv_lora_rank:
            return P()
        return P(None, None, None, "tp", None, None)

    def pages_layer_spec(self) -> P:
        """Per-layer cache [N, 2, Hkv, page, Dh]: Hkv over tp."""
        if self.cfg.kv_lora_rank:
            return P()
        return P(None, None, "tp", None, None)

    # -- application -------------------------------------------------------

    def _named(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def shard_params(self, params: Dict[str, Any]) -> Dict[str, Any]:
        specs = self.param_specs()

        def place(path, leaf):
            node = specs
            for k in path:
                node = node[k.key]
            return jax.device_put(leaf, self._named(node))

        return jax.tree_util.tree_map_with_path(place, params)

    def shard_pages(self, pages):
        if isinstance(pages, list):
            spec = self._named(self.pages_layer_spec())
            return [jax.device_put(p, spec) for p in pages]
        return jax.device_put(pages, self._named(self.pages_spec()))

    def replicate(self, x):
        return jax.device_put(x, self._named(P()))


def tp_sharding(cfg: ModelConfig, tp_size: int,
                devices: Optional[list] = None) -> ModelSharding:
    """Pure tensor-parallel sharding over the first ``tp_size`` devices."""
    devs = list(devices if devices is not None else jax.devices())[:tp_size]
    mesh = make_mesh(MeshSpec(tp=tp_size), devices=devs)
    return ModelSharding(cfg, mesh)


# -- transport-array sharding helpers ---------------------------------------
# The KV transfer paths move blocks as a STACKED rank-6 array
# [L, n, 2, Hkv, ps, Dh] regardless of whether the cache itself is the
# stacked array or a per-layer list; these helpers are the one place the
# cache placement -> transport placement mapping lives (engine/transfer.py
# and the engine's sharded gather both use them).


def transport_sharding(pages):
    """Sharding of the stacked ``[L, n, ...]`` transport array matching the
    cache's placement. For a per-layer list cache (rank-5 refs) the layer
    axis is prepended to the spec; any non-Named sharding (single device)
    passes through unchanged."""
    ref = pages[0] if isinstance(pages, list) else pages
    sharding = ref.sharding
    if isinstance(pages, list) and isinstance(sharding, NamedSharding):
        sharding = NamedSharding(sharding.mesh, P(None, *sharding.spec))
    return sharding


def shard_layout(sharding) -> tuple:
    """``(shard_count, axis)`` a sharding partitions its array over:
    ``(1, -1)`` for unpartitioned/single-device placements, ``(0, -1)``
    when more than one axis is partitioned (the per-shard KV wire carries
    exactly one sharded axis — multi-axis caches fall back to merged
    frames)."""
    if not isinstance(sharding, NamedSharding):
        return (1, -1)
    mesh_shape = dict(sharding.mesh.shape)
    parted = []
    for i, entry in enumerate(sharding.spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for nm in names:
            n *= int(mesh_shape.get(nm, 1))
        if n > 1:
            parted.append((n, i))
    if not parted:
        return (1, -1)
    if len(parted) > 1:
        return (0, -1)
    return parted[0]


__all__ = ["ModelSharding", "tp_sharding", "transport_sharding",
           "shard_layout"]
