"""Pipeline parallelism: layer stages over the ``pp`` mesh axis.

The reference never implements PP itself — it only forwards engine flags
(SURVEY §2.7; ``launch/dynamo-run/src/main.rs:28``); the engines' PP is
NCCL send/recv between layer shards. Here PP is built the XLA way
(SURVEY §7 stage 8, "GSPMD stage partitioning"): ONE ``shard_map`` program
in which

- the layer-stacked parameter pytree and the stacked paged KV cache shard
  their LAYER axis over ``pp`` — stage ``s`` holds layers
  ``[s*L/pp, (s+1)*L/pp)`` and exactly those layers' KV pages, so paged
  reads/writes stay stage-local with no cross-stage traffic;
- the batch is split into microbatches that flow through the stages on a
  ``lax.ppermute`` ring (the classic pipeline schedule: at tick ``t``
  stage ``s`` works microbatch ``t - s``); with ``M`` microbatches the
  pipeline runs ``M + pp - 1`` ticks and each stage idles only during
  fill/drain ticks;
- inactive ticks compute on garbage but their page writes are masked to
  the reserved garbage page (``new_lens = 0``) and their outputs dropped,
  keeping every tick shape-identical — the XLA-friendly alternative to
  data-dependent control flow;
- last-stage logits are collected per microbatch and ``psum``-broadcast
  at the end, so every rank returns the full ``[B, vocab]`` (multi-host
  leaders read results locally, like every other step family).

PP composes with TP (``pp x tp`` mesh): the ``shard_map`` stays fully
manual (partial-manual shard_map is not supported by this jax), so the
stage body does tensor parallelism explicitly — weights placed with
``P("pp", ..., "tp")`` (``pp_sharding_fns`` with a model config), each
device computing its head/ffn shard and the standard two per-layer
``lax.psum`` all-reduces over ``tp`` (after the attention out-projection
and the mlp down-projection) completing the activations. KV pages shard
``Hkv`` over tp inside each stage, so paged reads/writes stay chip-local
exactly as in the plain tp path.

PP also composes with DP (``pp x dp`` mesh): the batch splits over ``dp``
OUTSIDE the pipeline ring — each dp replica pipelines its own
microbatches — while the page pool stays REPLICATED across dp. The
invariant that keeps the replicas' caches identical: before every cache
write, the per-layer K/V (and the tick's table/position/new-length rows)
``all_gather`` over dp, so every replica applies the identical GLOBAL
write while attending only its local rows. The gathered K/V rows are KBs
at decode (vs psum-merging whole page-stack deltas, which would move the
entire cache per step).

The stage body takes the engine's Pallas ``attn_impl`` (the stacked
decode/prefill kernels run fine on a shard_map-local cache slab — same
call signature as ``paged_attention``), so pp serving no longer forces
the XLA scan path.

The schedule is family-agnostic over STAGE ADAPTERS (``_STAGE_ADAPTERS``):
llama-tree dense, gemma-2 (norm sandwich, GeGLU, per-layer windows,
softcaps), and MoE (routed experts, FFN width tp-sharded with one psum
after the linear combine). DeepSeek MLA is refused — its heterogeneous
dense/MoE two-stack layout doesn't fit a uniform stage slab; that family
serves via tp/dp/sp.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (
    _finish_layer,
    _project_qkv,
    _rms_norm,
)
from dynamo_tpu.ops.attention import paged_attention, write_kv


# tp tail (dims after the leading L axis) per layer-stacked leaf — the
# same placement ``parallel/sharding.py`` uses for the plain tp path:
# qkv/ffn-up shard their OUTPUT dim, out/down projections their INPUT dim
# (so the partial products line up for the per-layer psum). Families with
# differently-shaped leaves override via their stage adapter's TP_TAILS.
_TP_TAILS: Dict[str, Tuple] = {
    "attn_norm": (), "mlp_norm": (), "q_norm": (), "k_norm": (),
    "wq": (None, "tp"), "wk": (None, "tp"), "wv": (None, "tp"),
    "wo": ("tp", None),
    "w_gate": (None, "tp"), "w_up": (None, "tp"), "w_down": ("tp", None),
    "bq": ("tp",), "bk": ("tp",), "bv": ("tp",),
}

# MoE expert leaves carry a leading E dim: [L, E, H, I] / [L, E, I, H]
_TP_TAILS_MOE: Dict[str, Tuple] = {
    **_TP_TAILS,
    "w_router": (),
    "w_gate": (None, None, "tp"), "w_up": (None, None, "tp"),
    "w_down": (None, "tp", None),
}


def _layer_spec(name: str, pp_axis: str, tp: int,
                tails: Dict[str, Tuple] = _TP_TAILS) -> P:
    if tp == 1:
        return P(pp_axis)
    return P(pp_axis, *tails.get(name, ()))


# ------------------------------------------------------------- stage bodies
# One adapter per supported family: the pieces of a layer that differ
# (embedding, qkv projection, per-layer attention kwargs, the post-attention
# tail with its tp psum points, the final vocab projection). The pipeline
# schedule, KV writes, dp gathers, and microbatch ring are family-agnostic.


class _LlamaStage:
    TP_TAILS = _TP_TAILS

    def __init__(self, cfg: ModelConfig, cfg_local: ModelConfig):
        self.cfg, self.cfg_local = cfg, cfg_local
        self.sm_scale = cfg.head_dim ** -0.5

    def embed(self, params, tok):
        return params["embed"][tok]

    def qkv(self, lp, h, pos):
        return _project_qkv(self.cfg_local, lp, h, pos)

    def attend_kwargs(self, global_lidx):
        return {}

    def finish(self, lp, h, attn, psum):
        cfg = self.cfg
        if psum is None:
            return _finish_layer(cfg, lp, h, attn)
        # manual tensor parallelism: each device holds its head slice of
        # wo / ffn slice of w_down, so the projections produce PARTIAL
        # sums — the standard two all-reduces per layer complete them
        # (parallel/sharding.py places the plain-tp path identically;
        # GSPMD inserts the same psums there automatically)
        Bm_, S_ = h.shape[0], h.shape[1]
        h = h + psum(attn.reshape(Bm_, S_, -1) @ lp["wo"])
        x = _rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
        mlp = (jax.nn.silu(x @ lp["w_gate"])
               * (x @ lp["w_up"])) @ lp["w_down"]
        return h + psum(mlp)

    def tail(self, params, hidden):
        hn = _rms_norm(hidden, params["final_norm"], self.cfg.rms_norm_eps)
        lm_head = params.get("lm_head")
        if lm_head is None:
            lm_head = params["embed"].T
        # model-dtype operands + f32 accumulation, matching llama._logits
        # (f32-cast operands would run the vocab matmul at f32 MXU rate)
        return jnp.dot(hn, lm_head, preferred_element_type=jnp.float32)


class _MoeStage(_LlamaStage):
    """Mixtral/Qwen3-MoE: llama attention + routed experts. Under manual
    tp the expert FFN width shards (``_TP_TAILS_MOE``); the token-combine
    is LINEAR in the expert outputs, so ONE psum after the routed result
    completes the partial down-products — same two all-reduce points per
    layer as the dense family. The dispatch backend works too (its
    scatter/combine is also linear); its drop counter is discarded here
    (the pipeline returns the llama 2-tuple contract)."""

    TP_TAILS = _TP_TAILS_MOE

    def finish(self, lp, h, attn, psum):
        from dynamo_tpu.models import moe as _moe

        cfg = self.cfg
        if psum is None:
            h, _dropped = _moe._moe_layer_tail(cfg, lp, h, attn)
            return h
        Bm_, S_ = h.shape[0], h.shape[1]
        h = h + psum(attn.reshape(Bm_, S_, -1) @ lp["wo"])
        x = _rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
        if cfg.moe_backend == "dispatch":
            routed, _dropped = _moe.moe_mlp_dispatch(cfg, lp, x)
        else:
            routed = _moe.moe_mlp(cfg, lp, x)
        return h + psum(routed)


class _GemmaStage:
    """gemma-2: (1+w) RMSNorm sandwich around attention AND the GeGLU mlp,
    sqrt(H)-scaled embedding, alternating per-layer sliding windows, logit
    softcaps on attention and the final projection."""

    TP_TAILS = _TP_TAILS

    def __init__(self, cfg: ModelConfig, cfg_local: ModelConfig):
        from dynamo_tpu.models import gemma as _g

        self._g = _g
        self.cfg, self.cfg_local = cfg, cfg_local
        self.sm_scale = _g._sm_scale(cfg)

    def embed(self, params, tok):
        return self._g._embed(self.cfg, params, tok)

    def qkv(self, lp, h, pos):
        return self._g._project_qkv(self.cfg_local, lp, h, pos)

    def attend_kwargs(self, global_lidx):
        cfg = self.cfg
        win = 0
        if cfg.sliding_window:
            # even GLOBAL layers slide, odd are global (models/gemma.py
            # layer_windows) — closed form on the traced stage-local index
            win = jnp.where(global_lidx % 2 == 0, cfg.sliding_window, 0)
        return {"window": win,
                "softcap": cfg.attn_logit_softcap or None}

    def finish(self, lp, h, attn, psum):
        cfg, g = self.cfg, self._g
        if psum is None:
            return g._finish_layer(cfg, lp, h, attn)
        eps = cfg.rms_norm_eps
        Bm_, S_ = h.shape[0], h.shape[1]
        attn_out = psum(attn.reshape(Bm_, S_, -1) @ lp["wo"])
        h = h + g._rms_norm(attn_out, lp["post_attn_norm"], eps)
        x = g._rms_norm(h, lp["pre_ffw_norm"], eps)
        mlp = psum((jax.nn.gelu(x @ lp["w_gate"], approximate=True)
                    * (x @ lp["w_up"])) @ lp["w_down"])
        return h + g._rms_norm(mlp, lp["post_ffw_norm"], eps)

    def tail(self, params, hidden):
        cfg, g = self.cfg, self._g
        hn = g._rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps)
        lm_head = params.get("lm_head")
        if lm_head is None:
            lm_head = params["embed"].T
        # model-dtype operands + f32 accumulation (gemma._logits)
        logits = jnp.dot(hn, lm_head, preferred_element_type=jnp.float32)
        cap = cfg.final_logit_softcap
        if cap:
            logits = jnp.tanh(logits / cap) * cap
        return logits


_STAGE_ADAPTERS = {
    "dynamo_tpu.models.llama": _LlamaStage,
    "dynamo_tpu.models.gemma": _GemmaStage,
    "dynamo_tpu.models.moe": _MoeStage,
}


def stage_adapter_for(cfg: ModelConfig):
    """The pipeline stage adapter CLASS for this config's family, or None
    when the family cannot stage (DeepSeek MLA). The worker flag guard and
    both sharding/forward paths resolve through this one lookup."""
    from dynamo_tpu.models import get_family

    return _STAGE_ADAPTERS.get(getattr(get_family(cfg), "__name__", ""))


def _ffn_width(cfg: ModelConfig) -> int:
    """The per-layer FFN width the tp axis shards (expert width on MoE)."""
    if cfg.num_experts:
        return cfg.moe_intermediate_size or cfg.intermediate_size
    return cfg.intermediate_size


def _param_specs(params: Dict[str, Any], pp_axis: str, tp: int,
                 tails: Dict[str, Tuple] = _TP_TAILS) -> Dict[str, Any]:
    """Layer-stacked leaves shard axis 0 over pp (+ tp tails); the rest
    replicate (incl. lm_head: the vocab projection runs once on the full
    hidden state after the pipeline, replicated per device)."""
    layer_spec = {k: _layer_spec(k, pp_axis, tp, tails)
                  for k in params["layers"]}
    specs: Dict[str, Any] = {k: P() for k in params if k != "layers"}
    specs["layers"] = layer_spec
    return specs


def pipeline_forward(params: Dict[str, Any], cfg: ModelConfig,
                     tokens: jnp.ndarray, positions: jnp.ndarray,
                     pages: jnp.ndarray, page_table: jnp.ndarray,
                     total_lens: jnp.ndarray, new_lens: jnp.ndarray,
                     mesh: Mesh, pp_axis: str = "pp", tp_axis: str = "tp",
                     dp_axis: str = "dp",
                     n_microbatches: int | None = None,
                     attn_impl=None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in for ``llama.forward`` running the layers as a pp pipeline.

    Requires ``cfg.num_layers %% pp == 0``. ``n_microbatches`` must divide
    the PER-REPLICA batch; the default picks the LARGEST divisor of B/dp
    that is <= pp — M == pp keeps every stage busy in steady state,
    smaller batches run with pipeline bubbles rather than failing.
    ``pages`` is the stacked cache ``[L, N, 2, Hkv, ps, Dh]``. A ``tp``
    mesh axis > 1 additionally head/ffn-shards each stage (weights placed
    by ``pp_sharding_fns``); a ``dp`` axis > 1 splits the batch across
    replicas (module docstring: K/V writes all_gather over dp so the
    replicated page pool stays consistent). ``attn_impl`` optionally
    replaces the XLA paged attention inside the stage body — the stacked
    Pallas kernels match the call signature.

    Families (one stage adapter each, ``_STAGE_ADAPTERS``): the llama
    tree (llama/mistral/qwen dense), gemma-2 (4-norm sandwich, GeGLU,
    embed scaling, alternating per-layer windows + both softcaps), and
    MoE (routed experts; dispatch-backend drop counts are NOT surfaced
    under pp — the worker warns at startup). DeepSeek MLA is refused:
    its layers differ from any staged body and would serve silently
    wrong outputs.
    """
    from dynamo_tpu.models import get_family
    n_stages = mesh.shape[pp_axis]
    tp = dict(mesh.shape).get(tp_axis, 1)
    dp = dict(mesh.shape).get(dp_axis, 1)
    if n_stages == 1:
        # no stage body runs: every family's own forward serves
        out = get_family(cfg).forward(params, cfg, tokens, positions,
                                      pages, page_table, total_lens,
                                      new_lens)
        return out[0], out[1]
    adapter_factory = stage_adapter_for(cfg)
    if adapter_factory is None:
        raise ValueError(
            f"pipeline_forward has no stage adapter for "
            f"{cfg.model_type!r} — running it through another family's "
            f"layers would serve silently wrong outputs; use tp/dp/sp "
            f"for this family (worker/main.py guards the flag)")
    if cfg.num_layers % n_stages:
        raise ValueError(f"num_layers={cfg.num_layers} not divisible by "
                         f"pp={n_stages}")
    if tp > 1 and (cfg.num_kv_heads % tp or _ffn_width(cfg) % tp):
        raise ValueError(f"num_kv_heads={cfg.num_kv_heads}/"
                         f"ffn_width={_ffn_width(cfg)} not divisible by "
                         f"tp={tp}")
    B = tokens.shape[0]
    if B % dp:
        raise ValueError(f"batch {B} not divisible by dp={dp} (the engine "
                         f"aligns its batch buckets to dp when cfg.mesh "
                         f"is set)")
    B_local = B // dp
    # default: the largest microbatch count <= pp that divides the
    # per-replica batch (a small serving batch pipelines with bubbles
    # rather than failing)
    M = n_microbatches or max(m for m in range(1, n_stages + 1)
                              if B_local % m == 0)
    if B_local % M:
        raise ValueError(f"per-replica batch {B_local} not divisible by "
                         f"n_microbatches={M}")
    Bm = B_local // M
    layers_per_stage = cfg.num_layers // n_stages
    # per-device view of the head/ffn dims under manual tp: _project_qkv
    # reshapes by head COUNTS, which are local inside the shard_map body
    cfg_local = cfg
    if tp > 1:
        import dataclasses
        cfg_local = dataclasses.replace(
            cfg, num_heads=cfg.num_heads // tp,
            num_kv_heads=cfg.num_kv_heads // tp)
    stage_body = adapter_factory(cfg, cfg_local)
    sm_scale = stage_body.sm_scale
    # a passed attn_impl must carry the family's per-layer kwargs (the
    # stacked Pallas kernels advertise window/softcap support); otherwise
    # the XLA path serves — never silently drop a gemma window
    attend = attn_impl or paged_attention
    if (isinstance(stage_body, _GemmaStage) and attn_impl is not None
            and not getattr(attn_impl, "supports_window_softcap", False)):
        attend = paged_attention

    def shard_fn(params, tokens, positions, page_table, total_lens,
                 new_lens, pages_local):
        stage = lax.axis_index(pp_axis)
        last = n_stages - 1
        # microbatch stacks [M, Bm, ...] (per-dp-replica local rows)
        tok_mb = tokens.reshape(M, Bm, -1)
        pos_mb = positions.reshape(M, Bm, -1)
        tbl_mb = page_table.reshape(M, Bm, -1)
        tot_mb = total_lens.reshape(M, Bm)
        new_mb = new_lens.reshape(M, Bm)
        S = tok_mb.shape[2]
        H = cfg.hidden_size

        def gather_dp(x):
            """Global batch rows for the cache write: every dp replica at
            (stage, tick) processes the same microbatch index, so tiled
            all_gathers line up and all replicas apply identical writes."""
            if dp == 1:
                return x
            return lax.all_gather(x, dp_axis, axis=0, tiled=True)

        # local layer ids are GLOBAL indices into the pp-sharded page
        # stack's local slab (axis 0 of pages_local is layers_per_stage)
        local_layer_ids = jnp.arange(layers_per_stage)

        def run_stage(h, pages_local, pos, tbl, tot, new):
            pos_g, tbl_g, new_g = gather_dp(pos), gather_dp(tbl), \
                gather_dp(new)

            def body(carry, xs):
                h, pages_local = carry
                lp, lidx = xs
                q, k, v = stage_body.qkv(lp, h, pos)
                pages_local = write_kv(pages_local, lidx, gather_dp(k),
                                       gather_dp(v), tbl_g, pos_g, new_g)
                attn = attend(q, pages_local, lidx, tbl, pos, tot,
                              sm_scale,
                              **stage_body.attend_kwargs(
                                  stage * layers_per_stage + lidx))
                psum = ((lambda x: lax.psum(x, tp_axis)) if tp > 1
                        else None)
                h = stage_body.finish(lp, h, attn, psum)
                return (h, pages_local), None

            (h, pages_local), _ = lax.scan(
                body, (h, pages_local), (params["layers"], local_layer_ids))
            return h, pages_local

        def tick(t, carry):
            pages_local, h_in, out = carry
            m = t - stage                      # this stage's microbatch
            active = jnp.logical_and(m >= 0, m < M)
            mc = jnp.clip(m, 0, M - 1)
            tok = lax.dynamic_index_in_dim(tok_mb, mc, keepdims=False)
            pos = lax.dynamic_index_in_dim(pos_mb, mc, keepdims=False)
            tbl = lax.dynamic_index_in_dim(tbl_mb, mc, keepdims=False)
            tot = lax.dynamic_index_in_dim(tot_mb, mc, keepdims=False)
            new = lax.dynamic_index_in_dim(new_mb, mc, keepdims=False)
            # inactive ticks: mask page writes to the garbage page and let
            # the compute produce don't-care values
            new = jnp.where(active, new, 0)
            h0 = stage_body.embed(params, tok)  # [Bm, S, H]
            h = jnp.where(stage == 0, h0, h_in)
            h, pages_local = run_stage(h, pages_local, pos, tbl, tot, new)
            # last stage: record this microbatch's LAST-TOKEN hidden state
            # (the vocab projection — the dominant small-batch matmul —
            # runs ONCE after the loop, not per tick per stage)
            last_idx = jnp.maximum(new, 1) - 1                 # [Bm]
            h_last = jnp.take_along_axis(
                h, last_idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
            keep = jnp.logical_and(active, stage == last)
            prev = lax.dynamic_index_in_dim(out, mc, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(keep, h_last, prev), mc, 0)
            # hand the activation to the next stage (stage 0 re-embeds, so
            # the value it receives is ignored)
            h_next = lax.ppermute(
                h, pp_axis, [(i, i + 1) for i in range(n_stages - 1)])
            return pages_local, h_next, out

        out0 = jnp.zeros((M, Bm, H), params["embed"].dtype)
        h0 = jnp.zeros((Bm, S, H), params["embed"].dtype)
        pages_local, _h, out = lax.fori_loop(
            0, M + n_stages - 1, tick, (pages_local, h0, out0))
        # only the last stage holds real hidden states; broadcast them,
        # then project to the vocab once (per-replica local rows)
        out = lax.psum(
            jnp.where(stage == last, out, jnp.zeros_like(out)), pp_axis)
        logits = stage_body.tail(params, out.reshape(B_local, H))
        return logits, pages_local

    pages_spec = (P(pp_axis) if tp == 1
                  else P(pp_axis, None, None, tp_axis))
    batch = P(dp_axis)                 # rows split across dp replicas
    specs_in = (
        _param_specs(params, pp_axis, tp, stage_body.TP_TAILS),
        batch, batch, batch, batch, batch,  # tokens/pos/table/total/new
        pages_spec,                    # pages: layers staged, Hkv over tp,
                                       # REPLICATED over dp (gathered writes)
    )
    specs_out = (batch, pages_spec)
    fn = jax.shard_map(shard_fn, mesh=mesh, in_specs=specs_in,
                       out_specs=specs_out, check_vma=False)
    logits, pages = fn(params, tokens, positions, page_table, total_lens,
                       new_lens, pages)
    return logits, pages


def pp_sharding_fns(mesh: Mesh, cfg: ModelConfig | None = None,
                    pp_axis: str = "pp", tp_axis: str = "tp"):
    """(shard_params_fn, shard_pages_fn) placing the layer-stacked leaves
    and the stacked page cache on the pp axis — what a worker plugs into
    ``JaxEngineConfig`` to serve with ``pipeline_forward``.

    With a ``tp`` axis > 1 on the mesh, each layer leaf composes the stage
    placement with the tensor-parallel tail (wq ``P("pp", None, "tp")``,
    pages ``P("pp", None, None, "tp", ...)``); non-layer leaves replicate
    (the vocab projection runs replicated after the pipeline). ``cfg`` is
    required then, for the divisibility checks."""
    from jax.sharding import NamedSharding

    tp = dict(mesh.shape).get(tp_axis, 1)
    tails = _TP_TAILS
    if cfg is not None:
        adapter = stage_adapter_for(cfg)
        if adapter is not None:
            tails = adapter.TP_TAILS
    if tp > 1:
        if cfg is None:
            raise ValueError("pp x tp sharding needs the model config")
        if cfg.num_kv_heads % tp or _ffn_width(cfg) % tp:
            raise ValueError(
                f"num_kv_heads={cfg.num_kv_heads}/ffn_width="
                f"{_ffn_width(cfg)} not divisible by tp={tp}")
    pages_spec = (P(pp_axis) if tp == 1
                  else P(pp_axis, None, None, tp_axis))

    def shard_params(params):
        out = dict(params)
        out["layers"] = {
            k: jax.device_put(
                v, NamedSharding(mesh, _layer_spec(k, pp_axis, tp, tails)))
            for k, v in params["layers"].items()}
        for k, v in params.items():
            if k != "layers":
                out[k] = jax.device_put(v, NamedSharding(mesh, P()))
        return out

    def shard_pages(pages):
        return jax.device_put(pages, NamedSharding(mesh, pages_spec))

    return shard_params, shard_pages


__all__ = ["pipeline_forward", "pp_sharding_fns", "stage_adapter_for"]
