"""Parallelism: device meshes, model shardings, sequence/context parallelism.

The reference delegates every intra-engine parallelism strategy to vLLM/
SGLang/TRT-LLM flags over NCCL (SURVEY §2.7); here they are native jax:

- ``mesh.py`` — the named device mesh (axes ``dp``/``tp``/``sp``/``ep``) and
  helpers to build it from local or multi-host device sets.
- ``sharding.py`` — GSPMD shardings for the Llama-family param pytree and the
  paged KV cache: annotate once, let XLA insert the ICI collectives.
- ``ring_attention.py`` — sequence/context parallelism (net-new vs the
  reference, which has none — SURVEY §5).
- ``ring_prefill.py`` — the serving integration: whole-prompt prefill with
  the sequence axis sharded over ``sp``, writing the paged KV cache.
"""

from dynamo_tpu.parallel.mesh import MeshSpec, make_mesh
from dynamo_tpu.parallel.sharding import ModelSharding, tp_sharding

__all__ = ["MeshSpec", "make_mesh", "ModelSharding", "tp_sharding"]
