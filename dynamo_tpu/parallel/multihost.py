"""Multi-host serving: ONE logical worker endpoint over a multi-process mesh.

SURVEY §7 hard-part #3 and VERDICT r1 item 5. The reference gates multi-node
engine launches behind ``--num-nodes/--node-rank/--leader-addr`` CLI flags
(``launch/dynamo-run/src/main.rs:28``) plus an etcd leader/worker barrier
(``lib/runtime/src/utils/leader_worker_barrier.rs:16-80``), then delegates
the actual cross-node execution to vLLM/SGLang's NCCL world. Here the
cross-node execution model is jax multi-controller SPMD, and the design
follows from its one contract: **every process must enter the same compiled
computation with the same global arrays**.

  - ``initialize_distributed`` wires the processes into one jax world
    (``jax.distributed.initialize``): N hosts × local chips = one global
    device set; a ``Mesh`` over those devices makes every ``jit`` a
    multi-host program whose collectives ride ICI/DCN.
  - Rank 0 is the ONLY rank with a scheduler, allocator, RPC endpoint, and
    model registration — the "host 0 serves" pattern. Its engine loop taps
    every step: immediately before dispatching a jitted step it broadcasts
    the exact padded host arrays (a few KB: token ids, page tables,
    sampling knobs — never device state) over the coordinator event bus.
  - Ranks > 0 run ``follow_steps``: subscribe, deserialize, call the SAME
    jitted step with the SAME arrays. No scheduler, no divergence — the
    follower is a pure step executor, which is exactly the degree of
    freedom multi-controller SPMD leaves it.
  - Bring-up is rendezvoused with the existing leader/worker barrier
    (``runtime/barrier.py``): followers check in only AFTER subscribing, so
    no step message can be missed; the leader serves only after the barrier
    completes.

KV-block export/import, tiered offload (KVBM), and embeddings also ride
the broadcast stream: the engine's ``dispatch_gather_pages`` /
``scatter_pages_host`` / ``_embed_batch`` tap "gather"/"scatter"/"embed"
messages before dispatch, so every rank joins those jits on the globally
sharded cache (gathers produce replicated outputs the leader reads
locally) — disagg P/D and KVBM therefore compose with multi-host workers.
Batch-dim (dp) sharding across hosts works too: when the mesh carries a
``dp`` axis the engine constrains its batch inputs to ``P("dp")`` and
re-replicates the packed step output (a tiny [B, 2+2K] all-gather) inside
the step program (``jax_engine._shard_batch`` / ``_sample_tail``), so rank
0 reads every sampled row locally.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

def barrier_id(namespace: str, component: str) -> str:
    """Bring-up barrier id, namespaced like the step subject — two multihost
    groups on one coordinator must never satisfy each other's barriers."""
    return f"mh-bringup/{namespace}/{component}"


def step_subject(namespace: str, component: str) -> str:
    return f"{namespace}.{component}.mh_steps"


def initialize_distributed(coordinator_address: str, num_nodes: int,
                           node_rank: int,
                           local_device_count: Optional[int] = None) -> None:
    """Join this process into the jax multi-controller world.

    Must run before ANY other jax API touches the backend. On TPU pods the
    device count is discovered; ``local_device_count`` is for CPU tests
    (virtual host devices)."""
    import jax

    if local_device_count is not None:
        # virtual-CPU world (tests/dryruns): N host devices per process,
        # cross-process collectives over gloo. Real TPU pods autodetect.
        jax.config.update("jax_num_cpu_devices", local_device_count)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_nodes, process_id=node_rank)
    logger.info("jax.distributed: rank %d/%d, %d local / %d global devices",
                node_rank, num_nodes, jax.local_device_count(),
                jax.device_count())


# ---------------------------------------------------------------- wire form

def _pack_arrays(kind: str, arrays: Dict[str, np.ndarray], step: int) -> dict:
    msg = {"kind": kind, "step": int(step)}
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        msg[name] = {"d": str(a.dtype), "s": list(a.shape), "b": a.tobytes()}
    return msg


def _unpack_arrays(msg: dict) -> Dict[str, np.ndarray]:
    out = {}
    for name, v in msg.items():
        if isinstance(v, dict) and "b" in v:
            out[name] = np.frombuffer(v["b"], dtype=np.dtype(v["d"])).reshape(
                v["s"])
    return out


# ---------------------------------------------------------------- rank 0

class StepFanout:
    """Rank-0 side: engine step tap → ordered broadcast to followers.

    The tap runs in the engine's step thread; publishes hop to the event
    loop and are awaited before the step dispatches, so the wire order is
    exactly the execution order."""

    def __init__(self, drt, subject: str):
        self._drt = drt
        self._subject = subject
        self._loop = asyncio.get_running_loop()
        # resolved (step_id, ok) outcomes not yet relayed to followers;
        # appended from the event loop (engine _process/_fail_plan), drained
        # from the step thread inside tap() — list append/swap is
        # GIL-atomic, and ordering within one producer is preserved
        self._outcomes: list = []

    def note_outcome(self, step_id, ok: bool) -> None:
        if step_id is not None:
            self._outcomes.append((int(step_id), bool(ok)))

    def tap(self, kind: str, arrays: Dict[str, np.ndarray],
            step: int) -> None:
        msg = _pack_arrays(kind, arrays, step)
        if self._outcomes:
            drained, self._outcomes = self._outcomes, []
            # piggyback resolved outcomes on the next step message so
            # followers can cross-check their own per-step results
            # (divergence detection, ADVICE r2)
            msg["outcomes"] = drained
        fut = asyncio.run_coroutine_threadsafe(
            self._drt.publish_event(self._subject, msg), self._loop)
        fut.result(timeout=30.0)

    def install(self, engine) -> None:
        engine.step_tap = self.tap
        engine.step_outcome_cb = self.note_outcome


# ---------------------------------------------------------------- rank > 0

async def follow_steps(drt, subject: str, engine, *,
                       ready_event: Optional[asyncio.Event] = None) -> None:
    """Follower loop: execute every broadcast step on the local shards.

    Runs until the subscription closes (leader gone / runtime shutdown).
    ``engine`` is a full JaxEngine (same config as rank 0) whose scheduler
    is simply never used."""
    sub = await drt.subscribe_events(subject)
    if ready_event is not None:
        ready_event.set()
    consecutive_failures = 0
    my_failed_steps: Dict[int, bool] = {}
    async for _subject, msg in sub:
        # cross-check the leader's resolved outcomes against our own: a
        # step WE failed that the LEADER completed means this rank's
        # KV/pages state silently diverged — restart the group now rather
        # than serve corrupt state (ADVICE r2). The reverse (leader failed,
        # we succeeded) is benign: only the leader holds scheduler
        # bookkeeping, device state advanced identically on all ranks.
        for step_id, leader_ok in msg.get("outcomes", []):
            if my_failed_steps.pop(step_id, False) and leader_ok:
                raise RuntimeError(
                    f"multihost divergence: leader completed step {step_id} "
                    "but this rank failed it — restarting the group")
        arrays = _unpack_arrays(msg)
        try:
            await asyncio.to_thread(engine.execute_arrays, msg["kind"],
                                    arrays, msg["step"])
            consecutive_failures = 0
        except Exception:
            # when a step raises on ALL ranks — the common case, it's one
            # SPMD program — every rank logs and stays in lockstep for the
            # next step; the outcome cross-check above catches the
            # asymmetric case one message later.
            my_failed_steps[int(msg["step"])] = True
            consecutive_failures += 1
            if consecutive_failures >= 3:
                # persistently failing rank (dead pages buffer, OOM): exit
                # so the orchestrator restarts the group, instead of
                # silently diverging or wedging the leader's collectives
                raise
            logger.exception("follower step %s failed; continuing in "
                             "lockstep", msg.get("step"))


__all__ = ["initialize_distributed", "StepFanout", "follow_steps",
           "step_subject", "barrier_id"]
