"""Named device meshes for TPU slices.

Axis convention (orthogonal, any subset may be 1):

- ``dp``  — data parallel: independent model replicas (batch-sharded).
- ``tp``  — tensor parallel: attention heads / MLP columns over ICI.
- ``sp``  — sequence/context parallel: ring-attention over the sequence axis.
- ``ep``  — expert parallel: MoE experts over chips.

The reference exposes these only as engine flags (``--tensor-parallel-size``,
``--ep-num-redundant-experts`` …, SURVEY §2.7); here the mesh is the single
source of truth and every sharding is expressed against its axis names, so
XLA lays collectives onto ICI links.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "pp", "tp", "sp", "ep")


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape; unspecified axes default to 1."""

    dp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.tp * self.sp * self.ep

    def shape(self) -> Dict[str, int]:
        return {"dp": self.dp, "pp": self.pp, "tp": self.tp, "sp": self.sp,
                "ep": self.ep}

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "MeshSpec":
        unknown = set(d) - set(AXES)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}")
        return cls(**{k: int(v) for k, v in d.items()})


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with the canonical axis order.

    Axis order is (dp, pp, tp, sp, ep) — innermost axes get the
    fastest-varying device dimension, which on a TPU slice means ``tp``/``sp``
    neighbors sit on adjacent ICI links (jax device order is torus-major).
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec(tp=len(devices))
    if spec.size != len(devices):
        raise ValueError(
            f"mesh spec {spec.shape()} needs {spec.size} devices, "
            f"have {len(devices)}")
    arr = np.array(devices).reshape(spec.dp, spec.pp, spec.tp, spec.sp,
                                    spec.ep)
    return Mesh(arr, AXES)


__all__ = ["MeshSpec", "make_mesh", "AXES"]
