"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Net-new capability vs the reference, which has no sequence/context
parallelism anywhere (SURVEY §5 — it delegates long context to paged
attention + KV offload inside engines). For prompts too long for one chip's
HBM or prefill latency budget, the sequence axis is sharded over ``sp`` and
K/V shards rotate around the ring via ``jax.lax.ppermute`` — each hop rides
a single ICI neighbor link while every chip computes flash-style online
softmax against the shard it currently holds (blockwise/ring attention,
Liu et al. 2023).

Numerics: online softmax accumulation in float32 with a running row max —
the same update flash attention uses, so the result is bit-comparable to
single-device attention up to float32 reduction order.

``ring_attention`` is the shard_map-level primitive (callers are inside
``shard_map`` with a named ``sp`` axis); ``ring_self_attention`` is the
convenience wrapper that shards full arrays over a mesh and runs it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, kv_pos, kv_valid, sm_scale):
    """One q-shard vs one kv-shard: returns (num, den, mx) partials.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D] (kv heads already repeated to H)
    q_pos: [B, Sq]; kv_pos: [B, Sk]; kv_valid: [B, Sk] bool
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    causal = kv_pos[:, None, None, :] <= q_pos[:, None, :, None]
    valid = kv_valid[:, None, None, :]
    scores = jnp.where(causal & valid, scores, NEG_INF)
    mx = jnp.max(scores, axis=-1)                          # [B,H,Sq]
    p = jnp.exp(scores - mx[..., None])
    # rows with no visible kv yet: mx = NEG_INF; zero their contribution
    live = mx > NEG_INF / 2
    p = jnp.where(live[..., None], p, 0.0)
    num = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    den = jnp.sum(p, axis=-1)                              # [B,H,Sq]
    return num, den, mx, live


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   q_pos: jnp.ndarray, kv_pos: jnp.ndarray,
                   kv_valid: Optional[jnp.ndarray] = None,
                   sm_scale: Optional[float] = None,
                   axis_name: str = "sp",
                   return_partials: bool = False) -> jnp.ndarray:
    """Causal self-attention with the kv sequence sharded over a ring.

    Call INSIDE shard_map. Shapes are per-shard:
    q [B, Sq, Hq, D], k/v [B, Sk, Hkv, D], q_pos [B, Sq], kv_pos [B, Sk].
    Returns [B, Sq, Hq, D] in q's dtype.
    """
    B, Sq, Hq, D = q.shape
    Hkv = k.shape[2]
    if sm_scale is None:
        sm_scale = D ** -0.5
    if kv_valid is None:
        kv_valid = jnp.ones(kv_pos.shape, bool)
    if Hq != Hkv:
        rep = Hq // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    n = lax.psum(1, axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def rotate(x):
        return lax.ppermute(x, axis_name, perm)

    def body(carry, _):
        k_cur, v_cur, pos_cur, valid_cur, num, den, mx = carry
        pnum, pden, pmx, plive = _block_attend(q, k_cur, v_cur, q_pos,
                                               pos_cur, valid_cur, sm_scale)
        new_mx = jnp.maximum(mx, pmx)
        # rescale both accumulators to the new max; dead partials contribute 0
        old_scale = jnp.where(mx > NEG_INF / 2, jnp.exp(mx - new_mx), 0.0)
        p_scale = jnp.where(plive, jnp.exp(pmx - new_mx), 0.0)
        num = num * old_scale[..., None] + pnum * p_scale[..., None]
        den = den * old_scale + pden * p_scale
        carry = (rotate(k_cur), rotate(v_cur), rotate(pos_cur),
                 rotate(valid_cur), num, den, new_mx)
        return carry, None

    num0 = jnp.zeros((B, Hq, Sq, D), jnp.float32)
    den0 = jnp.zeros((B, Hq, Sq), jnp.float32)
    mx0 = jnp.full((B, Hq, Sq), NEG_INF, jnp.float32)
    carry, _ = lax.scan(body, (k, v, kv_pos, kv_valid, num0, den0, mx0),
                        None, length=n)
    num, den, mx = carry[4], carry[5], carry[6]
    if return_partials:
        # un-normalized online-softmax state, for merging with partials
        # from another context (e.g. cached pages in a prefix-hit ring
        # prefill): num [B,Hq,Sq,D], den/mx [B,Hq,Sq]
        return num, den, mx
    out = num / jnp.maximum(den, 1e-20)[..., None]         # [B,Hq,Sq,D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_self_attention(mesh: Mesh, q: jnp.ndarray, k: jnp.ndarray,
                        v: jnp.ndarray, positions: jnp.ndarray,
                        kv_valid: Optional[jnp.ndarray] = None,
                        sm_scale: Optional[float] = None,
                        axis_name: str = "sp",
                        head_axis: Optional[str] = None,
                        return_partials: bool = False) -> jnp.ndarray:
    """Full-array wrapper: shards the sequence axis over ``axis_name`` and
    runs ring attention. q/k/v [B, S, H, D], positions [B, S]; S must divide
    by the axis size.

    ``kv_valid`` [B, S] masks padded tail positions; ``head_axis`` names a
    mesh axis to shard the head dim over as well (tensor parallelism —
    attention is head-local so only the K/V ring needs collectives). A
    ``head_axis`` absent from the mesh or of size 1 is ignored.
    """
    from jax import shard_map

    if kv_valid is None:
        kv_valid = jnp.ones(positions.shape, bool)
    if head_axis is not None and mesh.shape.get(head_axis, 1) <= 1:
        head_axis = None
    seq_spec = P(None, axis_name, head_axis, None)
    pos_spec = P(None, axis_name)

    fn = functools.partial(ring_attention, sm_scale=sm_scale,
                           axis_name=axis_name,
                           return_partials=return_partials)
    if return_partials:
        nd_spec = P(None, head_axis, axis_name, None)   # num [B,Hq,Sq,D]
        sc_spec = P(None, head_axis, axis_name)         # den/mx [B,Hq,Sq]
        out_specs = (nd_spec, sc_spec, sc_spec)
    else:
        out_specs = seq_spec
    sharded = shard_map(
        fn, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, pos_spec, pos_spec, pos_spec),
        out_specs=out_specs, check_vma=False)
    return sharded(q, k, v, positions, positions, kv_valid)


__all__ = ["ring_attention", "ring_self_attention"]
