"""Sequence-parallel long-prompt prefill that fills the paged KV cache.

This wires ring attention (``parallel/ring_attention.py``) into the serving
engine's prefill contract: same signature family as ``models/llama.forward``
— (params, cfg, tokens, positions, pages, page_table, total_lens, new_lens)
→ (last-token logits, updated pages) — but the sequence axis is sharded over
the ``sp`` mesh axis and attention runs as a ring (K/V shards rotate via
``lax.ppermute`` over ICI) instead of gathering from the cache.

Why a separate forward instead of chunked prefill: a chunked prefill of
length S costs O(S²/chunk) cache re-gathers and serializes on one chip's
flops; the ring path does the whole prompt in ONE step with compute and
activation memory split ``sp`` ways. The K/V written back to the paged cache
is identical to what chunked prefill would have written, so decode proceeds
normally afterwards (and router block hashes/commits are unaffected).

Prefix-cache hits COMPOSE with the ring (VERDICT r2 weak #5 — the "long
shared system prompt" workload): new tokens attend to each other via the
ring AND to the resident cached pages via blockwise paged attention, the
two contexts merged with online-softmax partials
(``ops.attention.merge_softmax_partials``). With no resident prefix the
blockwise loop has a zero trip count — the novel-prompt path costs
nothing extra. The reference has no sequence parallelism anywhere
(SURVEY §5) — net-new capability.

Writes either cache layout (stacked ``[L, N, 2, Hkv, ps, Dh]`` for the scan
forward; per-layer page-major list for the unrolled/Pallas forward) and
composes
with tensor parallelism: the head axis stays sharded over ``tp`` inside the
ring (attention is head-local), so a ``(sp, tp)`` mesh uses both.
"""

from __future__ import annotations

from typing import List, Tuple, Union

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (
    _finish_layer,
    _logits,
    _project_qkv,
)
from dynamo_tpu.ops.attention import (
    PAGES_PER_CHUNK,
    _attend_blockwise,
    _gathered_to_bhtd,
    _pad_table,
    merge_softmax_partials,
    normalize_softmax_partials,
    write_kv,
    write_kv_layer,
)
from dynamo_tpu.parallel.ring_attention import ring_self_attention

Pages = Union[jnp.ndarray, List[jnp.ndarray]]


def ring_prefill(params, cfg: ModelConfig, tokens: jnp.ndarray,
                 positions: jnp.ndarray, pages: Pages,
                 page_table: jnp.ndarray, total_lens: jnp.ndarray,
                 new_lens: jnp.ndarray, *, mesh: Mesh,
                 sp_axis: str = "sp", tp_axis: str = "tp",
                 ) -> Tuple[jnp.ndarray, Pages]:
    """Full-prompt prefill with the sequence axis sharded over ``sp``.

    tokens/positions: [B, S] with S a multiple of the ``sp`` axis size;
    pads masked via ``new_lens`` exactly like ``llama.forward``. Positions
    may start past 0 — the resident prefix (pages below ``positions[:,0]``
    in the table) is attended via blockwise paged attention and merged
    into the ring's online softmax. Returns (logits [B, vocab] at each
    row's last real token, updated pages).
    """
    sm_scale = cfg.head_dim ** -0.5
    B, S = tokens.shape
    sp = mesh.shape[sp_axis]
    if S % sp:
        raise ValueError(f"padded prompt length {S} not divisible by "
                         f"sp={sp}")
    seq_sharded = NamedSharding(mesh, P(None, sp_axis, None))
    kv_valid = jnp.arange(S)[None, :] < new_lens[:, None]   # [B, S]
    start = positions[:, 0]                                 # [B] prefix len
    Hkv = cfg.num_kv_heads
    G = cfg.num_heads // Hkv
    table_pad = _pad_table(page_table, PAGES_PER_CHUNK)

    h = params["embed"][tokens]                             # [B, S, H]
    h = lax.with_sharding_constraint(h, seq_sharded)

    def layer(h, pages, lp, write, gather_layer):
        q, k, v = _project_qkv(cfg, lp, h, positions)
        pages = write(pages, k, v)
        ring_parts = ring_self_attention(
            mesh, q, k, v, positions, kv_valid=kv_valid, sm_scale=sm_scale,
            axis_name=sp_axis, head_axis=tp_axis, return_partials=True)

        def gather_chunk(c):
            tbl = lax.dynamic_slice(
                table_pad, (0, c * PAGES_PER_CHUNK), (B, PAGES_PER_CHUNK))
            g = gather_layer(pages, tbl)   # [B, C, 2, Hkv, ps, Dh]
            return _gathered_to_bhtd(g[:, :, 0]), _gathered_to_bhtd(g[:, :, 1])

        # cached-context partials: new-token queries vs positions < start
        # (zero loop trips when there is no resident prefix)
        qg = q.reshape(B, S, Hkv, G, cfg.head_dim)
        ctx_parts = _attend_blockwise(
            qg, gather_chunk, page_table.shape[1], pages.shape[-2],
            PAGES_PER_CHUNK, positions, start, sm_scale,
            return_partials=True)
        num, den, _mx = merge_softmax_partials(ring_parts, ctx_parts)
        out = normalize_softmax_partials(num, den)          # [B,Hq,S,D]
        attn = out.transpose(0, 2, 1, 3).astype(q.dtype)    # [B,S,Hq,D]
        h = _finish_layer(cfg, lp, h, attn)
        return lax.with_sharding_constraint(h, seq_sharded), pages

    if isinstance(pages, list):
        out_pages: List[jnp.ndarray] = []
        for l in range(cfg.num_layers):
            lp = {k: v[l] for k, v in params["layers"].items()}
            h, kv = layer(h, pages[l], lp,
                          lambda pg, k, v: write_kv_layer(
                              pg, k, v, page_table, positions, new_lens),
                          gather_layer=lambda pg, tbl: pg[tbl])
            out_pages.append(kv)
        return _logits(cfg, params, h, new_lens), out_pages

    def body(carry, xs):
        h, pages = carry
        lp, lidx = xs
        h, pages = layer(h, pages, lp,
                         lambda pg, k, v: write_kv(
                             pg, lidx, k, v, page_table, positions, new_lens),
                         gather_layer=lambda pg, tbl: pg[lidx, tbl])
        return (h, pages), None

    (h, pages), _ = lax.scan(
        body, (h, pages), (params["layers"], jnp.arange(cfg.num_layers)))
    return _logits(cfg, params, h, new_lens), pages


__all__ = ["ring_prefill"]
