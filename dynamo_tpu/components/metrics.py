"""Standalone metrics component: worker-load plane -> Prometheus.

Parity: reference ``components/metrics/src/main.rs`` — poll a target
component's per-instance stats (our ``__stats__`` plane standing in for NATS
``$SRV.STATS``), aggregate ``ForwardPassMetrics``, subscribe to the router's
KV-hit-rate events, expose everything on an HTTP ``/metrics`` endpoint for
Prometheus/Grafana.

Run: ``python -m dynamo_tpu.components.metrics --namespace ns --component tpu``
"""

from __future__ import annotations

import argparse
import asyncio
import logging
from typing import Dict, Optional

from prometheus_client import CollectorRegistry, Counter, Gauge

from dynamo_tpu.kv_router.router import kv_hit_rate_subject
from dynamo_tpu.protocols.events import ForwardPassMetrics, KVHitRateEvent
from dynamo_tpu.runtime.runtime import DEFAULT_COORDINATOR, DistributedRuntime
from dynamo_tpu.runtime.system_server import SystemServer
from dynamo_tpu.utils.aio import reap_task
from dynamo_tpu.utils.logging import configure_logging

logger = logging.getLogger(__name__)


class MetricsAggregator:
    """Scrape + subscribe loops feeding a Prometheus registry."""

    def __init__(self, drt: DistributedRuntime, namespace: str,
                 component: str, endpoint: str = "generate",
                 interval_s: float = 2.0):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.endpoint = endpoint
        self.interval_s = interval_s
        self.registry = CollectorRegistry()
        ns = "dynamo_worker"
        labels = ["worker"]
        self.active_slots = Gauge(f"{ns}_request_active_slots", "",
                                  labels, registry=self.registry)
        self.total_slots = Gauge(f"{ns}_request_total_slots", "",
                                 labels, registry=self.registry)
        self.waiting = Gauge(f"{ns}_requests_waiting", "",
                             labels, registry=self.registry)
        self.kv_active = Gauge(f"{ns}_kv_active_blocks", "",
                               labels, registry=self.registry)
        self.kv_total = Gauge(f"{ns}_kv_total_blocks", "",
                              labels, registry=self.registry)
        self.cache_usage = Gauge(f"{ns}_cache_usage_ratio", "",
                                 labels, registry=self.registry)
        self.hit_rate = Gauge(f"{ns}_prefix_cache_hit_rate", "",
                              labels, registry=self.registry)
        # speculative decoding acceptance (cumulative per worker; gauges
        # SET to the scraped running totals — the source accumulates)
        self.spec_drafts = Gauge(f"{ns}_spec_drafts", "",
                                 labels, registry=self.registry)
        self.spec_draft_tokens = Gauge(f"{ns}_spec_draft_tokens", "",
                                       labels, registry=self.registry)
        self.spec_accepted_tokens = Gauge(
            f"{ns}_spec_accepted_tokens", "", labels,
            registry=self.registry)
        # MoE dispatch-backend overflow (token-expert assignments dropped)
        self.moe_dropped = Gauge(f"{ns}_moe_dropped_tokens", "",
                                 labels, registry=self.registry)
        self.router_isl_blocks = Counter(
            "dynamo_router_isl_blocks_total", "", registry=self.registry)
        self.router_overlap_blocks = Counter(
            "dynamo_router_overlap_blocks_total", "", registry=self.registry)
        self._scrape_task: Optional[asyncio.Task] = None
        self._event_task: Optional[asyncio.Task] = None
        self._event_sub = None

    async def start(self) -> "MetricsAggregator":
        self._event_sub = await self.drt.subscribe_events(
            kv_hit_rate_subject(self.namespace, self.component))
        self._event_task = asyncio.create_task(self._event_loop())
        self._scrape_task = asyncio.create_task(self._scrape_loop())
        return self

    async def stop(self) -> None:
        await reap_task(self._scrape_task)
        await reap_task(self._event_task)
        if self._event_sub is not None:
            try:
                await self._event_sub.cancel()
            except Exception:
                pass

    async def _event_loop(self) -> None:
        async for _subject, payload in self._event_sub:
            try:
                ev = KVHitRateEvent.from_dict(payload)
                self.router_isl_blocks.inc(ev.isl_blocks)
                self.router_overlap_blocks.inc(ev.overlap_blocks)
            except Exception:
                logger.exception("bad kv hit-rate event %r", payload)

    async def _scrape_loop(self) -> None:
        comp = self.drt.namespace(self.namespace).component(self.component)
        ep_path = f"{self.namespace}/{self.component}/{self.endpoint}"
        while True:
            try:
                scraped = await comp.scrape_stats()
                for iid, stats in scraped.items():
                    ep = stats.get(ep_path) if isinstance(stats, dict) else None
                    data = ep.get("data") if isinstance(ep, dict) else None
                    if not data:
                        continue
                    m = ForwardPassMetrics.from_dict(data)
                    w = f"{iid:x}"
                    self.active_slots.labels(w).set(
                        m.worker_stats.request_active_slots)
                    self.total_slots.labels(w).set(
                        m.worker_stats.request_total_slots)
                    self.waiting.labels(w).set(
                        m.worker_stats.num_requests_waiting)
                    self.kv_active.labels(w).set(m.kv_stats.kv_active_blocks)
                    self.kv_total.labels(w).set(m.kv_stats.kv_total_blocks)
                    self.cache_usage.labels(w).set(
                        m.kv_stats.gpu_cache_usage_perc)
                    self.hit_rate.labels(w).set(
                        m.kv_stats.gpu_prefix_cache_hit_rate)
                    if m.spec_decode_stats is not None:
                        sd = m.spec_decode_stats
                        self.spec_drafts.labels(w).set(sd.num_drafts)
                        self.spec_draft_tokens.labels(w).set(
                            sd.num_draft_tokens)
                        self.spec_accepted_tokens.labels(w).set(
                            sd.num_accepted_tokens)
                    self.moe_dropped.labels(w).set(
                        m.worker_stats.moe_dropped_tokens)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("stats scrape failed")
            await asyncio.sleep(self.interval_s)


async def amain(args: argparse.Namespace) -> None:
    drt = await DistributedRuntime.create(coordinator=args.coordinator)
    agg = await MetricsAggregator(
        drt, args.namespace, args.component, args.endpoint,
        interval_s=args.interval).start()
    server = await SystemServer(registry=agg.registry, host=args.host,
                                port=args.port).start()
    print(f"metrics component on {server.host}:{server.port}", flush=True)
    try:
        await drt.runtime.wait_shutdown()
    finally:
        await server.stop()
        await agg.stop()
        await drt.close()


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu metrics component")
    p.add_argument("--coordinator", default=DEFAULT_COORDINATOR)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="tpu")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9091)
    configure_logging()
    try:
        asyncio.run(amain(p.parse_args()))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
