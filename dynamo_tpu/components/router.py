"""Standalone KV-router service.

Parity: reference ``components/router/src/main.rs:59-97`` — host the KV
router behind its own runtime endpoint so any client (not just the OpenAI
frontend) gets KV-aware placement: requests sent to
``{namespace}/{router_component}/generate`` are forwarded to the best worker
and the response stream is relayed back. A custom ``WorkerSelector`` can be
injected by importing and wrapping ``serve_router``.

Run: ``python -m dynamo_tpu.components.router --namespace ns --component tpu``
"""

from __future__ import annotations

import argparse
import asyncio
import logging
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.kv_router import KvPushRouter
from dynamo_tpu.kv_router.scheduler import WorkerSelector
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.runtime.runtime import DEFAULT_COORDINATOR, DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging

logger = logging.getLogger(__name__)


def router_handler(router: KvPushRouter):
    async def handler(payload: Any, ctx) -> AsyncIterator[Any]:
        async for item in router.generate_stream(payload):
            yield item
    return handler


async def serve_router(drt: DistributedRuntime, namespace: str,
                       worker_component: str, router_component: str,
                       block_size: int = 16,
                       selector: Optional[WorkerSelector] = None,
                       **router_kwargs) -> KvPushRouter:
    """Wire a KvPushRouter over the worker component and serve it."""
    worker_ep = (drt.namespace(namespace).component(worker_component)
                 .endpoint("generate"))
    client = await worker_ep.client()
    card = ModelDeploymentCard(name=f"{worker_component}-router",
                               kv_cache_block_size=block_size)
    router = await KvPushRouter.create(drt, client, card,
                                       selector=selector, **router_kwargs)
    serve_ep = (drt.namespace(namespace).component(router_component)
                .endpoint("generate"))
    await serve_ep.serve(router_handler(router))
    logger.info("kv router serving %s/%s/generate -> %s/%s",
                namespace, router_component, namespace, worker_component)
    return router


async def amain(args: argparse.Namespace) -> None:
    drt = await DistributedRuntime.create(coordinator=args.coordinator)
    router = await serve_router(
        drt, args.namespace, args.component, args.router_component,
        block_size=args.block_size,
        overlap_score_weight=args.kv_overlap_score_weight,
        temperature=args.router_temperature)
    print(f"router component serving "
          f"{args.namespace}/{args.router_component}/generate", flush=True)
    try:
        await drt.runtime.wait_shutdown()
    finally:
        await router.close()
        await drt.close()


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo_tpu standalone KV router")
    p.add_argument("--coordinator", default=DEFAULT_COORDINATOR)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="tpu",
                   help="worker component to route over")
    p.add_argument("--router-component", default="router")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    p.add_argument("--router-temperature", type=float, default=0.0)
    configure_logging()
    try:
        asyncio.run(amain(p.parse_args()))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
