"""Standalone deployable components.

Parity with the reference's ``components/`` processes beyond the frontend and
workers (which live in ``dynamo_tpu.frontend`` / ``dynamo_tpu.worker``):

- ``metrics``: scrapes a component's worker stats + KV hit-rate events into a
  Prometheus exposition (reference ``components/metrics``, Rust).
- ``router``: hosts the KV router as its own service endpoint so external
  clients can use KV-aware placement without embedding the frontend
  (reference ``components/router``, Rust).
"""
