"""Model deployment card (MDC): everything a frontend needs to serve a model.

Parity: reference ``lib/llm/src/model_card/model.rs:87-230``
(``ModelDeploymentCard``: model info, tokenizer kind, prompt formatter,
context length, kv block size, migration limit, checksums) and
``local_model.rs`` (build from an HF repo dir, attach = publish).

The card is JSON-serializable and travels through the coordinator KV (the
reference ships tokenizer artifacts via the NATS object store; we inline the
tokenizer JSON in the card when no shared filesystem is available).
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ModelDeploymentCard:
    name: str = ""
    model_path: Optional[str] = None  # local HF repo dir, if reachable
    context_length: int = 8192
    kv_cache_block_size: int = 16
    migration_limit: int = 3
    model_type: str = "chat"  # chat | completions | embedding | backend
    eos_token_ids: List[int] = field(default_factory=list)
    bos_token_id: Optional[int] = None
    chat_template: Optional[str] = None  # jinja2 source
    tokenizer_json: Optional[str] = None  # inline tokenizers-library JSON
    tokenizer_path: Optional[str] = None  # path to tokenizer.json
    hf_config: Dict[str, Any] = field(default_factory=dict)  # raw config.json
    # top-logprob alternatives the serving engine computes per token
    # (JaxEngineConfig.num_top_logprobs); the preprocessor clamps request
    # top_logprobs to this so accepted requests are actually served in
    # full. Default matches JaxEngineConfig's default — workers that raise
    # the engine K must set this too (worker/main.py does).
    num_top_logprobs: int = 8
    # widest sparse penalty/logit_bias window the serving engine ships per
    # request (JaxEngineConfig.penalty_window); the preprocessor rejects
    # logit_bias wider than this instead of silently dropping entries on
    # device. Workers that change the engine window must set this too
    # (worker/main.py does).
    penalty_window: int = 32
    extra: Dict[str, Any] = field(default_factory=dict)

    def load_tokenizer(self):
        """Resolve the card's tokenizer (inline JSON preferred, else path).

        A ``.model`` path selects the native SentencePiece backend
        (reference: ``lib/llm/src/tokenizers/sp.rs`` behind the same file
        dispatch, ``tokenizers.rs:586``); anything else is HF
        ``tokenizers`` JSON."""
        from dynamo_tpu.preprocessor.tokenizer import HfTokenizer  # lazy: avoids cycle
        if self.tokenizer_json:
            return HfTokenizer.from_json(self.tokenizer_json)
        if self.tokenizer_path:
            if self.tokenizer_path.endswith(".model"):
                from dynamo_tpu.preprocessor.sp_tokenizer import SpTokenizer
                return SpTokenizer.from_file(self.tokenizer_path)
            return HfTokenizer.from_file(self.tokenizer_path)
        raise ValueError(f"model card {self.name!r} carries no tokenizer")

    # -- identity ---------------------------------------------------------

    def checksum(self) -> str:
        """Stable digest used to detect frontend/worker config drift
        (parity: ``mdc_sum`` on PreprocessedRequest)."""
        payload = json.dumps({
            "name": self.name,
            "context_length": self.context_length,
            "kv_cache_block_size": self.kv_cache_block_size,
            "eos_token_ids": self.eos_token_ids,
            "chat_template": self.chat_template,
        }, sort_keys=True).encode()
        return hashlib.sha256(payload).hexdigest()[:16]

    # -- serialization ----------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "model_path": self.model_path,
            "context_length": self.context_length,
            "kv_cache_block_size": self.kv_cache_block_size,
            "migration_limit": self.migration_limit,
            "model_type": self.model_type,
            "eos_token_ids": list(self.eos_token_ids),
            "bos_token_id": self.bos_token_id,
            "chat_template": self.chat_template,
            "tokenizer_json": self.tokenizer_json,
            "tokenizer_path": self.tokenizer_path,
            "hf_config": self.hf_config,
            # engine-capability advertisements: without these on the wire
            # the frontend preprocessor falls back to defaults and either
            # rejects requests the worker could serve or accepts ones the
            # device would truncate
            "num_top_logprobs": self.num_top_logprobs,
            "penalty_window": self.penalty_window,
            "extra": self.extra,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelDeploymentCard":
        return cls(
            name=d.get("name", ""),
            model_path=d.get("model_path"),
            context_length=d.get("context_length", 8192),
            kv_cache_block_size=d.get("kv_cache_block_size", 16),
            migration_limit=d.get("migration_limit", 3),
            model_type=d.get("model_type", "chat"),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            bos_token_id=d.get("bos_token_id"),
            chat_template=d.get("chat_template"),
            tokenizer_json=d.get("tokenizer_json"),
            tokenizer_path=d.get("tokenizer_path"),
            hf_config=d.get("hf_config", {}),
            num_top_logprobs=d.get("num_top_logprobs", 8),
            penalty_window=d.get("penalty_window", 32),
            extra=d.get("extra", {}),
        )

    # -- construction from an HF-style local repo dir ---------------------

    @classmethod
    def from_local_path(cls, path: str, name: Optional[str] = None,
                        inline_tokenizer: bool = True,
                        **overrides: Any) -> "ModelDeploymentCard":
        """Build a card from a local HuggingFace-style model directory
        (config.json, tokenizer.json, tokenizer_config.json).

        Parity: reference ``model_card/create.rs`` (from_repo); GGUF files
        route through ``from_gguf`` (reference gguf loader).
        """
        if path.endswith(".gguf") and os.path.isfile(path):
            return cls.from_gguf(path, name=name, **overrides)
        card = cls(name=name or os.path.basename(os.path.normpath(path)),
                   model_path=path)
        cfg_path = os.path.join(path, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
            card.hf_config = cfg
            card.context_length = int(
                cfg.get("max_position_embeddings")
                or cfg.get("n_positions") or card.context_length)
            eos = cfg.get("eos_token_id")
            if isinstance(eos, int):
                card.eos_token_ids = [eos]
            elif isinstance(eos, list):
                card.eos_token_ids = list(eos)
            bos = cfg.get("bos_token_id")
            if isinstance(bos, int):
                card.bos_token_id = bos
        tok_path = os.path.join(path, "tokenizer.json")
        sp_path = os.path.join(path, "tokenizer.model")
        if os.path.exists(tok_path):
            card.tokenizer_path = tok_path
            if inline_tokenizer:
                with open(tok_path) as f:
                    card.tokenizer_json = f.read()
        elif os.path.exists(sp_path):
            # SentencePiece-only checkpoint (original llama/mistral/gemma
            # releases): the native SP backend serves it
            card.tokenizer_path = sp_path
        tc_path = os.path.join(path, "tokenizer_config.json")
        if os.path.exists(tc_path):
            with open(tc_path) as f:
                tc = json.load(f)
            tmpl = tc.get("chat_template")
            if isinstance(tmpl, str):
                card.chat_template = tmpl
            elif isinstance(tmpl, list) and tmpl:  # named templates
                for entry in tmpl:
                    if entry.get("name") == "default":
                        card.chat_template = entry.get("template")
                        break
                else:
                    card.chat_template = tmpl[0].get("template")
        # standalone chat_template.json / chat_template.jinja override
        ct_json = os.path.join(path, "chat_template.json")
        if os.path.exists(ct_json):
            with open(ct_json) as f:
                card.chat_template = json.load(f).get("chat_template",
                                                      card.chat_template)
        ct_jinja = os.path.join(path, "chat_template.jinja")
        if os.path.exists(ct_jinja):
            with open(ct_jinja) as f:
                card.chat_template = f.read()
        for k, v in overrides.items():
            setattr(card, k, v)
        return card

    @classmethod
    def from_gguf(cls, path: str, name: Optional[str] = None,
                  **overrides: Any) -> "ModelDeploymentCard":
        """Card from a GGUF single-file model (metadata-driven).

        The GGUF vocab is not reconstructed into a fast tokenizer here; pair
        the file with a ``tokenizer.json`` next to it (checked automatically)
        or pass ``tokenizer_path`` explicitly.
        """
        from dynamo_tpu.models.gguf import GgufFile
        gf = GgufFile(path)
        cfg = gf.to_model_config()
        base = os.path.basename(path)
        card = cls(name=name or base.rsplit(".", 1)[0], model_path=path,
                   context_length=cfg.max_position_embeddings)
        specials = gf.special_token_ids()
        if specials.get("eos") is not None:
            card.eos_token_ids = [int(specials["eos"])]
        if specials.get("bos") is not None:
            card.bos_token_id = int(specials["bos"])
        tmpl = gf.metadata.get("tokenizer.chat_template")
        if isinstance(tmpl, str):
            card.chat_template = tmpl
        sibling = os.path.join(os.path.dirname(path), "tokenizer.json")
        if os.path.exists(sibling):
            card.tokenizer_path = sibling
            with open(sibling) as f:
                card.tokenizer_json = f.read()
        for k, v in overrides.items():
            setattr(card, k, v)
        return card


@dataclass
class ModelEntry:
    """Registration of a served model, written to the coordinator KV under
    ``models/{name}/{instance_id:x}`` with the worker's lease.

    Parity: reference ``discovery/model_entry.rs`` + MODEL_ROOT_PATH watch.
    """

    name: str
    namespace: str
    component: str
    endpoint: str
    model_type: str = "chat"
    card: Optional[ModelDeploymentCard] = None

    def key(self, instance_id: int) -> str:
        return f"models/{self.name}/{instance_id:x}"

    def to_json(self) -> bytes:
        return json.dumps({
            "name": self.name,
            "namespace": self.namespace,
            "component": self.component,
            "endpoint": self.endpoint,
            "model_type": self.model_type,
            "card": self.card.to_dict() if self.card else None,
        }).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "ModelEntry":
        d = json.loads(data)
        card = d.get("card")
        return cls(
            name=d["name"], namespace=d["namespace"], component=d["component"],
            endpoint=d["endpoint"], model_type=d.get("model_type", "chat"),
            card=ModelDeploymentCard.from_dict(card) if card else None)


MODEL_ROOT_PREFIX = "models/"

__all__ = ["ModelDeploymentCard", "ModelEntry", "MODEL_ROOT_PREFIX"]
