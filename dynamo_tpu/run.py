"""Single-process run CLI: ``python -m dynamo_tpu.run in=<input> out=<engine>``.

Parity: reference ``launch/dynamo-run`` (``dynamo-run in=[http|text|batch:|
stdin] out=[mocker|echo_full|...]`` — ``launch/dynamo-run/src/main.rs:28``).
One process, no coordinator: build the engine, wrap it in the local pipeline
(preprocess -> engine -> detokenize), and drive it from the chosen input.

  in=http            OpenAI server on --http-port
  in=text            interactive chat REPL
  in=stdin           one prompt per stdin line -> completion per line
  in=batch:FILE      jsonl {"prompt": ...} -> --output jsonl, concurrent
  out=echo           token-echo engine (no model needed)
  out=mocker         simulated engine (timing model, test tokenizer ok)
  out=jax            the TPU engine (requires --model-path)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys
import time
from typing import Optional, Tuple

from dynamo_tpu.engine.base import EchoEngine, EngineBase
from dynamo_tpu.llm.pipeline import LocalEnginePipeline
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.protocols.openai import ChatCompletionRequest, CompletionRequest
from dynamo_tpu.utils.logging import configure_logging


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="dynamo_tpu single-process runner",
        usage="python -m dynamo_tpu.run in=<http|text|stdin|batch:FILE> "
              "out=<echo|mocker|jax> [options]")
    p.add_argument("io", nargs=2, metavar="in=.../out=...",
                   help="input and engine selectors")
    p.add_argument("--model-path", default=None)
    p.add_argument("--model-name", default=None)
    p.add_argument("--random-weights", action="store_true")
    p.add_argument("--http-host", default="0.0.0.0")
    p.add_argument("--http-port", type=int, default=8080)
    p.add_argument("--max-tokens", type=int, default=256)
    p.add_argument("--output", default="-", help="batch output (jsonl)")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--num-pages", type=int, default=2048)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-num-seqs", type=int, default=32)
    p.add_argument("--max-context", type=int, default=8192)
    p.add_argument("--max-prefill-chunk", type=int, default=1024)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--speculative-num-tokens", type=int, default=0,
                   help="n-gram prompt-lookup speculative decoding "
                        "(see worker.main --speculative-num-tokens)")
    return p


def parse_io(io) -> Tuple[str, str]:
    spec = {}
    for part in io:
        key, _, val = part.partition("=")
        if key not in ("in", "out") or not val:
            raise SystemExit(f"bad selector {part!r}; expected in=.../out=...")
        spec[key] = val
    if "in" not in spec or "out" not in spec:
        raise SystemExit("both in= and out= are required")
    return spec["in"], spec["out"]


def build_engine_and_card(out: str, args) -> Tuple[EngineBase, ModelDeploymentCard]:
    if out == "echo":
        from dynamo_tpu.utils.testing import make_test_card
        card = (ModelDeploymentCard.from_local_path(args.model_path,
                                                    name=args.model_name)
                if args.model_path else make_test_card(name="echo"))
        return EchoEngine(), card
    if out == "mocker":
        from dynamo_tpu.mocker import MockEngineArgs, MockerEngine
        from dynamo_tpu.utils.testing import make_test_card
        card = (ModelDeploymentCard.from_local_path(args.model_path,
                                                    name=args.model_name)
                if args.model_path else make_test_card(name="mock-model"))
        return MockerEngine(MockEngineArgs(
            num_pages=args.num_pages, page_size=args.page_size,
            max_num_seqs=args.max_num_seqs,
            max_context=args.max_context)), card
    if out == "jax":
        if not args.model_path:
            raise SystemExit("out=jax requires --model-path")
        from dynamo_tpu.models.hub import resolve_model_path
        from dynamo_tpu.worker.main import (
            arm_guided, build_engine, build_parser)
        args.model_path = resolve_model_path(args.model_path)
        card = ModelDeploymentCard.from_local_path(args.model_path,
                                                   name=args.model_name)
        # start from the WORKER parser's own defaults so build_engine's
        # knob set can grow without silently breaking this CLI (found
        # live: a hand-built Namespace was missing every flag added since)
        ns = build_parser().parse_args(["--model-path", args.model_path])
        for k in ("dtype", "num_pages", "page_size", "max_num_seqs",
                  "max_prefill_chunk", "max_context",
                  "tensor_parallel_size", "random_weights",
                  "speculative_num_tokens"):
            setattr(ns, k, getattr(args, k))
        engine = build_engine(ns)
        arm_guided(engine, card)
        return engine, card
    raise SystemExit(f"unknown engine {out!r}; choose echo|mocker|jax")


async def run_http(pipeline: LocalEnginePipeline, args) -> None:
    from dynamo_tpu.http.service import HttpService
    from dynamo_tpu.llm.model_manager import ModelManager
    from dynamo_tpu.utils.config import RuntimeConfig
    manager = ModelManager()
    manager.add(pipeline.card.name, pipeline)
    # the single-process server honors the same request-lifecycle knobs as
    # the distributed frontend (DYN_RUNTIME_REQUEST_TIMEOUT_S, shedding
    # high-water marks — see docs/deployment.md)
    try:
        cfg = RuntimeConfig.load()
    except Exception:
        logging.getLogger(__name__).warning(
            "bad runtime config; request-lifecycle knobs use defaults",
            exc_info=True)
        cfg = RuntimeConfig()
    service = await HttpService(manager, host=args.http_host,
                                port=args.http_port,
                                request_timeout_s=cfg.request_timeout_s,
                                max_inflight=cfg.http_max_inflight,
                                max_model_inflight=cfg.http_max_model_inflight,
                                shed_retry_after_s=cfg.http_shed_retry_after_s,
                                ).start()
    print(f"listening on {service.host}:{service.port} "
          f"(model {pipeline.card.name})", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await service.stop()


async def _complete(pipeline: LocalEnginePipeline, prompt: str,
                    max_tokens: int) -> str:
    req = CompletionRequest(model=pipeline.card.name, prompt=prompt,
                            max_tokens=max_tokens)
    parts = []
    async for out in pipeline.generate_completion(req):
        if out.text:
            parts.append(out.text)
    return "".join(parts)


async def run_text(pipeline: LocalEnginePipeline, args) -> None:
    print(f"model: {pipeline.card.name} — interactive chat, ctrl-d to exit",
          flush=True)
    loop = asyncio.get_running_loop()
    history = []
    while True:
        try:
            line = await loop.run_in_executor(None, input, "user> ")
        except (EOFError, KeyboardInterrupt):
            return
        if not line.strip():
            continue
        history.append({"role": "user", "content": line})
        req = ChatCompletionRequest(model=pipeline.card.name,
                                    messages=list(history),
                                    max_tokens=args.max_tokens)
        sys.stdout.write("assistant> ")
        parts = []
        async for chunk in pipeline.generate_chat(req):
            for choice in chunk.choices:
                delta = choice.delta.content if choice.delta else None
                if delta:
                    parts.append(delta)
                    sys.stdout.write(delta)
                    sys.stdout.flush()
        sys.stdout.write("\n")
        history.append({"role": "assistant", "content": "".join(parts)})


async def run_stdin(pipeline: LocalEnginePipeline, args) -> None:
    loop = asyncio.get_running_loop()
    while True:
        line = await loop.run_in_executor(None, sys.stdin.readline)
        if not line:
            return
        line = line.strip()
        if line:
            print(await _complete(pipeline, line, args.max_tokens), flush=True)


async def run_batch(pipeline: LocalEnginePipeline, path: str, args) -> None:
    """jsonl in -> jsonl out with bounded concurrency (parity:
    ``lib/llm/src/entrypoint/input/batch.rs``)."""
    with open(path) as f:
        items = [json.loads(line) for line in f if line.strip()]
    sem = asyncio.Semaphore(args.concurrency)
    out_fh = sys.stdout if args.output == "-" else open(args.output, "w")
    t0 = time.perf_counter()
    done = 0

    async def one(i: int, item: dict) -> dict:
        async with sem:
            text = await _complete(pipeline, item["prompt"],
                                   item.get("max_tokens", args.max_tokens))
            return {"index": i, "prompt": item["prompt"], "text": text}

    try:
        results = await asyncio.gather(
            *[one(i, item) for i, item in enumerate(items)])
        for r in sorted(results, key=lambda r: r["index"]):
            out_fh.write(json.dumps(r) + "\n")
            done += 1
    finally:
        if out_fh is not sys.stdout:
            out_fh.close()
    print(f"batch: {done}/{len(items)} prompts in "
          f"{time.perf_counter() - t0:.2f}s", file=sys.stderr, flush=True)


async def amain(args) -> None:
    inp, out = parse_io(args.io)
    engine, card = build_engine_and_card(out, args)
    await engine.start()
    pipeline = LocalEnginePipeline(card, engine)
    try:
        if inp == "http":
            await run_http(pipeline, args)
        elif inp == "text":
            await run_text(pipeline, args)
        elif inp == "stdin":
            await run_stdin(pipeline, args)
        elif inp.startswith("batch:"):
            await run_batch(pipeline, inp[len("batch:"):], args)
        else:
            raise SystemExit(f"unknown input {inp!r}")
    finally:
        await engine.stop()


def main() -> None:
    configure_logging()
    try:
        asyncio.run(amain(build_parser().parse_args()))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
