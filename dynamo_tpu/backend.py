"""Backend stage: incremental detokenization + stop handling between the
engine and the frontend.

Parity: reference ``lib/llm/src/backend.rs:67-477`` (``Backend::from_mdc``,
``Decoder``/``DecodeStream``, the stop-sequence "jail", eos handling).

The *jail* holds back emitted text whenever its tail could be the start of a
stop sequence; once the tail provably can't complete any stop string, the held
text is released.  On a confirmed stop match, text is truncated at the match
and the stream finishes with ``FinishReason.STOP``.
"""

from __future__ import annotations

import logging
import time
from typing import AsyncIterator, List, Optional

from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.preprocessor.tokenizer import DecodeStream, HfTokenizer
from dynamo_tpu.protocols.common import (
    BackendOutput,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)

logger = logging.getLogger(__name__)


def _longest_suffix_prefix(text: str, stops: List[str]) -> int:
    """Length of the longest suffix of ``text`` that is a proper prefix of any
    stop string (i.e. text that must stay jailed)."""
    best = 0
    for stop in stops:
        maxlen = min(len(text), len(stop) - 1)
        for n in range(maxlen, 0, -1):
            if stop.startswith(text[-n:]):
                best = max(best, n)
                break
    return best


class StopJail:
    """Streaming stop-sequence matcher over text deltas."""

    def __init__(self, stops: List[str]):
        self.stops = [s for s in stops if s]
        self._held = ""
        self.matched: Optional[str] = None

    def push(self, delta: str) -> str:
        """Feed a text delta; returns text safe to emit now.  After a match,
        ``self.matched`` is set and everything from the stop string on is
        swallowed."""
        if self.matched is not None:
            return ""
        if not self.stops:
            return delta
        text = self._held + delta
        # earliest occurrence in the text wins, not list order
        best_idx, best_stop = -1, None
        for stop in self.stops:
            idx = text.find(stop)
            if idx >= 0 and (best_idx < 0 or idx < best_idx):
                best_idx, best_stop = idx, stop
        if best_stop is not None:
            self.matched = best_stop
            self._held = ""
            return text[:best_idx]
        keep = _longest_suffix_prefix(text, self.stops)
        self._held = text[len(text) - keep:] if keep else ""
        return text[:len(text) - keep] if keep else text

    def flush(self) -> str:
        """Release any jailed text at end of stream (no match happened)."""
        out, self._held = self._held, ""
        return out


class Backend:
    """Per-model detokenizer stage factory."""

    def __init__(self, card: ModelDeploymentCard,
                 tokenizer: Optional[HfTokenizer] = None):
        self.card = card
        self.tokenizer = tokenizer if tokenizer is not None else card.load_tokenizer()

    def _logprob_entry(self, piece: str, logprob: Optional[float],
                       top: Optional[dict], num_top: int) -> dict:
        """One OpenAI ``logprobs.content[]`` element (chat format; the
        completions route reshapes these into the legacy arrays).

        ``piece`` is the token's TRUE text delta from the incremental
        decoder — concatenating ``bytes`` across entries reconstructs the
        stream exactly (a token mid-multibyte contributes "" now and the
        full character lands on the completing token), unlike decoding the
        id in isolation, which yields U+FFFD for byte-fallback tokens.
        Alternatives are decoded in isolation (no stream position exists
        for a token that wasn't chosen).

        Reference surface: ``lib/llm/src/protocols/openai`` logprobs types;
        the engines there populate them via vLLM — here the native engine's
        top-K step outputs feed them directly."""
        entry = {"token": piece, "logprob": logprob,
                 "bytes": list(piece.encode("utf-8"))}
        if top:
            ranked = sorted(top.items(), key=lambda kv: -kv[1])[:num_top]
            entry["top_logprobs"] = [
                {"token": (t := self.tokenizer.decode([tid],
                                                      skip_special_tokens=False)),
                 "logprob": lp, "bytes": list(t.encode("utf-8"))}
                for tid, lp in ranked]
        return entry

    async def transform(self, request: PreprocessedRequest,
                        engine_stream: AsyncIterator[LLMEngineOutput]
                        ) -> AsyncIterator[BackendOutput]:
        """Wrap an engine output stream with detokenization + stop handling."""
        decoder = self.tokenizer.decode_stream()
        jail = StopJail(request.stop_conditions.stop or [])
        eos_ids = set(request.eos_token_ids or self.card.eos_token_ids)
        ignore_eos = request.stop_conditions.ignore_eos
        stop_ids = set(request.stop_conditions.stop_token_ids or [])
        completion = 0
        # None = logprobs off; 0 = sampled token only; N = +N alternatives
        want_logprobs = request.sampling_options.logprobs
        # detokenize stage accounting: the per-frame decode work is
        # interleaved with engine frames, so it's accumulated and recorded
        # as ONE retroactive span at stream end (utils/tracing)
        detok_s = 0.0

        try:
            async for out in engine_stream:
                if out.error:
                    yield BackendOutput(error=out.error,
                                        finish_reason=FinishReason.ERROR)
                    return
                _t0 = time.perf_counter()
                emit_ids: List[int] = []
                pieces: List[str] = []
                lp_content: Optional[List[dict]] = (
                    [] if want_logprobs is not None else None)
                finish: Optional[FinishReason] = out.finish_reason
                for j, tok in enumerate(out.token_ids):
                    completion += 1
                    if not ignore_eos and tok in eos_ids:
                        finish = FinishReason.EOS
                        break
                    if tok in stop_ids:
                        finish = FinishReason.STOP
                        break
                    emit_ids.append(tok)
                    piece = decoder.step(tok)
                    pieces.append(piece)
                    if lp_content is not None:
                        lp = (out.log_probs[j]
                              if out.log_probs and j < len(out.log_probs)
                              else None)
                        top = (out.top_logprobs[j]
                               if out.top_logprobs
                               and j < len(out.top_logprobs) else None)
                        lp_content.append(self._logprob_entry(
                            piece, lp, top, want_logprobs))
                text = jail.push("".join(pieces)) if pieces else ""
                if jail.matched is not None:
                    finish = FinishReason.STOP
                    if lp_content:
                        # drop entries for tokens the jail trimmed (the stop
                        # string itself). Approximate across frames: text
                        # may include chars the jail held from earlier
                        # frames whose entries already went out, which only
                        # errs toward keeping a boundary token.
                        kept, acc = [], 0
                        for e in lp_content:
                            if acc >= len(text):
                                break
                            kept.append(e)
                            acc += len(e["token"])
                        lp_content = kept
                detok_s += time.perf_counter() - _t0
                if finish is not None:
                    if jail.matched is None:
                        text += jail.flush()
                    yield BackendOutput(
                        token_ids=emit_ids, text=text or None,
                        finish_reason=finish,
                        cum_log_probs=out.cum_log_probs, log_probs=out.log_probs,
                        logprobs_content=lp_content or None,
                        prompt_tokens=out.prompt_tokens or len(request.token_ids),
                        completion_tokens=out.completion_tokens or completion,
                        cached_tokens=out.cached_tokens)
                    return
                if emit_ids or text:
                    yield BackendOutput(
                        token_ids=emit_ids, text=text or None,
                        cum_log_probs=out.cum_log_probs, log_probs=out.log_probs,
                        logprobs_content=lp_content or None)
            # engine ended without a finish reason: surface what we have
            tail = jail.flush()
            yield BackendOutput(
                token_ids=[], text=tail or None, finish_reason=FinishReason.LENGTH,
                prompt_tokens=len(request.token_ids), completion_tokens=completion)
        finally:
            # Deterministically close the engine hop on early exit (stop match,
            # client disconnect): propagates GeneratorExit down the chain so
            # remote streams send a cancel frame instead of generating on.
            aclose = getattr(engine_stream, "aclose", None)
            if aclose is not None:
                await aclose()
            if detok_s > 0:
                # retroactive span: the accumulated decode time, anchored so
                # it ends now (the stage breakdown cares about the total,
                # not the interleaving)
                from dynamo_tpu.utils.tracing import get_tracer
                now = time.time()
                get_tracer().record("detokenize", now - detok_s, now,
                                    attrs={"accumulated": True})


__all__ = ["Backend", "StopJail"]
