"""Backend stage: incremental detokenization + stop handling between the
engine and the frontend.

Parity: reference ``lib/llm/src/backend.rs:67-477`` (``Backend::from_mdc``,
``Decoder``/``DecodeStream``, the stop-sequence "jail", eos handling).

The *jail* holds back emitted text whenever its tail could be the start of a
stop sequence; once the tail provably can't complete any stop string, the held
text is released.  On a confirmed stop match, text is truncated at the match
and the stream finishes with ``FinishReason.STOP``.
"""

from __future__ import annotations

import logging
from typing import AsyncIterator, List, Optional

from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.preprocessor.tokenizer import DecodeStream, HfTokenizer
from dynamo_tpu.protocols.common import (
    BackendOutput,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)

logger = logging.getLogger(__name__)


def _longest_suffix_prefix(text: str, stops: List[str]) -> int:
    """Length of the longest suffix of ``text`` that is a proper prefix of any
    stop string (i.e. text that must stay jailed)."""
    best = 0
    for stop in stops:
        maxlen = min(len(text), len(stop) - 1)
        for n in range(maxlen, 0, -1):
            if stop.startswith(text[-n:]):
                best = max(best, n)
                break
    return best


class StopJail:
    """Streaming stop-sequence matcher over text deltas."""

    def __init__(self, stops: List[str]):
        self.stops = [s for s in stops if s]
        self._held = ""
        self.matched: Optional[str] = None

    def push(self, delta: str) -> str:
        """Feed a text delta; returns text safe to emit now.  After a match,
        ``self.matched`` is set and everything from the stop string on is
        swallowed."""
        if self.matched is not None:
            return ""
        if not self.stops:
            return delta
        text = self._held + delta
        # earliest occurrence in the text wins, not list order
        best_idx, best_stop = -1, None
        for stop in self.stops:
            idx = text.find(stop)
            if idx >= 0 and (best_idx < 0 or idx < best_idx):
                best_idx, best_stop = idx, stop
        if best_stop is not None:
            self.matched = best_stop
            self._held = ""
            return text[:best_idx]
        keep = _longest_suffix_prefix(text, self.stops)
        self._held = text[len(text) - keep:] if keep else ""
        return text[:len(text) - keep] if keep else text

    def flush(self) -> str:
        """Release any jailed text at end of stream (no match happened)."""
        out, self._held = self._held, ""
        return out


class Backend:
    """Per-model detokenizer stage factory."""

    def __init__(self, card: ModelDeploymentCard,
                 tokenizer: Optional[HfTokenizer] = None):
        self.card = card
        self.tokenizer = tokenizer if tokenizer is not None else card.load_tokenizer()

    async def transform(self, request: PreprocessedRequest,
                        engine_stream: AsyncIterator[LLMEngineOutput]
                        ) -> AsyncIterator[BackendOutput]:
        """Wrap an engine output stream with detokenization + stop handling."""
        decoder = self.tokenizer.decode_stream()
        jail = StopJail(request.stop_conditions.stop or [])
        eos_ids = set(request.eos_token_ids or self.card.eos_token_ids)
        ignore_eos = request.stop_conditions.ignore_eos
        stop_ids = set(request.stop_conditions.stop_token_ids or [])
        completion = 0

        try:
            async for out in engine_stream:
                if out.error:
                    yield BackendOutput(error=out.error,
                                        finish_reason=FinishReason.ERROR)
                    return
                emit_ids: List[int] = []
                finish: Optional[FinishReason] = out.finish_reason
                for tok in out.token_ids:
                    completion += 1
                    if not ignore_eos and tok in eos_ids:
                        finish = FinishReason.EOS
                        break
                    if tok in stop_ids:
                        finish = FinishReason.STOP
                        break
                    emit_ids.append(tok)
                text = jail.push(decoder.extend(emit_ids)) if emit_ids else ""
                if jail.matched is not None:
                    finish = FinishReason.STOP
                if finish is not None:
                    if jail.matched is None:
                        text += jail.flush()
                    yield BackendOutput(
                        token_ids=emit_ids, text=text or None,
                        finish_reason=finish,
                        cum_log_probs=out.cum_log_probs, log_probs=out.log_probs,
                        prompt_tokens=out.prompt_tokens or len(request.token_ids),
                        completion_tokens=out.completion_tokens or completion,
                        cached_tokens=out.cached_tokens)
                    return
                if emit_ids or text:
                    yield BackendOutput(
                        token_ids=emit_ids, text=text or None,
                        cum_log_probs=out.cum_log_probs, log_probs=out.log_probs)
            # engine ended without a finish reason: surface what we have
            tail = jail.flush()
            yield BackendOutput(
                token_ids=[], text=tail or None, finish_reason=FinishReason.LENGTH,
                prompt_tokens=len(request.token_ids), completion_tokens=completion)
        finally:
            # Deterministically close the engine hop on early exit (stop match,
            # client disconnect): propagates GeneratorExit down the chain so
            # remote streams send a cancel frame instead of generating on.
            aclose = getattr(engine_stream, "aclose", None)
            if aclose is not None:
                await aclose()


__all__ = ["Backend", "StopJail"]
