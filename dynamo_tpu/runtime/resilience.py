"""Failure-aware routing policy: breakers, retry budget, latency book.

The three classic resilience mechanisms wrapped around instance selection
(``runtime/push_router.py`` and ``kv_router/``), in the lineage of
Finagle/Envoy outlier handling and "The Tail at Scale" hedging:

- ``CircuitBreaker`` per instance: closed -> open after N consecutive
  failures (connect errors, stream drops, deadline timeouts — and, when
  ``breaker_slow_ttft_s`` is set, slow-call TTFT observations) -> half-open
  single probe after a cooldown that doubles on repeated opens -> closed on
  probe success.  Keepalive-down reports force an immediate open, so the
  breaker fires *before* lease expiry removes the instance.
- ``RetryBudget``: a frontend-wide token bucket — every first attempt
  deposits ``ratio`` tokens (default 0.1: at most ~10% of requests may
  retry), every retry or hedge spends one — so a transient fault is
  retried but a fleet-wide brownout cannot amplify into a retry storm.
- ``LatencyBook``: per-instance EWMA of observed TTFT and request latency
  plus a fleet-wide p95 TTFT ring, feeding the cost score and the hedge
  delay.

``RouterPolicy`` composes the three with router-side in-flight counts and
scraped worker stats (queue depth / active slots from the ``__stats__``
plane) into one object shared by a ``PushRouter`` and (in KV mode) the
``KvScheduler``.  All counters surface process-wide through
``get_router_stats()`` — sampled by the frontend's /metrics collector
(``dynamo_frontend_router_*``) so tests and dashboards see one book.
"""

from __future__ import annotations

import enum
import logging
import random
import time
from collections import defaultdict, deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


# /metrics gauge encoding of BreakerState (documented in observability.md)
BREAKER_GAUGE = {BreakerState.CLOSED: 0.0, BreakerState.HALF_OPEN: 0.5,
                 BreakerState.OPEN: 1.0}


class RouterStats:
    """Process-wide router counters, sampled at /metrics scrape time by
    ``http.metrics.RouterMetricsCollector``.  Plain dicts, no prometheus
    objects — routers live outside the HTTP service's registry."""

    def __init__(self) -> None:
        self.decisions: Dict[str, int] = defaultdict(int)      # by policy
        self.retries: Dict[str, int] = defaultdict(int)        # by reason
        self.hedges: Dict[str, int] = defaultdict(int)         # by outcome
        self.breaker_transitions: Dict[str, int] = defaultdict(int)  # by state
        self.breaker_states: Dict[str, float] = {}             # by instance hex
        self.budget_balance: float = 0.0
        self.budget_exhausted: int = 0
        # NetKV-style pricing: decisions where a remote prefix hit was
        # priced against the measured kv_transfer plane bandwidth
        self.net_priced: Dict[str, int] = defaultdict(int)     # by outcome
        self.net_cost_seconds_sum: float = 0.0
        self.net_cost_seconds_count: int = 0

    def note_net_priced(self, outcome: str, net_cost_s: float) -> None:
        self.net_priced[outcome] += 1
        self.net_cost_seconds_sum += max(0.0, net_cost_s)
        self.net_cost_seconds_count += 1


_STATS = RouterStats()


def get_router_stats() -> RouterStats:
    return _STATS


@dataclass
class RouterPolicyConfig:
    """Knobs for the failure-aware routing policy (docs/deployment.md
    "Failure-aware routing" table; layered through RuntimeConfig
    ``router_*`` fields and frontend CLI flags)."""

    breaker_failures: int = 3          # consecutive failures that open
    breaker_cooldown_s: float = 1.0    # first open->half-open dwell
    breaker_cooldown_cap_s: float = 30.0  # dwell doubles per re-open, capped
    breaker_slow_ttft_s: float = 0.0   # TTFT >= this counts as a failure (0 off)
    retry_budget_ratio: float = 0.1    # tokens earned per first attempt
    retry_budget_floor: float = 3.0    # starting balance (cold-start retries)
    hedge: bool = False                # hedged dispatch for routed requests
    hedge_delay_s: float = 0.0         # fixed hedge delay (0 = p95-based)
    hedge_delay_floor_s: float = 0.02  # lower bound on the p95-based delay
    ttft_weight: float = 25.0          # score units per second of EWMA TTFT
    net_weight: float = 25.0           # score units per second of KV transfer
    ewma_alpha: float = 0.3            # EWMA smoothing for TTFT/latency
    stats_interval_s: float = 1.0      # __stats__ scrape period (COST mode)

    @classmethod
    def from_runtime_config(cls, cfg: Any) -> "RouterPolicyConfig":
        return cls(
            breaker_failures=cfg.router_breaker_failures,
            breaker_cooldown_s=cfg.router_breaker_cooldown_s,
            breaker_slow_ttft_s=cfg.router_breaker_slow_ttft_s,
            retry_budget_ratio=cfg.router_retry_budget,
            hedge=cfg.router_hedge,
            hedge_delay_s=cfg.router_hedge_delay_s,
            stats_interval_s=cfg.router_stats_interval_s)


class CircuitBreaker:
    """closed -> open -> half-open -> closed, for one instance.

    ``allow()`` is side-effect free (selection filters call it for every
    candidate); the single half-open probe slot is claimed by
    ``on_dispatch()`` when a request is actually sent."""

    def __init__(self, failures: int = 3, cooldown_s: float = 1.0,
                 cooldown_cap_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, failures)
        self.base_cooldown_s = cooldown_s
        self.cooldown_cap_s = cooldown_cap_s
        self._clock = clock
        self.state = BreakerState.CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._cooldown = cooldown_s
        self._probe_inflight = False
        self.opens = 0  # lifetime open transitions (incl. force_open)

    def allow(self) -> bool:
        if self.state is BreakerState.CLOSED:
            return True
        if self.state is BreakerState.OPEN:
            return (self._clock() - self._opened_at) >= self._cooldown
        return not self._probe_inflight  # HALF_OPEN: one probe at a time

    def on_dispatch(self) -> None:
        """A request was actually sent to this instance."""
        if (self.state is BreakerState.OPEN
                and (self._clock() - self._opened_at) >= self._cooldown):
            self.state = BreakerState.HALF_OPEN
            self._probe_inflight = True
        elif self.state is BreakerState.HALF_OPEN:
            self._probe_inflight = True

    def record_success(self) -> bool:
        """Returns True when the breaker just closed (half-open probe won)."""
        self._consecutive = 0
        self._probe_inflight = False
        if self.state is not BreakerState.CLOSED:
            self.state = BreakerState.CLOSED
            self._cooldown = self.base_cooldown_s
            return True
        return False

    def record_failure(self) -> bool:
        """Returns True when the breaker just opened."""
        self._consecutive += 1
        self._probe_inflight = False
        if self.state is BreakerState.HALF_OPEN:
            # failed probe: back to open with a doubled dwell
            self._cooldown = min(self.cooldown_cap_s, self._cooldown * 2)
            return self._open()
        if (self.state is BreakerState.CLOSED
                and self._consecutive >= self.failure_threshold):
            return self._open()
        return False

    def force_open(self) -> bool:
        """Immediate open (keepalive declared the instance down)."""
        self._probe_inflight = False
        return self._open()

    def _open(self) -> bool:
        was_open = self.state is BreakerState.OPEN
        self.state = BreakerState.OPEN
        self._opened_at = self._clock()
        if not was_open:
            self.opens += 1
        return not was_open


class BreakerBoard:
    """Per-instance breakers for one endpoint, with /metrics bookkeeping."""

    def __init__(self, cfg: RouterPolicyConfig,
                 stats: Optional[RouterStats] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self.stats = stats or get_router_stats()
        self._clock = clock
        self._breakers: Dict[int, CircuitBreaker] = {}

    def get(self, iid: int) -> CircuitBreaker:
        br = self._breakers.get(iid)
        if br is None:
            br = self._breakers[iid] = CircuitBreaker(
                failures=self.cfg.breaker_failures,
                cooldown_s=self.cfg.breaker_cooldown_s,
                cooldown_cap_s=self.cfg.breaker_cooldown_cap_s,
                clock=self._clock)
        return br

    def allow(self, iid: int) -> bool:
        return self.get(iid).allow()

    def on_dispatch(self, iid: int) -> None:
        br = self.get(iid)
        before = br.state
        br.on_dispatch()
        if br.state is not before:
            self._transition(iid, br)

    def record_success(self, iid: int) -> None:
        br = self.get(iid)
        if br.record_success():
            self._transition(iid, br)
        else:
            self._publish(iid, br)

    def record_failure(self, iid: int) -> None:
        br = self.get(iid)
        if br.record_failure():
            logger.warning("circuit breaker for instance %x opened", iid)
            self._transition(iid, br)

    def force_open(self, iid: int) -> None:
        br = self.get(iid)
        if br.force_open():
            logger.warning("circuit breaker for instance %x force-opened "
                           "(instance reported down)", iid)
            self._transition(iid, br)

    def state(self, iid: int) -> BreakerState:
        return self.get(iid).state

    def open_count(self) -> int:
        return sum(1 for b in self._breakers.values()
                   if b.state is BreakerState.OPEN)

    def opens_total(self) -> int:
        return sum(b.opens for b in self._breakers.values())

    def prune(self, live: set) -> None:
        for iid in [i for i in self._breakers if i not in live]:
            del self._breakers[iid]
            self.stats.breaker_states.pop(f"{iid:x}", None)

    def _transition(self, iid: int, br: CircuitBreaker) -> None:
        self.stats.breaker_transitions[br.state.value] += 1
        self._publish(iid, br)

    def _publish(self, iid: int, br: CircuitBreaker) -> None:
        self.stats.breaker_states[f"{iid:x}"] = BREAKER_GAUGE[br.state]


class RetryBudget:
    """Token bucket bounding retries+hedges to a fraction of traffic."""

    def __init__(self, ratio: float = 0.1, floor: float = 3.0,
                 stats: Optional[RouterStats] = None):
        self.ratio = max(0.0, ratio)
        self.floor = max(0.0, floor)
        # cap keeps a quiet period from banking unbounded retry credit
        self.cap = max(self.floor, 10.0)
        self.balance = self.floor
        self.stats = stats or get_router_stats()
        self.stats.budget_balance = self.balance

    def deposit(self) -> None:
        self.balance = min(self.cap, self.balance + self.ratio)
        self.stats.budget_balance = self.balance

    def try_spend(self, cost: float = 1.0) -> bool:
        if self.balance >= cost:
            self.balance -= cost
            self.stats.budget_balance = self.balance
            return True
        self.stats.budget_exhausted += 1
        return False


class LatencyBook:
    """Per-instance EWMA TTFT/latency plus a fleet-wide p95 TTFT ring."""

    def __init__(self, alpha: float = 0.3, ring: int = 256):
        self.alpha = alpha
        self._ttft: Dict[int, float] = {}
        self._latency: Dict[int, float] = {}
        self._recent: deque = deque(maxlen=ring)

    def observe_ttft(self, iid: int, seconds: float) -> None:
        prev = self._ttft.get(iid)
        self._ttft[iid] = (seconds if prev is None
                           else prev + self.alpha * (seconds - prev))
        self._recent.append(seconds)

    def observe_latency(self, iid: int, seconds: float) -> None:
        prev = self._latency.get(iid)
        self._latency[iid] = (seconds if prev is None
                              else prev + self.alpha * (seconds - prev))

    def ttft(self, iid: int, default: float = 0.0) -> float:
        return self._ttft.get(iid, default)

    def latency(self, iid: int, default: float = 0.0) -> float:
        return self._latency.get(iid, default)

    def ttft_p95(self, default: float = 0.0) -> float:
        if not self._recent:
            return default
        ordered = sorted(self._recent)
        return ordered[min(len(ordered) - 1, int(len(ordered) * 0.95))]

    def prune(self, live: set) -> None:
        for d in (self._ttft, self._latency):
            for iid in [i for i in d if i not in live]:
                del d[iid]


class RouterPolicy:
    """Shared resilience + scoring state for one endpoint's fleet."""

    def __init__(self, config: Optional[RouterPolicyConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = config or RouterPolicyConfig()
        self.stats = get_router_stats()
        self.breakers = BreakerBoard(self.cfg, self.stats, clock=clock)
        self.budget = RetryBudget(self.cfg.retry_budget_ratio,
                                  self.cfg.retry_budget_floor, self.stats)
        self.lat = LatencyBook(alpha=self.cfg.ewma_alpha)
        self.inflight: Dict[int, int] = defaultdict(int)
        # scraped worker-side view: iid -> {queue_depth, active_slots, active}
        self.worker_stats: Dict[int, Dict[str, float]] = {}
        # scraped kv_transfer bandwidth book: iid -> {plane -> bytes/s EWMA}
        # (what workers publish from KvBandwidthBook.snapshot())
        self.net_bw: Dict[int, Dict[str, float]] = {}

    # -- client wiring -----------------------------------------------------

    def attach_client(self, client: Any) -> None:
        """Feed keepalive/error instance-down reports into the breakers —
        the breaker opens the moment the pool declares a connection dead,
        before lease expiry prunes the instance."""
        add = getattr(client, "add_down_listener", None)
        if add is not None:
            add(self.on_instance_down)

    def on_instance_down(self, iid: int) -> None:
        self.breakers.force_open(iid)

    # -- request accounting ------------------------------------------------

    def begin(self, iid: int) -> None:
        self.inflight[iid] += 1

    def end(self, iid: int) -> None:
        n = self.inflight.get(iid, 0)
        if n <= 1:
            self.inflight.pop(iid, None)
        else:
            self.inflight[iid] = n - 1

    def observe_ttft(self, iid: int, seconds: float) -> None:
        self.lat.observe_ttft(iid, seconds)
        slow = self.cfg.breaker_slow_ttft_s
        if slow > 0 and seconds >= slow:
            # slow-call accounting: a worker that answers, but only after
            # the threshold, fails toward an open breaker — the
            # SIGSTOP/ChaosProxy-delay case PR 2 could only *detect*
            self.breakers.record_failure(iid)

    def on_success(self, iid: int, latency_s: Optional[float] = None) -> None:
        self.breakers.record_success(iid)
        if latency_s is not None:
            self.lat.observe_latency(iid, latency_s)

    def on_failure(self, iid: int, kind: str) -> None:
        self.breakers.record_failure(iid)

    # -- scraped worker stats ----------------------------------------------

    def ingest_scrape(self, scraped: Dict[int, Any], endpoint_path: str) -> None:
        """Parse a ``component.scrape_stats()`` result (the ``__stats__``
        plane: {iid: {path: {requests, active, errors, data}}}) into the
        per-instance load view the scorer reads."""
        for iid, stats in scraped.items():
            ep = stats.get(endpoint_path) if isinstance(stats, dict) else None
            if not isinstance(ep, dict):
                continue
            data = ep.get("data") if isinstance(ep.get("data"), dict) else {}
            ws = data.get("worker_stats") if isinstance(
                data.get("worker_stats"), dict) else {}
            self.worker_stats[iid] = {
                "queue_depth": float(ws.get("num_requests_waiting", 0) or 0),
                "active_slots": float(ws.get("request_active_slots", 0) or 0),
                "active": float(ep.get("active", 0) or 0),
            }
            kt = data.get("kv_transfer") if isinstance(
                data.get("kv_transfer"), dict) else {}
            planes = {}
            for plane, snap in kt.items():
                if isinstance(snap, dict):
                    bw = float(snap.get("bw_bytes_per_s", 0) or 0)
                    if bw > 0:
                        planes[str(plane)] = bw
            if planes:
                self.net_bw[iid] = planes

    def update_worker_stats(self, iid: int, queue_depth: float,
                            active_slots: float = 0.0,
                            active: float = 0.0) -> None:
        self.worker_stats[iid] = {"queue_depth": float(queue_depth),
                                  "active_slots": float(active_slots),
                                  "active": float(active)}

    def plane_bw(self, iid: int) -> float:
        """Best measured kv_transfer bandwidth (bytes/s EWMA) toward a
        worker, across planes — 0.0 when no transfer has been observed
        (an unmeasured path earns no remote-hit credit)."""
        planes = self.net_bw.get(iid)
        return max(planes.values()) if planes else 0.0

    def net_cost_s(self, iid: int, est_transfer_bytes: float) -> float:
        """NetKV-style network price: seconds to move the missing prefix
        over the best measured plane.  ``inf`` when bytes must move but no
        bandwidth has ever been observed."""
        if est_transfer_bytes <= 0:
            return 0.0
        bw = self.plane_bw(iid)
        return (est_transfer_bytes / bw) if bw > 0 else float("inf")

    def prune(self, live: set) -> None:
        self.breakers.prune(live)
        self.lat.prune(live)
        for iid in [i for i in self.worker_stats if i not in live]:
            del self.worker_stats[iid]
        for iid in [i for i in self.inflight if i not in live]:
            del self.inflight[iid]
        for iid in [i for i in self.net_bw if i not in live]:
            del self.net_bw[iid]

    # -- scoring -----------------------------------------------------------

    def score(self, iid: int,
              est_transfer_bytes: float = 0.0) -> Tuple[float, Dict[str, Any]]:
        """Cost of routing one more request to ``iid``, with the inputs —
        the per-decision trace attrs the ROADMAP's "debuggable post-hoc"
        requirement asks for.  ``est_transfer_bytes`` is the KV volume a
        remote placement would have to move to this worker; it is priced
        at the measured per-plane bandwidth EWMA (``net_cost`` term)."""
        ws = self.worker_stats.get(iid, {})
        inflight = self.inflight.get(iid, 0)
        queue = ws.get("queue_depth", 0.0)
        active = ws.get("active_slots", 0.0)
        ewma = self.lat.ttft(iid, 0.0)
        net_cost = self.net_cost_s(iid, est_transfer_bytes)
        net_term = (self.cfg.net_weight * net_cost
                    if net_cost not in (0.0, float("inf")) else 0.0)
        total = (inflight + queue + active + self.cfg.ttft_weight * ewma
                 + net_term)
        state = self.breakers.state(iid)
        return total, {
            "score": round(total, 4),
            "ewma_ttft_s": round(ewma, 4),
            "inflight": inflight,
            "queue_depth": queue,
            "active_slots": active,
            "net_cost": (round(net_cost, 6)
                         if net_cost != float("inf") else -1.0),
            "breaker": state.value,
        }

    def cost_bias(self, iid: int) -> float:
        """The terms the KV scheduler's own cost model lacks: router-side
        in-flight count and observed-latency penalty.  (Queue depth is NOT
        included — the KvScheduler already prices scraped
        ``num_requests_waiting``.)"""
        return (self.inflight.get(iid, 0)
                + self.cfg.ttft_weight * self.lat.ttft(iid, 0.0))

    def select(self, candidates: List[int]) -> Tuple[int, Dict[str, Any]]:
        """Min-cost choice with random tie-break; candidates are assumed
        pre-filtered for breakers/drain by the caller."""
        scored = [(self.score(i), i) for i in candidates]
        best = min(s for (s, _), _ in scored)
        ties = [(inputs, i) for (s, inputs), i in scored if s == best]
        inputs, chosen = random.choice(ties)
        inputs = dict(inputs)
        inputs["candidates"] = len(candidates)
        inputs["breakers_open"] = self.breakers.open_count()
        return chosen, inputs

    # -- hedging / deadlines -----------------------------------------------

    def hedge_delay_s(self) -> float:
        if self.cfg.hedge_delay_s > 0:
            return self.cfg.hedge_delay_s
        return max(self.cfg.hedge_delay_floor_s,
                   self.lat.ttft_p95(self.cfg.hedge_delay_floor_s))

    def can_redispatch(self, iid: int, deadline_unix: Optional[float]) -> bool:
        """Satellite-1 guard: a retry or hedge whose target cannot plausibly
        produce a first token before the deadline is never dispatched — the
        worker would only drop it."""
        if deadline_unix is None:
            return True
        return (deadline_unix - time.time()) > self.lat.ttft(iid, 0.0)


__all__ = ["BreakerState", "BREAKER_GAUGE", "CircuitBreaker", "BreakerBoard",
           "RetryBudget", "LatencyBook", "RouterPolicy", "RouterPolicyConfig",
           "RouterStats", "get_router_stats"]
