"""Leader/worker barrier: multi-host engine bring-up rendezvous.

Parity: reference ``lib/runtime/src/utils/leader_worker_barrier.rs:16-80`` —
the leader publishes shared bring-up data under ``barrier/{id}/data``, waits
for N workers to check in under ``barrier/{id}/workers/...``, then publishes
``complete``; workers post their check-in and block on the completion marker.
Used to coordinate multi-host jax slice start-up (host 0 = leader owning the
serving endpoint, other hosts join the mesh) the way the reference gates
multi-node sglang/trtllm launches over etcd.

Keys carry the caller's lease so a crashed participant's check-in vanishes
with its lease instead of wedging the next rendezvous.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Optional

logger = logging.getLogger(__name__)


def _data_key(barrier_id: str) -> str:
    return f"barrier/{barrier_id}/data"


def _worker_prefix(barrier_id: str) -> str:
    return f"barrier/{barrier_id}/workers/"


def _status_key(barrier_id: str) -> str:
    return f"barrier/{barrier_id}/status"


class BarrierError(RuntimeError):
    pass


class _ResyncPuts:
    """Replays this participant's barrier keys across a coordinator restart.

    Barrier keys ride the primary lease, so a state-wiped coordinator loses
    them; while a rendezvous is in flight each participant keeps its own
    puts here and re-issues them from a resync hook (under the lease's
    CURRENT id — the resync may have re-granted it). Peers' watches then see
    the re-puts as synthesized events and the rendezvous completes instead
    of deadlocking."""

    def __init__(self, drt: Any, lease: Any):
        self._drt = drt
        self._lease = lease
        self._keys: dict = {}
        drt.coord.add_resync_hook(self._replay)

    async def put(self, key: str, value: bytes) -> None:
        self._keys[key] = value
        await self._drt.coord.put(key, value,
                                  lease_id=self._lease.lease_id)

    async def _replay(self) -> None:
        for key, value in self._keys.items():
            await self._drt.coord.put(key, value,
                                      lease_id=self._lease.lease_id)
        if self._keys:
            logger.info("re-published %d barrier key(s) after coordinator "
                        "resync", len(self._keys))

    def close(self) -> None:
        self._drt.coord.remove_resync_hook(self._replay)


async def leader_barrier(drt, barrier_id: str, data: Any, num_workers: int,
                         timeout: float = 60.0) -> None:
    """Publish data, await ``num_workers`` check-ins, mark complete.

    On timeout the barrier is marked aborted (workers waiting on it fail
    fast) and ``BarrierError`` raises.
    """
    lease = await drt.primary_lease()
    puts = _ResyncPuts(drt, lease)
    watch = None
    try:  # from here: puts.close() must run even if the first put fails
        await puts.put(_data_key(barrier_id), json.dumps(data).encode())
        watch = await drt.coord.watch_prefix(_worker_prefix(barrier_id))
        seen = {key for key, _v in watch.snapshot}
        deadline = asyncio.get_running_loop().time() + timeout
        while len(seen) < num_workers:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                await puts.put(_status_key(barrier_id), b"abort")
                raise BarrierError(
                    f"barrier {barrier_id}: {len(seen)}/{num_workers} workers "
                    f"after {timeout}s")
            try:
                ev = await asyncio.wait_for(watch.__anext__(),
                                            timeout=remaining)
            except asyncio.TimeoutError:
                continue
            if ev.type == "put":
                seen.add(ev.key)
        await puts.put(_status_key(barrier_id), b"complete")
    finally:
        puts.close()
        if watch is not None:
            try:
                await watch.cancel()
            except Exception:
                pass


async def worker_barrier(drt, barrier_id: str, worker_name: str,
                         timeout: float = 60.0) -> Any:
    """Check in and wait for completion; returns the leader's data."""
    lease = await drt.primary_lease()
    puts = _ResyncPuts(drt, lease)
    watch = None
    try:  # from here: puts.close() must run even if the first put fails
        await puts.put(f"{_worker_prefix(barrier_id)}{worker_name}",
                       worker_name.encode())
        watch = await drt.coord.watch_prefix(_status_key(barrier_id))
        status: Optional[bytes] = None
        for _key, value in watch.snapshot:
            status = value
        deadline = asyncio.get_running_loop().time() + timeout
        while status not in (b"complete", b"abort"):
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise BarrierError(
                    f"barrier {barrier_id}: no completion after {timeout}s")
            try:
                ev = await asyncio.wait_for(watch.__anext__(),
                                            timeout=remaining)
            except asyncio.TimeoutError:
                continue
            if ev.type == "put" and ev.value is not None:
                status = ev.value
        if status == b"abort":
            raise BarrierError(f"barrier {barrier_id} aborted by leader")
        raw = await drt.coord.get(_data_key(barrier_id))
        if raw is None:
            raise BarrierError(f"barrier {barrier_id}: data vanished")
        return json.loads(raw)
    finally:
        puts.close()
        if watch is not None:
            try:
                await watch.cancel()
            except Exception:
                pass


__all__ = ["leader_barrier", "worker_barrier", "BarrierError"]
