"""Coordinator: the framework's self-contained control-plane service.

One asyncio TCP server provides what the reference gets from two external
services (SURVEY.md §2.1, L0):

- **KV plane** (etcd-equivalent; reference ``lib/runtime/src/transports/etcd.rs``):
  put/get/delete with prefix queries, *leases* with TTL + keep-alive (all keys
  attached to a lease vanish when it expires — that is the liveness mechanism),
  and *prefix watches* that stream put/delete events to clients.
- **Event plane** (NATS-equivalent; reference ``transports/nats.rs``):
  subject-based pub/sub with trailing-wildcard subscriptions (``a.b.>``), used
  for KV-cache events, metrics broadcasts and the prefill queue.
- **Object store** (reference uses NATS object store for model-card artifacts):
  named buckets of binary blobs, implemented on the KV plane with chunking.
- **Barrier** (reference ``utils/leader_worker_barrier.rs``): implemented
  client-side on KV + watch (see ``LeaderWorkerBarrier`` in barrier.py).

Wire protocol: length-prefixed msgpack frames (codec.py).  Requests carry a
client-assigned ``rid`` and are answered with ``{"rid", "ok", ...}``; server-
initiated traffic (watch events, pub/sub messages) carries ``evt`` instead.

The coordinator is deliberately a single-threaded asyncio process: control
plane operations are low-rate (registrations, watches, metrics) while the hot
request path rides direct worker TCP connections and never touches it.

**Replication & failover** (parity in intent with etcd's Raft replication,
scaled down to a primary + hot-standby pair): a standby coordinator
(``--standby-of host:port``) attaches to the primary over the SAME wire
protocol (``repl_attach``), receives a full state snapshot (KV, leases with
remaining TTLs, queues, boot epoch, id counter, fencing term), then applies
the primary's ordered replication log (put/delete, lease grant/keepalive/
revoke, queue push/pop) streamed as ``evt: "repl"`` frames.  Because the
standby mirrors the primary's *boot epoch and id counter*, promotion looks to
a resyncing ``CoordClient`` like a blip of the same server: the resync takes
the cheap probe path (keepalive each lease — it exists, same id) instead of
the re-grant storm a fresh process forces.  Lease deadlines are rebased by a
grace window at promotion so the fleet doesn't mass-expire mid-failover.

Split-brain safety rides a **monotonic fencing term**: bumped at every
promotion, echoed on ping, stamped on writes by term-aware clients.  A write
stamped with a term the server doesn't hold bounces (``fenced: True`` + the
highest term known) and the client re-points along its address list; a
primary that observes a higher term — via a stamped write or its peer probe
of a lost standby — knows it is deposed, fences its writers, and (when it
knows the new primary's address) demotes itself into a hot standby of it,
restoring redundancy automatically.  Requests without a term field (PR 3-era
clients) are served exactly as before: fencing is opt-in at the wire level.

``CoordClient`` accepts a comma-separated address list
(``"host:6650,host:6651"``); connect and the PR 3 reconnect loop walk the
list, skipping standbys/deposed primaries, so failover needs no client
reconfiguration.  With a single address and a non-replicated server the
behavior is bit-for-bit the PR 3 protocol.
"""

from __future__ import annotations

import asyncio
import fnmatch
import itertools
import logging
import os
import random
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (Any, AsyncIterator, Awaitable, Callable, Dict, List,
                    Optional, Tuple)

from dynamo_tpu.runtime.codec import read_frame, send_frame, write_frame
from dynamo_tpu.utils.aio import decorrelated_jitter, reap_task

logger = logging.getLogger(__name__)

LEASE_SCAN_INTERVAL = 0.5  # seconds between lease-expiry scans

# replication / failover knobs (constructor args override)
DEFAULT_PROMOTE_AFTER_S = 2.0       # standby self-promotes after this silence
DEFAULT_PROMOTE_LEASE_GRACE_S = 1.0  # extra lease headroom added at promotion

# ops that mutate replicated state: term-fenced on the server, term-stamped
# by term-aware clients. queue_pull consumes a job, queue_cancel unparks a
# pull — both are state changes a deposed primary must not serve.
_WRITE_OPS = frozenset({
    "put", "put_if_absent", "delete", "delete_prefix", "grant_lease",
    "keepalive", "revoke", "queue_push", "queue_pull", "queue_cancel"})


def _subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style match: exact, or trailing ``>`` wildcard matching the rest."""
    if pattern == subject:
        return True
    if pattern.endswith(".>"):
        return subject.startswith(pattern[:-1])  # keep the dot
    if pattern == ">":
        return True
    return False


@dataclass
class _KvEntry:
    value: bytes
    lease_id: int = 0
    version: int = 1


@dataclass
class _Lease:
    lease_id: int
    ttl: float
    expires_at: float
    keys: set = field(default_factory=set)


@dataclass
class _Watch:
    watch_id: int
    prefix: str
    conn: "_Conn"


@dataclass
class _Subscription:
    sub_id: int
    pattern: str
    conn: "_Conn"
    queue_group: Optional[str] = None


class _StandbyPeer:
    """Server-side handle for one attached standby: an ordered outbound
    queue drained by a pump task, so log entries are emitted synchronously
    at the mutation point (no await between state change and emit) yet sent
    without blocking the dispatcher.  Queue depth is the standby's
    replication lag in ops."""

    def __init__(self, conn: "_Conn", addr: str):
        self.conn = conn
        self.addr = addr
        self.q: "asyncio.Queue" = asyncio.Queue()
        self.task: Optional[asyncio.Task] = None
        # stamped on every frame the standby sends (it pings through the
        # replication connection): a silent-but-open connection — the
        # partitioned link — must not count as a healthy standby
        self.last_contact = time.monotonic()

    async def _pump(self) -> None:
        while True:
            frame = await self.q.get()
            await self.conn.send(frame)
            if not self.conn.alive:
                return


class _Conn:
    """Server-side state for one client connection."""

    def __init__(self, server: "Coordinator", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.watches: Dict[int, _Watch] = {}
        self.subs: Dict[int, _Subscription] = {}
        self.leases: set = set()
        self.alive = True
        self._wlock = asyncio.Lock()

    async def send(self, obj: Any) -> None:
        if not self.alive:
            return
        try:
            async with self._wlock:
                await send_frame(self.writer, obj)
        except (ConnectionError, RuntimeError):
            self.alive = False


class Coordinator:
    """The control-plane server.  ``async with Coordinator(port=0) as c: ...``"""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 standby_of: Optional[str] = None,
                 promote_after_s: Optional[float] = None,
                 lease_grace_s: Optional[float] = None):
        self.host = host
        self.port = port
        env = os.environ.get
        # replication role: None = (acting) primary; "host:port" = hot
        # standby mirroring that primary's state until promotion
        self.standby_of = standby_of
        self.promote_after_s = (float(env("DYN_COORD_PROMOTE_AFTER_S",
                                          str(DEFAULT_PROMOTE_AFTER_S)))
                                if promote_after_s is None
                                else promote_after_s)
        self.lease_grace_s = (float(env("DYN_COORD_PROMOTE_LEASE_GRACE_S",
                                        str(DEFAULT_PROMOTE_LEASE_GRACE_S)))
                              if lease_grace_s is None else lease_grace_s)
        self._kv: Dict[str, _KvEntry] = {}
        self._leases: Dict[int, _Lease] = {}
        self._watches: Dict[int, _Watch] = {}
        # subscriptions indexed for O(matching) publish fan-out: exact
        # subjects in a dict, the (few) trailing-wildcard patterns in a
        # list — per-page KV events at fleet scale must not pay an
        # O(all subscriptions) scan per message (VERDICT r2 weak #6)
        self._subs_exact: Dict[str, List[_Subscription]] = {}
        self._subs_wild: List[_Subscription] = []
        self._queue_rr: Dict[Tuple[str, str], int] = {}  # (pattern, group) -> rr counter
        # work queues (JetStream-queue role; the reference's prefill queue
        # rides a NATS JetStream consumer group, rust/llm/nats.rs:109):
        # FIFO per name, pulls park until an item arrives
        self._queues: Dict[str, "deque[bytes]"] = {}
        self._queue_pulls: Dict[str, "deque[Tuple[_Conn, Any]]"] = {}
        # id counter as a plain int (not itertools.count): a standby must
        # mirror it from the snapshot/log so ids it grants post-promotion
        # never collide with replicated lease ids
        self._next_id = 1
        # boot epoch: lets a resyncing client tell "same server, state
        # intact" from "fresh/wiped server" — the id counter restarts on a
        # real process restart, so a probed lease id may EXIST yet belong
        # to another client's re-grant; epoch mismatch forces re-grants.
        # A standby MIRRORS the primary's epoch, so promotion presents as
        # a blip of the same server (probe path, no re-grant storm).
        self._epoch = random.getrandbits(63)
        # fencing term: bumped at every promotion, echoed on ping, checked
        # against the term stamped on writes by term-aware clients
        self._term = 0
        self._deposed_term: Optional[int] = None  # > _term once deposed
        self._repl_seq = 0
        self._standbys: Dict["_Conn", _StandbyPeer] = {}
        self._peer_addrs: set = set()  # standby listen addrs (for probing)
        self._primary_last_contact = 0.0
        # has this standby EVER installed a snapshot? Auto-promotion is
        # gated on it: a standby that never reached its primary (started
        # during a blip, partitioned at boot) promoting with EMPTY state
        # and a fresh epoch would split the fleet while the real primary
        # is alive. Manual promotion (admin op / SIGUSR1) stays available
        # for the operator who knows the primary is really gone.
        self._ever_attached = False
        self.failovers_total = 0  # promotions performed by this process
        self._server: Optional[asyncio.base_events.Server] = None
        self._lease_task: Optional[asyncio.Task] = None
        self._standby_task: Optional[asyncio.Task] = None
        self._peer_probe_task: Optional[asyncio.Task] = None
        self._conns: set = set()

    def _next(self) -> int:
        i = self._next_id
        self._next_id += 1
        return i

    @property
    def role(self) -> str:
        if self.standby_of is not None:
            return "standby"
        return "deposed" if self._deposed_term is not None else "primary"

    @property
    def replication_lag_ops(self) -> int:
        """Ops queued to the slowest attached standby (0 = fully caught up
        or no standby)."""
        return max((p.q.qsize() for p in self._standbys.values()), default=0)

    @property
    def standbys_attached(self) -> int:
        return len(self._standbys)

    @property
    def prefix_index_entries(self) -> int:
        """Live holder snapshots in the fleet prefix index
        (``kvstore/prefix_index/`` keys whose kv-store TTL envelope has
        not expired) — the ``dynamo_coord_prefix_index_entries`` gauge.
        The envelope is the client-side ``_CoordBucket`` format
        ({"e": expiry, "v": value, "t": ttl}); an undecodable entry
        counts as live (the reader, not this gauge, is the authority)."""
        import time as _time

        from dynamo_tpu.runtime import codec as _codec
        n = 0
        now = _time.time()
        for key, e in self._kv.items():
            if not key.startswith("kvstore/prefix_index/"):
                continue
            try:
                env = _codec.unpack(e.value)
                if env.get("e") and env["e"] <= now:
                    continue
            except Exception:  # noqa: BLE001 — count it, don't crash scrape
                pass
            n += 1
        return n

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> "Coordinator":
        self._server = await asyncio.start_server(self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        if self.standby_of is None:
            # lease expiry is a PRIMARY duty: a standby expiring replicated
            # leases on its own clock would diverge from the source of truth
            self._lease_task = asyncio.create_task(self._lease_scanner())
            if self._peer_addrs:
                # a restarted ex-primary still knows its standbys: probe
                # them so a promotion that happened while we were down
                # deposes (and demotes) us instead of splitting the brain
                self._ensure_peer_probe()
        else:
            self._primary_last_contact = time.monotonic()
            self._standby_task = asyncio.create_task(self._standby_loop())
        logger.info("coordinator listening on %s:%d (%s)",
                    self.host, self.port, self.role)
        return self

    async def stop(self) -> None:
        await reap_task(self._lease_task)
        await reap_task(self._standby_task)
        await reap_task(self._peer_probe_task)
        self._lease_task = self._standby_task = self._peer_probe_task = None
        for peer in list(self._standbys.values()):
            await reap_task(peer.task)
        self._standbys.clear()
        if self._server:
            self._server.close()
        # close live connections BEFORE wait_closed(): on py3.12 wait_closed
        # blocks until every connection handler exits, and handlers sit in
        # read() until their socket dies — the old order deadlocked when a
        # client was still attached (e.g. killing a coordinator under load)
        for conn in list(self._conns):
            conn.alive = False
            try:
                conn.writer.close()
            except Exception:
                pass
        if self._server:
            await self._server.wait_closed()

    async def __aenter__(self) -> "Coordinator":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- connection handling ----------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        conn = _Conn(self, reader, writer)
        self._conns.add(conn)
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                peer = self._standbys.get(conn)
                if peer is not None:
                    peer.last_contact = time.monotonic()
                try:
                    await self._dispatch(conn, frame)
                except Exception as e:  # protocol error -> report, keep conn
                    logger.exception("coordinator dispatch error")
                    rid = frame.get("rid") if isinstance(frame, dict) else None
                    if rid is not None:
                        await conn.send({"rid": rid, "ok": False, "error": str(e)})
        finally:
            conn.alive = False
            self._conns.discard(conn)
            self._drop_standby(conn)
            for w in list(conn.watches.values()):
                self._watches.pop(w.watch_id, None)
            self._drop_conn_subs(conn)
            for pulls in self._queue_pulls.values():
                # drop this connection's parked queue pulls
                for item in [p for p in pulls if p[0] is conn]:
                    pulls.remove(item)
            # leases owned by a dropped connection keep ticking until TTL expiry
            # (matches etcd semantics: reconnect within TTL keeps instances alive)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, conn: _Conn, f: Dict[str, Any]) -> None:
        op = f.get("op")
        rid = f.get("rid")
        if self.standby_of is not None and op not in ("ping", "promote"):
            # a standby serves nothing: clients walk their address list to
            # the primary (the hint), an attaching standby re-points too
            await conn.send({"rid": rid, "ok": False, "standby": True,
                             "term": self._term, "primary": self.standby_of,
                             "error": f"standby; primary at "
                                      f"{self.standby_of}"})
            return
        if op in _WRITE_OPS:
            ft = f.get("term")
            if self._deposed_term is not None:
                # deposed: reads still answer (stale-tolerant, like any
                # outage window) but writes bounce so no divergent state
                # accrues; the term re-points term-aware clients
                await conn.send({
                    "rid": rid, "ok": False, "fenced": True,
                    "term": self._deposed_term,
                    "error": f"deposed: a newer primary holds term "
                             f"{self._deposed_term}"})
                return
            if ft is not None and int(ft) != self._term:
                if int(ft) > self._term:
                    # the client has seen a newer primary than us: we are
                    # the deposed half of a split brain — fence ourselves
                    self._depose(int(ft))
                await conn.send({
                    "rid": rid, "ok": False, "fenced": True,
                    "term": max(int(ft), self._term),
                    "error": f"term mismatch: yours {int(ft)}, "
                             f"server {self._term}"})
                return
        if op == "put":
            await self._op_put(f["key"], f["value"], f.get("lease", 0))
            await conn.send({"rid": rid, "ok": True})
        elif op == "get":
            e = self._kv.get(f["key"])
            await conn.send({"rid": rid, "ok": True,
                             "value": e.value if e else None,
                             "lease": e.lease_id if e else 0})
        elif op == "get_prefix":
            items = [
                {"key": k, "value": e.value, "lease": e.lease_id}
                for k, e in sorted(self._kv.items()) if k.startswith(f["prefix"])
            ]
            await conn.send({"rid": rid, "ok": True, "items": items})
        elif op == "delete":
            n = await self._op_delete(f["key"])
            await conn.send({"rid": rid, "ok": True, "deleted": n})
        elif op == "delete_prefix":
            keys = [k for k in self._kv if k.startswith(f["prefix"])]
            for k in keys:
                await self._op_delete(k)
            await conn.send({"rid": rid, "ok": True, "deleted": len(keys)})
        elif op == "put_if_absent":
            if f["key"] in self._kv:
                await conn.send({"rid": rid, "ok": True, "created": False})
            else:
                await self._op_put(f["key"], f["value"], f.get("lease", 0))
                await conn.send({"rid": rid, "ok": True, "created": True})
        elif op == "grant_lease":
            lease = self._grant_lease(float(f.get("ttl", 10.0)))
            conn.leases.add(lease.lease_id)
            await conn.send({"rid": rid, "ok": True, "lease": lease.lease_id,
                             "ttl": lease.ttl})
        elif op == "keepalive":
            lease = self._leases.get(f["lease"])
            if lease is None:
                await conn.send({"rid": rid, "ok": False, "error": "lease not found"})
            else:
                lease.expires_at = time.monotonic() + lease.ttl
                self._emit("keepalive", lease.lease_id)
                await conn.send({"rid": rid, "ok": True})
        elif op == "revoke":
            await self._revoke_lease(f["lease"])
            await conn.send({"rid": rid, "ok": True})
        elif op == "watch_prefix":
            watch_id = self._next()
            w = _Watch(watch_id=watch_id, prefix=f["prefix"], conn=conn)
            self._watches[watch_id] = w
            conn.watches[watch_id] = w
            # initial snapshot rides the response so watchers never miss state
            items = [
                {"key": k, "value": e.value, "lease": e.lease_id}
                for k, e in sorted(self._kv.items()) if k.startswith(f["prefix"])
            ]
            await conn.send({"rid": rid, "ok": True, "watch_id": watch_id,
                             "items": items})
        elif op == "unwatch":
            w = conn.watches.pop(f["watch_id"], None)
            if w:
                self._watches.pop(w.watch_id, None)
            await conn.send({"rid": rid, "ok": True})
        elif op == "publish":
            n = await self._op_publish(f["subject"], f["payload"])
            await conn.send({"rid": rid, "ok": True, "delivered": n})
        elif op == "subscribe":
            sub_id = self._next()
            sub = _Subscription(sub_id=sub_id, pattern=f["subject"], conn=conn,
                                queue_group=f.get("queue_group"))
            self._add_sub(sub)
            conn.subs[sub_id] = sub
            await conn.send({"rid": rid, "ok": True, "sub_id": sub_id})
        elif op == "unsubscribe":
            sub = conn.subs.pop(f["sub_id"], None)
            if sub:
                self._remove_sub(sub)
            await conn.send({"rid": rid, "ok": True})
        elif op == "queue_push":
            depth = await self._op_queue_push(f["queue"], f["payload"])
            await conn.send({"rid": rid, "ok": True, "depth": depth})
        elif op == "queue_pull":
            await self._op_queue_pull(conn, rid, f["queue"])
        elif op == "queue_cancel":
            pulls = self._queue_pulls.get(f["queue"], deque())
            target = [(c, r) for (c, r) in pulls
                      if c is conn and r == f["pull_rid"]]
            for item in target:
                pulls.remove(item)
            await conn.send({"rid": rid, "ok": True,
                             "cancelled": bool(target)})
        elif op == "queue_depth":
            q = self._queues.get(f["queue"])
            await conn.send({"rid": rid, "ok": True,
                             "depth": len(q) if q else 0,
                             "pullers": len(self._queue_pulls.get(
                                 f["queue"], ()))})
        elif op == "ping":
            resp = {"rid": rid, "ok": True, "time": time.time(),
                    "epoch": self._epoch, "term": self._term,
                    "role": self.role}
            if self.standby_of is not None:
                resp["standby"] = True
            if self._deposed_term is not None:
                resp["deposed"] = True
                resp["deposed_by"] = self._deposed_term
            await conn.send(resp)
        elif op == "promote":
            # manual promotion (admin op; also reachable via SIGUSR1 on a
            # standalone process) — idempotent on an acting primary
            self.promote(reason=str(f.get("reason") or "admin op"))
            await conn.send({"rid": rid, "ok": True, "term": self._term,
                             "role": self.role})
        elif op == "repl_attach":
            if self._deposed_term is not None:
                await conn.send({"rid": rid, "ok": False, "fenced": True,
                                 "term": self._deposed_term,
                                 "error": "deposed; attach to the primary"})
                return
            peer = _StandbyPeer(conn, str(f.get("addr") or ""))
            # snapshot + register with NO await in between: every entry
            # emitted after this point queues behind the snapshot, so the
            # standby's log has no gap and no overlap
            peer.q.put_nowait({"rid": rid, "ok": True,
                               "snapshot": self._snapshot()})
            self._standbys[conn] = peer
            if peer.addr:
                self._peer_addrs.add(peer.addr)
            peer.task = asyncio.create_task(peer._pump())
            self._ensure_peer_probe()
            logger.info("standby %s attached (%d key(s), %d lease(s), "
                        "seq %d)", peer.addr or "<unknown>", len(self._kv),
                        len(self._leases), self._repl_seq)
        else:
            await conn.send({"rid": rid, "ok": False, "error": f"unknown op {op!r}"})

    # -- KV ----------------------------------------------------------------

    async def _op_put(self, key: str, value: bytes, lease_id: int) -> None:
        if lease_id:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise ValueError(f"lease {lease_id} not found")
            lease.keys.add(key)
        prev = self._kv.get(key)
        self._kv[key] = _KvEntry(value=value, lease_id=lease_id,
                                 version=(prev.version + 1) if prev else 1)
        self._emit("put", key, value, lease_id)
        await self._notify_watchers("put", key, value, lease_id)

    async def _op_delete(self, key: str) -> int:
        e = self._kv.pop(key, None)
        if e is None:
            return 0
        if e.lease_id and e.lease_id in self._leases:
            self._leases[e.lease_id].keys.discard(key)
        self._emit("delete", key)
        await self._notify_watchers("delete", key, None, e.lease_id)
        return 1

    async def _notify_watchers(self, etype: str, key: str,
                               value: Optional[bytes], lease_id: int) -> None:
        for w in list(self._watches.values()):
            if key.startswith(w.prefix):
                await w.conn.send({"evt": "watch", "watch_id": w.watch_id,
                                   "type": etype, "key": key, "value": value,
                                   "lease": lease_id})

    # -- leases ------------------------------------------------------------

    def _grant_lease(self, ttl: float) -> _Lease:
        lease_id = self._next()
        lease = _Lease(lease_id=lease_id, ttl=ttl,
                       expires_at=time.monotonic() + ttl)
        self._leases[lease_id] = lease
        self._emit("lease", lease_id, ttl)
        return lease

    async def _revoke_lease(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        # unlease first, then the per-key deletes replicate themselves —
        # the standby applies the same sequence
        self._emit("unlease", lease_id)
        for key in list(lease.keys):
            await self._op_delete(key)

    async def _lease_scanner(self) -> None:
        while True:
            await asyncio.sleep(LEASE_SCAN_INTERVAL)
            if self._deposed_term is not None:
                # deposed: expiry is the new primary's duty now. Keepalives
                # bounce here (fenced), so expiring on our clock would mass-
                # revoke every lease within one TTL and stream spurious
                # delete events to watchers still attached to this half.
                continue
            now = time.monotonic()
            expired = [lid for lid, l in self._leases.items() if l.expires_at < now]
            for lid in expired:
                logger.info("lease %d expired; revoking %d keys",
                            lid, len(self._leases[lid].keys))
                await self._revoke_lease(lid)

    # -- work queues -------------------------------------------------------

    async def _op_queue_push(self, queue: str, payload: bytes) -> int:
        """FIFO push; delivers straight to a parked puller when one waits.

        Delivery is at-most-once (no acks): the prefill flow tolerates a
        lost job because the decode side times out and falls back to local
        prefill. Returns the post-push depth (0 = handed to a puller).

        Each delivery carries ``age_s`` — time spent queued by THE
        COORDINATOR'S clock — so consumers can expire stale jobs without
        comparing wall clocks across hosts (clock skew immune)."""
        pulls = self._queue_pulls.get(queue)
        while pulls:
            conn, rid = pulls.popleft()
            if conn.alive:
                await conn.send({"rid": rid, "ok": True, "payload": payload,
                                 "age_s": 0.0, "depth": 0})
                return 0
        q = self._queues.setdefault(queue, deque())
        q.append((payload, time.monotonic()))
        self._emit("qpush", queue, payload)
        return len(q)

    async def _op_queue_pull(self, conn: _Conn, rid: Any, queue: str) -> None:
        """Answer with the oldest item now, or park until a push arrives.
        A parked pull on a dying connection is skipped at delivery time."""
        q = self._queues.get(queue)
        if q:
            payload, t_in = q.popleft()
            self._emit("qpop", queue)
            await conn.send({"rid": rid, "ok": True, "payload": payload,
                             "age_s": time.monotonic() - t_in,
                             "depth": len(q)})
            return
        self._queue_pulls.setdefault(queue, deque()).append((conn, rid))

    # -- pub/sub -----------------------------------------------------------

    @staticmethod
    def _is_wild(pattern: str) -> bool:
        return pattern == ">" or pattern.endswith(".>")

    def _add_sub(self, sub: _Subscription) -> None:
        if self._is_wild(sub.pattern):
            self._subs_wild.append(sub)
        else:
            self._subs_exact.setdefault(sub.pattern, []).append(sub)

    def _remove_sub(self, sub: _Subscription) -> None:
        if self._is_wild(sub.pattern):
            self._subs_wild = [s for s in self._subs_wild
                               if s.sub_id != sub.sub_id]
        else:
            lst = self._subs_exact.get(sub.pattern, [])
            lst[:] = [s for s in lst if s.sub_id != sub.sub_id]
            if not lst:
                self._subs_exact.pop(sub.pattern, None)

    def _drop_conn_subs(self, conn: _Conn) -> None:
        for sub in list(conn.subs.values()):
            self._remove_sub(sub)

    def _matching_subs(self, subject: str):
        # snapshot copies: fan-out awaits between sends, and an
        # unsubscribe/disconnect during an await mutates these lists —
        # iterating the live list would skip a subscriber
        yield from list(self._subs_exact.get(subject, ()))
        for s in list(self._subs_wild):
            if _subject_matches(s.pattern, subject):
                yield s

    async def _op_publish(self, subject: str, payload: bytes) -> int:
        delivered = 0
        # queue groups: of the members subscribed with the same (pattern, group),
        # exactly one receives each message (NATS queue semantics — the
        # reference uses this for the JetStream prefill queue).
        groups: Dict[Tuple[str, str], List[_Subscription]] = {}
        for s in self._matching_subs(subject):
            if not s.conn.alive:
                continue
            if s.queue_group:
                groups.setdefault((s.pattern, s.queue_group), []).append(s)
            else:
                await s.conn.send({"evt": "msg", "sub_id": s.sub_id,
                                   "subject": subject, "payload": payload})
                delivered += 1
        for gkey, members in groups.items():
            idx = self._queue_rr.get(gkey, 0) % len(members)
            self._queue_rr[gkey] = idx + 1
            s = members[idx]
            await s.conn.send({"evt": "msg", "sub_id": s.sub_id,
                               "subject": subject, "payload": payload})
            delivered += 1
        return delivered

    # -- replication (primary side) ----------------------------------------

    def _emit(self, *entry: Any) -> None:
        """Append one ordered log entry to every attached standby's queue.
        Called synchronously AT the mutation point — never after an await —
        so the log order is exactly the apply order."""
        if not self._standbys:
            return
        self._repl_seq += 1
        frame = {"evt": "repl", "seq": self._repl_seq, "term": self._term,
                 "nid": self._next_id, "entry": list(entry)}
        for peer in self._standbys.values():
            peer.q.put_nowait(frame)

    def _snapshot(self) -> Dict[str, Any]:
        """Full state for a freshly attached standby (sync — must be built
        atomically with registering the standby)."""
        now = time.monotonic()
        return {
            "epoch": self._epoch,
            "term": self._term,
            "next_id": self._next_id,
            "seq": self._repl_seq,
            "kv": [[k, e.value, e.lease_id, e.version]
                   for k, e in self._kv.items()],
            # deadlines travel as REMAINING ttl: monotonic clocks don't
            # compare across hosts
            "leases": [[l.lease_id, l.ttl, max(0.0, l.expires_at - now)]
                       for l in self._leases.values()],
            "queues": [[name, [[p, now - t] for (p, t) in q]]
                       for name, q in self._queues.items() if q],
        }

    def _drop_standby(self, conn: "_Conn") -> None:
        peer = self._standbys.pop(conn, None)
        if peer is not None and peer.task is not None:
            peer.task.cancel()
            # addr stays in _peer_addrs: the probe loop needs it to detect
            # (and join) a standby that promoted while detached from us

    def _ensure_peer_probe(self) -> None:
        if self._peer_probe_task is None or self._peer_probe_task.done():
            self._peer_probe_task = asyncio.create_task(
                self._peer_probe_loop())

    async def _peer_probe_loop(self) -> None:
        """Primary-side split-brain detector: ping known standby addresses
        that are NOT currently attached.  A peer answering as a primary
        with a higher term means a promotion happened behind a partition —
        this process is deposed and demotes itself into a standby of the
        winner, restoring redundancy without an operator."""
        interval = max(min((self.promote_after_s or 2.0) / 2.0, 1.0), 0.1)
        while True:
            await asyncio.sleep(interval)
            if self.standby_of is not None:
                return  # demoted: the standby loop owns liveness now
            # a standby counts as healthy only while it keeps TALKING: an
            # open-but-silent replication connection (partitioned link,
            # blackhole) is exactly the case that splits the brain
            now = time.monotonic()
            stale_after = max(self.promote_after_s or 2.0, 3 * interval)
            attached = {p.addr for p in self._standbys.values()
                        if now - p.last_contact < stale_after}
            for addr in list(self._peer_addrs):
                if addr in attached or addr == self.address:
                    continue
                resp = await self._probe_peer(addr)
                if not resp or resp.get("standby"):
                    continue
                t = int(resp.get("term", 0) or 0)
                if t > self._term or (self._deposed_term is not None
                                      and t >= self._deposed_term):
                    self._depose(t, peer_addr=addr)
                    if self.standby_of is not None:
                        return

    async def _probe_peer(self, addr: str) -> Optional[Dict[str, Any]]:
        host, _, port = addr.rpartition(":")
        writer = None
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host or "127.0.0.1", int(port)),
                timeout=0.5)
            await send_frame(writer, {"op": "ping", "rid": 1})
            return await asyncio.wait_for(read_frame(reader), timeout=0.5)
        except (OSError, asyncio.TimeoutError, ValueError):
            return None
        finally:
            if writer is not None:
                try:
                    writer.close()
                except Exception:
                    pass

    def _depose(self, new_term: int,
                peer_addr: Optional[str] = None) -> None:
        """Another coordinator holds a newer term: fence our writers; when
        the winner's address is known, rejoin as its hot standby."""
        if self.standby_of is not None:
            return
        if self._deposed_term is None or new_term > self._deposed_term:
            logger.warning(
                "coordinator %s deposed: observed term %d > ours %d%s",
                self.address, new_term, self._term,
                f" (new primary at {peer_addr})" if peer_addr else "")
            self._deposed_term = new_term
        # attached standbys must re-point to the new primary, not us
        for c in list(self._standbys):
            self._drop_standby(c)
            try:
                c.writer.close()
            except Exception:
                pass
        if peer_addr is not None:
            # demote: primary duties off, mirror the winner (the attach
            # replaces our — possibly divergent — state with its snapshot;
            # until it lands, auto-promotion must not trust this state)
            self._deposed_term = None
            self._ever_attached = False
            self.standby_of = peer_addr
            if self._lease_task is not None:
                self._lease_task.cancel()
            self._primary_last_contact = time.monotonic()
            self._standby_task = asyncio.create_task(self._standby_loop())
            logger.warning("coordinator %s demoted to standby of %s",
                           self.address, peer_addr)

    # -- replication (standby side) ----------------------------------------

    async def _standby_loop(self) -> None:
        """Attach to the primary, mirror its state, promote when it has
        been silent past ``promote_after_s`` (<=0 = manual-only)."""
        sleep_s = 0.05
        while self.standby_of is not None:
            self._maybe_promote()
            if self.standby_of is None:
                return
            phost, _, pport = self.standby_of.rpartition(":")
            writer = None
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(phost or "127.0.0.1",
                                            int(pport)),
                    timeout=max(min(self.promote_after_s or 1.0, 1.0), 0.1))
                sleep_s = 0.05
                await self._standby_attach(reader, writer)
            except asyncio.CancelledError:
                raise
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass  # primary down/unreachable: retry or promote
            except Exception:
                logger.exception("standby replication error")
            finally:
                if writer is not None:
                    try:
                        writer.close()
                    except Exception:
                        pass
            if self.standby_of is None:
                return
            await asyncio.sleep(sleep_s)
            sleep_s = min(sleep_s * 2, 0.5)

    async def _standby_attach(self, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
        # advertise an address the PRIMARY can actually dial back: bound to
        # a wildcard, self.address would be "0.0.0.0:port" — the primary's
        # peer probe would dial its own host and fencing would silently
        # never fire. The replication socket's local endpoint is our IP on
        # the route to the primary, which is exactly reachable from it.
        addr = self.address
        if self.host in ("", "0.0.0.0", "::"):
            local = writer.get_extra_info("sockname")
            if local:
                addr = f"{local[0]}:{self.port}"
        await send_frame(writer, {"op": "repl_attach", "rid": 1,
                                  "addr": addr})
        ping_interval = (max(min(self.promote_after_s / 3.0, 1.0), 0.05)
                         if self.promote_after_s and self.promote_after_s > 0
                         else 1.0)
        rids = itertools.count(2)
        last_ping = time.monotonic()
        attached = False
        while True:
            self._maybe_promote()
            if self.standby_of is None:
                return  # promoted mid-stream
            now = time.monotonic()
            if now - last_ping >= ping_interval:
                last_ping = now
                # liveness probe on the SAME connection the log rides: a
                # blackholed link (open TCP, no bytes) parks the reads and
                # the missing ping replies trip the promotion deadline
                await send_frame(writer, {"op": "ping", "rid": next(rids)})
            try:
                frame = await asyncio.wait_for(read_frame(reader),
                                               timeout=ping_interval)
            except asyncio.TimeoutError:
                continue
            if frame is None:
                raise ConnectionError("primary closed replication stream")
            self._primary_last_contact = time.monotonic()
            if frame.get("evt") == "repl":
                if attached:
                    await self._apply_repl(frame)
            elif frame.get("rid") == 1:
                if not frame.get("ok"):
                    hint = frame.get("primary")
                    if frame.get("standby") and hint:
                        # our primary demoted: follow it to the winner
                        logger.warning(
                            "replication target %s is itself a standby; "
                            "re-pointing to %s", self.standby_of, hint)
                        self.standby_of = hint
                    raise ConnectionError(
                        f"repl_attach refused: {frame.get('error')}")
                self._install_snapshot(frame["snapshot"])
                attached = True
            # other rids are ping replies: contact stamp above is enough

    def _install_snapshot(self, snap: Dict[str, Any]) -> None:
        now = time.monotonic()
        self._kv = {k: _KvEntry(value=v, lease_id=int(lid),
                                version=int(ver))
                    for k, v, lid, ver in snap.get("kv", [])}
        self._leases = {}
        for lid, ttl, remaining in snap.get("leases", []):
            self._leases[int(lid)] = _Lease(
                lease_id=int(lid), ttl=float(ttl),
                expires_at=now + float(remaining))
        for key, e in self._kv.items():
            if e.lease_id and e.lease_id in self._leases:
                self._leases[e.lease_id].keys.add(key)
        self._queues = {
            name: deque((p, now - float(age)) for p, age in items)
            for name, items in snap.get("queues", [])}
        self._epoch = int(snap["epoch"])
        self._term = int(snap.get("term", 0))
        self._next_id = int(snap.get("next_id", 1))
        self._repl_seq = int(snap.get("seq", 0))
        self._ever_attached = True
        logger.info(
            "standby installed snapshot from %s: %d key(s), %d lease(s), "
            "%d queue(s), seq %d, term %d", self.standby_of, len(self._kv),
            len(self._leases), len(self._queues), self._repl_seq, self._term)

    async def _apply_repl(self, frame: Dict[str, Any]) -> None:
        """Apply one primary log entry.  The mirrored ``nid`` keeps our id
        counter at least the primary's, so ids granted after promotion
        never collide with replicated lease ids."""
        self._term = int(frame.get("term", self._term))
        self._next_id = max(self._next_id, int(frame.get("nid", 0)))
        self._repl_seq = int(frame.get("seq", self._repl_seq))
        e = frame.get("entry") or []
        try:
            kind = e[0]
            if kind == "put":
                await self._op_put(e[1], e[2], int(e[3]))
            elif kind == "delete":
                await self._op_delete(e[1])
            elif kind == "lease":
                lid, ttl = int(e[1]), float(e[2])
                self._leases[lid] = _Lease(
                    lease_id=lid, ttl=ttl,
                    expires_at=time.monotonic() + ttl)
                self._next_id = max(self._next_id, lid + 1)
            elif kind == "keepalive":
                lease = self._leases.get(int(e[1]))
                if lease is not None:
                    lease.expires_at = time.monotonic() + lease.ttl
            elif kind == "unlease":
                # key deletes follow as their own entries
                self._leases.pop(int(e[1]), None)
            elif kind == "qpush":
                self._queues.setdefault(e[1], deque()).append(
                    (e[2], time.monotonic()))
            elif kind == "qpop":
                q = self._queues.get(e[1])
                if q:
                    q.popleft()
            else:
                logger.warning("unknown replication entry %r", kind)
        except Exception:  # noqa: BLE001 — one bad entry must not kill
            # the mirror; the next full-snapshot re-attach repairs drift
            logger.exception("failed to apply replication entry %r", e)

    def _maybe_promote(self) -> None:
        if (self.standby_of is None
                or not self.promote_after_s or self.promote_after_s <= 0
                or time.monotonic() - self._primary_last_contact
                < self.promote_after_s):
            return
        if not self._ever_attached:
            # nothing mirrored: promoting would bring up an EMPTY primary
            # with a fresh epoch next to a possibly-alive real one
            logger.warning(
                "standby %s: primary %s silent past %.1fs but no snapshot "
                "was ever installed; NOT auto-promoting (use the promote "
                "admin op / SIGUSR1 to force)", self.address,
                self.standby_of, self.promote_after_s)
            self._primary_last_contact = time.monotonic()  # re-arm, no spam
            return
        self.promote(reason=f"primary silent "
                            f">= {self.promote_after_s:.1f}s")

    def promote(self, reason: str = "manual") -> None:
        """Become the acting primary: bump the fencing term, rebase lease
        deadlines by the grace window (no mass-expiry mid-failover), start
        primary duties.  Idempotent on an acting primary."""
        if self.standby_of is None and self._deposed_term is None:
            return
        self.standby_of = None
        self._term = max(self._term, self._deposed_term or 0) + 1
        self._deposed_term = None
        self.failovers_total += 1
        # skip the id counter past anything the dead primary may have
        # issued in the replication-lag window before it died: a lease
        # granted there is unknown to us, and re-issuing its NUMBER to a
        # new client would make the victim's same-epoch resync probe adopt
        # the foreign lease (the exact hazard the boot-epoch check exists
        # to prevent — async replication re-opens it under a matching
        # epoch unless the id spaces are kept disjoint)
        self._next_id += 1000
        now = time.monotonic()
        for lease in self._leases.values():
            lease.expires_at = max(lease.expires_at,
                                   now + lease.ttl + self.lease_grace_s)
        if self._lease_task is None or self._lease_task.done():
            self._lease_task = asyncio.create_task(self._lease_scanner())
        logger.warning(
            "coordinator %s promoted to primary (%s): term %d, %d key(s), "
            "%d lease(s) rebased +%.1fs grace, %d queued job(s)",
            self.address, reason, self._term, len(self._kv),
            len(self._leases), self.lease_grace_s,
            sum(len(q) for q in self._queues.values()))


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class NotPrimaryError(ConnectionError):
    """The reached coordinator is alive but not the acting primary (a
    standby awaiting promotion, a deposed/stale primary).  The reconnect
    loop walks on with a SHORT retry cap instead of growing the outage
    backoff: the pair is up, the failover completes within the promote
    window, and waiting out a full backoff cycle would dominate the
    failover-to-ready latency."""


# retry ceiling while bouncing off a live-but-not-primary server
_NOT_PRIMARY_RETRY_CAP_S = 0.25


class WatchEvent:
    __slots__ = ("type", "key", "value", "lease_id")

    def __init__(self, type: str, key: str, value: Optional[bytes], lease_id: int = 0):
        self.type = type
        self.key = key
        self.value = value
        self.lease_id = lease_id

    def __repr__(self) -> str:
        return f"WatchEvent({self.type}, {self.key!r})"


class Watch:
    """A live prefix watch: initial snapshot + async iterator of events.

    The watch survives coordinator reconnects: ``state`` tracks the
    last-delivered view of the prefix, and on resync the client re-scans the
    prefix and diffs against it, synthesizing put/delete events so consumers
    see one consistent stream instead of EOF (see CoordClient._resync)."""

    def __init__(self, client: "CoordClient", watch_id: int,
                 snapshot: List[Dict[str, Any]], prefix: str = ""):
        self._client = client
        self.watch_id = watch_id
        self.prefix = prefix
        self.snapshot = [(i["key"], i["value"]) for i in snapshot]
        # last-known view: key -> (value, lease_id); tuples are stored by
        # identity so the resync grace pass can tell "unchanged since the
        # outage" from "re-put with the same value"
        self.state: Dict[str, Tuple[Optional[bytes], int]] = {
            i["key"]: (i["value"], i.get("lease", 0)) for i in snapshot}
        self.queue: asyncio.Queue = asyncio.Queue()
        self.cancelled = False

    def _deliver(self, ev: WatchEvent) -> None:
        if ev.type == "put":
            self.state[ev.key] = (ev.value, ev.lease_id)
        elif ev.type == "delete":
            self.state.pop(ev.key, None)
        self.queue.put_nowait(ev)

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self.queue.get()
        if ev is None:
            raise StopAsyncIteration
        return ev

    async def cancel(self) -> None:
        # flag first: a resync running concurrently must not resurrect
        # this watch when it swaps in the re-registered id map
        self.cancelled = True
        await self._client.unwatch(self.watch_id)


class Subscription:
    """A live pub/sub subscription: async iterator of (subject, payload).

    Remembers its subject/queue_group so the client can re-subscribe it
    transparently after a coordinator reconnect."""

    def __init__(self, client: "CoordClient", sub_id: int, subject: str = "",
                 queue_group: Optional[str] = None):
        self._client = client
        self.sub_id = sub_id
        self.subject = subject
        self.queue_group = queue_group
        self.queue: asyncio.Queue = asyncio.Queue()
        self.cancelled = False

    def __aiter__(self) -> "Subscription":
        return self

    async def __anext__(self) -> Tuple[str, bytes]:
        item = await self.queue.get()
        if item is None:
            raise StopAsyncIteration
        return item

    async def cancel(self) -> None:
        self.cancelled = True  # see Watch.cancel
        await self._client.unsubscribe(self.sub_id)


class Lease:
    """Client-side lease handle with automatic keep-alive task.

    Robustness contract (vs. the original fail-on-first-error loop):

    - transient keep-alive failures retry within the TTL budget instead of
      declaring the lease lost immediately;
    - a coordinator disconnect parks the loop until the supervised client
      reconnects — the resync protocol re-probes the lease and, if the
      server no longer knows it, re-grants it under a NEW id, mutating
      ``lease_id`` in place and firing ``on_relocated`` callbacks;
    - ``lost`` fires only when the lease is genuinely unrecoverable: the
      client closed (or gave up reconnecting), or keep-alives kept failing
      past a full TTL while connected.
    """

    def __init__(self, client: "CoordClient", lease_id: int, ttl: float):
        self.client = client
        self.lease_id = lease_id
        self.ttl = ttl
        self._task: Optional[asyncio.Task] = None
        self.lost = asyncio.Event()
        self._relocated_cbs: List[Callable[[int, int], None]] = []
        self._last_ok = time.monotonic()

    def on_relocated(self, cb: Callable[[int, int], None]) -> None:
        """Register ``cb(old_id, new_id)``, fired when a coordinator resync
        re-grants this lease under a fresh id."""
        self._relocated_cbs.append(cb)

    def _relocate(self, new_id: int) -> None:
        old, self.lease_id = self.lease_id, new_id
        self._last_ok = time.monotonic()
        logger.info("lease %d relocated to %d by coordinator resync",
                    old, new_id)
        for cb in list(self._relocated_cbs):
            try:
                cb(old, new_id)
            except Exception:
                logger.exception("lease relocated callback failed")

    def _mark_lost(self) -> None:
        # deregister before signalling: a later resync must not re-grant a
        # lease nobody keeps alive any more
        self.client._lease_handles.discard(self)
        self.lost.set()

    def start_keepalive(self) -> None:
        self._task = asyncio.create_task(self._keepalive_loop())

    async def _keepalive_loop(self) -> None:
        # (no CancelledError catch: see utils/aio.reap_task)
        interval = max(self.ttl / 3.0, 0.1)
        retry_sleep = max(min(interval / 4.0, 0.25), 0.02)
        self._last_ok = time.monotonic()
        while True:
            await asyncio.sleep(interval)
            while True:
                if self.client.closed.is_set():
                    self._mark_lost()
                    return
                if not self.client.connected:
                    # outage: the resync protocol re-probes / re-grants this
                    # lease as part of reconnecting, so just wait it out
                    try:
                        await self.client.wait_connected()
                    except ConnectionError:
                        logger.warning("lease %d lost: coordinator client "
                                       "closed", self.lease_id)
                        self._mark_lost()
                        return
                    self._last_ok = time.monotonic()
                    break
                try:
                    # bounded: a half-open connection (blackholed but not
                    # reset — read loop never errors) must not hang the RPC
                    # forever, or the lease silently expires server-side
                    # while this loop still believes it is healthy; a hang
                    # lands in the TTL-budget branch below like any other
                    # transient failure
                    await asyncio.wait_for(
                        self.client.keepalive(self.lease_id),
                        timeout=interval)
                    self._last_ok = time.monotonic()
                    break
                except ConnectionError:
                    # the write side can fail before the read loop marks the
                    # connection down; yield briefly so we land in the
                    # disconnected branch above instead of spinning
                    await asyncio.sleep(retry_sleep)
                    continue
                except Exception:
                    # transient server-side refusal (e.g. "lease not found"
                    # racing an in-flight relocation): retry inside the TTL
                    # budget before giving the lease up for dead
                    if time.monotonic() - self._last_ok >= self.ttl:
                        logger.warning(
                            "lease %d keep-alive failed past its %.1fs TTL "
                            "budget; lost", self.lease_id, self.ttl)
                        self._mark_lost()
                        return
                    await asyncio.sleep(retry_sleep)

    async def revoke(self) -> None:
        if self._task:
            self._task.cancel()
        # deregister first so a concurrent resync can't resurrect it
        self.client._lease_handles.discard(self)
        try:
            await self.client.revoke(self.lease_id)
        except Exception:
            pass


def replay_registry(client: Any, attr: str, factory: Callable[[], Any],
                    replay: Callable[[Any], Awaitable[None]]) -> Any:
    """Owner-replay registry cached on ``client`` under ``attr``, with ONE
    resync hook replaying its contents after every reconnect.

    Handles are constructed per call-site (kv buckets, model registrations),
    so a hook per handle would accumulate on the client forever and replay
    superseded state; a shared registry gives replace-not-accumulate
    semantics. Only the first caller's ``replay`` is attached; ``client``
    may be any duck-typed store — no hook on ones without resync support."""
    reg = getattr(client, attr, None)
    if reg is None:
        reg = factory()
        setattr(client, attr, reg)
        if hasattr(client, "add_resync_hook"):
            async def _replay_hook() -> None:
                await replay(reg)

            client.add_resync_hook(_replay_hook)
    return reg


class CoordClient:
    """Async client for the Coordinator, with a supervised connection.

    A coordinator crash/restart is transparent to consumers (parity with how
    the reference's etcd/NATS clients survive server restarts):

    - on disconnect, in-flight calls fail fast with ``ConnectionError`` but
      watches, subscriptions and leases are KEPT; a background task retries
      the connection with decorrelated-jitter backoff;
    - on reconnect, a **resync protocol** runs: live leases are probed and
      re-granted (new ids) where the server lost them, registered *resync
      hooks* replay owner state (instance registrations, model cards,
      barrier check-ins), every watch re-scans its prefix and diffs against
      its last-known state to synthesize put/delete deltas, and event
      subscriptions are re-established;
    - ``closed`` now means *permanently* closed: ``close()`` was called, or
      the reconnect give-up window (``DYN_COORD_RECONNECT_MAX_S``) elapsed.

    Knobs (env, or constructor overrides): ``DYN_COORD_RECONNECT`` (0
    disables supervision and restores fail-on-first-disconnect),
    ``DYN_COORD_RECONNECT_BASE_S`` / ``_CAP_S`` (backoff),
    ``DYN_COORD_RECONNECT_MAX_S`` (0 = retry forever) and
    ``DYN_COORD_RESYNC_GRACE_S`` (stale-read window before a key missing
    from the post-restart scan is reported deleted).
    """

    def __init__(self, address: str, reconnect: Optional[bool] = None,
                 reconnect_base_s: Optional[float] = None,
                 reconnect_cap_s: Optional[float] = None,
                 reconnect_max_s: Optional[float] = None,
                 resync_grace_s: Optional[float] = None,
                 resync_timeout_s: Optional[float] = None):
        # comma-separated multi-address: "host:6650,host:6651" names a
        # replicated pair; connect and the reconnect loop walk the list,
        # skipping standbys, so failover needs no reconfiguration. A list
        # of one is exactly the single-coordinator behavior.
        self.addresses: List[Tuple[str, int]] = []
        for part in address.split(","):
            part = part.strip()
            if not part:
                continue
            host, _, port = part.rpartition(":")
            self.addresses.append((host or "127.0.0.1", int(port)))
        if not self.addresses:
            raise ValueError(f"no coordinator address in {address!r}")
        self._addr_idx = 0
        self.host, self.port = self.addresses[0]
        env = os.environ.get
        self.reconnect = (env("DYN_COORD_RECONNECT", "1").lower()
                          not in ("0", "false", "no")
                          if reconnect is None else reconnect)
        self.reconnect_base_s = (float(env("DYN_COORD_RECONNECT_BASE_S",
                                           "0.05"))
                                 if reconnect_base_s is None
                                 else reconnect_base_s)
        self.reconnect_cap_s = (float(env("DYN_COORD_RECONNECT_CAP_S", "2.0"))
                                if reconnect_cap_s is None else reconnect_cap_s)
        self.reconnect_max_s = (float(env("DYN_COORD_RECONNECT_MAX_S", "0"))
                                if reconnect_max_s is None else reconnect_max_s)
        self.resync_grace_s = (float(env("DYN_COORD_RESYNC_GRACE_S", "5.0"))
                               if resync_grace_s is None else resync_grace_s)
        self.resync_timeout_s = (float(env("DYN_COORD_RESYNC_TIMEOUT_S",
                                           "30.0"))
                                 if resync_timeout_s is None
                                 else resync_timeout_s)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._rids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watches: Dict[int, Watch] = {}
        self._subs: Dict[int, Subscription] = {}
        self._lease_handles: set = set()
        self._resync_hooks: List[Callable] = []
        # events/messages that raced ahead of watch/subscription registration
        # (the server's response and a first event can share one TCP segment)
        self._orphan_events: Dict[int, list] = {}
        self._orphan_msgs: Dict[int, list] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._reconnect_task: Optional[asyncio.Task] = None
        self._resync_task: Optional[asyncio.Task] = None
        # what the last resync attempt re-registered on the live connection;
        # re-swept for late cancels once connected. Objects, not ids: a
        # wiped server's restarted id counter reuses NUMBERS, so an id alone
        # cannot say whose registration it names
        self._resync_watch_objs: List[Watch] = []
        self._resync_sub_objs: List[Subscription] = []
        self._deferred: set = set()  # grace-delayed delete tasks
        self._wlock: Optional[asyncio.Lock] = None
        self._connected = asyncio.Event()
        self._closing = False
        self._disconnected_at: Optional[float] = None
        self._server_epoch: Optional[int] = None
        # highest fencing term seen (ping echo / fenced bounce); stamped on
        # writes. None until a term-aware server is seen — so against a
        # pre-replication server nothing is stamped (fencing disabled)
        self._term: Optional[int] = None
        self._conn_lost_flag = False  # current connection died (see below)
        self.closed = asyncio.Event()
        # observability (exported via http/metrics.CoordClientMetrics)
        self.reconnects_total = 0
        self.resyncs_total = 0
        self.last_outage_s = 0.0

    # -- connection supervision --------------------------------------------

    @property
    def connected(self) -> bool:
        return self._connected.is_set() and not self.closed.is_set()

    async def wait_connected(self, timeout: Optional[float] = None) -> None:
        """Block until the connection is up and resynced; raises
        ``ConnectionError`` when the client is permanently closed (or on
        ``timeout``)."""
        if self.closed.is_set():
            raise ConnectionError("coordinator client closed")
        if self._connected.is_set():
            return
        conn = asyncio.ensure_future(self._connected.wait())
        clo = asyncio.ensure_future(self.closed.wait())
        try:
            done, _ = await asyncio.wait({conn, clo}, timeout=timeout,
                                         return_when=asyncio.FIRST_COMPLETED)
            if conn in done:
                return
            if clo in done:
                raise ConnectionError("coordinator client closed")
            raise ConnectionError(
                "timed out waiting for coordinator reconnect")
        finally:
            conn.cancel()
            clo.cancel()

    def add_resync_hook(self, hook: Callable) -> Callable:
        """Register an async callable invoked after leases are re-established
        on every reconnect, BEFORE watches are re-scanned — re-put owner
        state (instance registrations, model cards, barrier keys) here so
        the resync diff already sees it. Returns ``hook`` for symmetry with
        ``remove_resync_hook``."""
        self._resync_hooks.append(hook)
        return hook

    def remove_resync_hook(self, hook: Callable) -> None:
        try:
            self._resync_hooks.remove(hook)
        except ValueError:
            pass

    async def connect(self) -> "CoordClient":
        last: Optional[BaseException] = None
        for _ in range(len(self.addresses)):
            self.host, self.port = self.addresses[self._addr_idx]
            try:
                await self._connect_one()
                return self
            except asyncio.CancelledError:
                await self.close()
                raise
            except BaseException as e:
                # this address failed (dead, standby, deposed): tear the
                # attempt down WITHOUT closing the client and walk on
                last = e
                await self._abort_conn_attempt()
                self._addr_idx = (self._addr_idx + 1) % len(self.addresses)
        # a half-opened connection (server died mid-handshake) must not
        # leave a background reconnect loop running on an object the
        # caller is about to abandon — connect() either works or is void
        await self.close()
        raise last if last is not None else ConnectionError(
            "no coordinator reachable")

    async def _connect_one(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._wlock = asyncio.Lock()
        self._connected.set()
        self._reader_task = asyncio.create_task(self._read_loop(self._reader))
        # baseline boot epoch: resync compares against it to tell a blipped
        # server (state intact, probe leases) from a fresh one (re-grant).
        # bounded like resync: a server that accepts TCP but never
        # answers must not hang startup forever
        resp = await asyncio.wait_for(self._call("ping"),
                                      timeout=self.resync_timeout_s or None)
        if resp.get("standby"):
            raise NotPrimaryError(
                f"{self.host}:{self.port} is a standby coordinator")
        if resp.get("deposed"):
            raise NotPrimaryError(
                f"{self.host}:{self.port} is a deposed coordinator")
        self._server_epoch = resp.get("epoch")
        term = resp.get("term")
        self._term = int(term) if term is not None else None

    async def _abort_conn_attempt(self) -> None:
        """Undo one failed connect() attempt: kill the socket and reader
        task without flipping ``closed`` (the walk continues)."""
        self._connected.clear()
        # null the reader FIRST (sync): any read loop dying from here on
        # sees a superseded connection in _on_conn_lost and cannot start
        # reconnect supervision behind the walk's back
        task, self._reader_task = self._reader_task, None
        self._reader = None
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
        # a read loop that died DURING the attempt (server hung up) may
        # already have started supervision — and that loop may have opened
        # a fresh connection meanwhile: reap it, then sweep again
        if self._reconnect_task is not None:
            reconnect, self._reconnect_task = self._reconnect_task, None
            await reap_task(reconnect)
        if task is not None:
            await reap_task(task)
        # anything the reaped supervision installed before dying
        extra, self._reader_task = self._reader_task, None
        self._reader = None
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
        if extra is not None:
            await reap_task(extra)

    async def close(self) -> None:
        self._closing = True
        if self._reconnect_task is not None:
            await reap_task(self._reconnect_task)
            self._reconnect_task = None
        await reap_task(self._reader_task)
        if self._writer:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass
        self._finalize_closed()

    async def __aenter__(self) -> "CoordClient":
        return await self.connect()

    async def __aexit__(self, *exc) -> None:
        await self.close()

    def _dispatch_frame(self, frame: Dict[str, Any]) -> None:
        if "rid" in frame and frame["rid"] is not None:
            fut = self._pending.pop(frame["rid"], None)
            if fut and not fut.done():
                fut.set_result(frame)
        elif frame.get("evt") == "watch":
            ev = WatchEvent(frame["type"], frame["key"],
                            frame.get("value"), frame.get("lease", 0))
            w = self._watches.get(frame["watch_id"])
            if w:
                w._deliver(ev)
            else:
                buf = self._orphan_events.setdefault(frame["watch_id"], [])
                if len(buf) < 10_000:
                    buf.append(ev)
        elif frame.get("evt") == "msg":
            item = (frame["subject"], frame["payload"])
            s = self._subs.get(frame["sub_id"])
            if s:
                s.queue.put_nowait(item)
            else:
                buf = self._orphan_msgs.setdefault(frame["sub_id"], [])
                if len(buf) < 10_000:
                    buf.append(item)

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                self._dispatch_frame(frame)
        except (ConnectionError, OSError):
            pass  # CancelledError must propagate (see utils/aio.reap_task)
        finally:
            self._on_conn_lost(reader)

    def _on_conn_lost(self, reader: asyncio.StreamReader) -> None:
        if reader is not self._reader:
            return  # a stale loop from a superseded connection
        # a still-running reconnect task (below we early-return rather than
        # double-supervise) must not declare success on this dead
        # connection: it re-checks this flag after its resync completes
        self._conn_lost_flag = True
        self._connected.clear()
        # in-flight calls fail fast (callers retry or surface the outage);
        # orphan buffers are connection-scoped — clear them so events from a
        # dead watch registration can't accumulate forever (nor leak into a
        # reconnected session whose server assigns fresh ids)
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(
                    ConnectionError("coordinator connection lost"))
        self._pending.clear()
        self._orphan_events.clear()
        self._orphan_msgs.clear()
        # registrations from the dead connection need no undo
        self._resync_watch_objs = []
        self._resync_sub_objs = []
        # a pending grace-window delete must not fire while offline: no
        # events arrive to refute it (the owner may have re-put the key on
        # the server, invisibly to us), and the next resync re-diffs anyway
        for t in list(self._deferred):
            t.cancel()
        if self._closing or not self.reconnect:
            self._finalize_closed()
            return
        if self._reconnect_task is not None and not self._reconnect_task.done():
            return  # supervision already running; it retries on its own
        self._disconnected_at = time.monotonic()
        logger.warning("coordinator connection %s:%d lost; reconnecting",
                       self.host, self.port)
        self._reconnect_task = asyncio.create_task(self._reconnect_loop())

    def _finalize_closed(self) -> None:
        """Permanent teardown: fail everything and end every iterator."""
        if self.closed.is_set():
            return
        self.closed.set()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(
                    ConnectionError("coordinator connection lost"))
        self._pending.clear()
        for t in list(self._deferred):
            t.cancel()
        for w in self._watches.values():
            w.queue.put_nowait(None)
        for s in self._subs.values():
            s.queue.put_nowait(None)

    async def _reconnect_loop(self) -> None:
        sleep_s = self.reconnect_base_s

        def backoff() -> float:
            # a fleet of clients must not stampede the restarted
            # coordinator in lockstep (same helper as push_router failover)
            return decorrelated_jitter(sleep_s, self.reconnect_base_s,
                                       self.reconnect_cap_s)

        while True:
            if self._closing:
                return
            down_for = time.monotonic() - (self._disconnected_at
                                           or time.monotonic())
            if self.reconnect_max_s and down_for > self.reconnect_max_s:
                logger.error(
                    "giving up on coordinator %s:%d after %.1fs offline",
                    self.host, self.port, down_for)
                self._finalize_closed()
                return
            # walk the address list: each failed attempt advances to the
            # next candidate (a single address degenerates to retry-same)
            self.host, self.port = self.addresses[self._addr_idx]
            try:
                # bounded attempt: a blackholed address must not park the
                # loop for the kernel connect timeout (minutes) — backoff
                # pacing and the give-up window only advance between tries
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(self.host, self.port),
                    timeout=max(self.reconnect_cap_s, 1.0))
            except (OSError, asyncio.TimeoutError):
                self._addr_idx = (self._addr_idx + 1) % len(self.addresses)
                sleep_s = backoff()
                await asyncio.sleep(sleep_s)
                continue
            old_writer, self._reader, self._writer = \
                self._writer, reader, writer
            self._conn_lost_flag = False  # tracking the NEW connection now
            if old_writer is not None:
                try:
                    old_writer.close()
                except Exception:
                    pass
            self._reader_task = asyncio.create_task(self._read_loop(reader))
            try:
                # bounded: a server that accepts the connection but never
                # answers (frozen / blackholed half-open) must not park
                # supervision forever — the give-up window is only checked
                # between attempts. wait_for runs _resync in its OWN task,
                # so _call's disconnected-fail-fast exemption tracks it.
                # attempts, not completions: divergence from
                # reconnects_total below is the retried-resync signal
                self.resyncs_total += 1
                self._resync_task = asyncio.ensure_future(self._resync())
                try:
                    await asyncio.wait_for(self._resync_task,
                                           timeout=self.resync_timeout_s
                                           or None)
                finally:
                    self._resync_task = None
                if self._conn_lost_flag:
                    # the connection died during resync, after answering the
                    # last call — the read loop's _on_conn_lost deferred to
                    # this (still-running) task, so the retry is on us:
                    # declaring success would wedge the client forever
                    raise ConnectionError("connection lost during resync")
            except NotPrimaryError as e:
                # a live server that just isn't the primary (yet): walk on
                # with a short retry cap — promotion completes within the
                # promote window and a full outage backoff would dominate
                # the failover-to-ready latency
                logger.info("coordinator resync walked on (%s)", e)
                try:
                    writer.close()
                except Exception:
                    pass
                self._addr_idx = (self._addr_idx + 1) % len(self.addresses)
                sleep_s = min(backoff(), _NOT_PRIMARY_RETRY_CAP_S)
                await asyncio.sleep(sleep_s)
                continue
            except Exception as e:  # noqa: BLE001 — any resync failure
                # (connection died again, server error, landed on a
                # standby/deposed/stale primary) restarts supervision on
                # the next address
                logger.warning("coordinator resync failed (%s); retrying", e)
                try:
                    writer.close()
                except Exception:
                    pass
                self._addr_idx = (self._addr_idx + 1) % len(self.addresses)
                sleep_s = backoff()
                await asyncio.sleep(sleep_s)
                continue
            outage = time.monotonic() - (self._disconnected_at
                                         or time.monotonic())
            self._disconnected_at = None
            self.reconnects_total += 1
            self.last_outage_s = outage
            self._connected.set()
            # re-sweep for cancels that raced the resync after its own
            # cancelled-sweep passed: their unwatch RPC failed fast while
            # the resync held the connection, but the registration is LIVE.
            # Each object's current id was issued to IT on this connection,
            # so this can never hit a sibling (unlike sweeping raw ids —
            # a wiped server's restarted counter reuses numbers); an id the
            # resync's own sweep already dropped errors harmlessly
            for w in self._resync_watch_objs:
                if w.cancelled:
                    self._orphan_events.pop(w.watch_id, None)
                    try:
                        await self._call("unwatch", watch_id=w.watch_id)
                    except Exception:
                        pass
            self._resync_watch_objs = []
            for s in self._resync_sub_objs:
                if s.cancelled:
                    self._orphan_msgs.pop(s.sub_id, None)
                    try:
                        await self._call("unsubscribe", sub_id=s.sub_id)
                    except Exception:
                        pass
            self._resync_sub_objs = []
            logger.info(
                "coordinator %s:%d reconnected after %.2fs outage; resynced "
                "%d lease(s), %d watch(es), %d subscription(s), %d hook(s)",
                self.host, self.port, outage, len(self._lease_handles),
                len(self._watches), len(self._subs),
                len(self._resync_hooks))
            return

    async def _resync(self) -> None:
        """Rebuild server-side session state on a fresh connection.

        Order matters: leases first (hooks attach keys to them), then the
        resync hooks (owners re-put their state so the watch re-scan below
        already includes it), then watches (prefix re-scan + diff against
        each watch's last-known state), then subscriptions."""
        # 0. boot epoch: a changed epoch means a fresh/wiped process whose
        # restarted id counter may have RE-ISSUED our old lease ids to other
        # clients — an existence probe would then adopt a foreign lease
        # (and die with it when its real owner revokes). Same epoch means
        # the server's state survived and probing is trustworthy. A hot
        # standby MIRRORS its primary's epoch, so a failover lands here as
        # the cheap probe path: every replicated lease keeps its id.
        ping = await self._call("ping")
        if ping.get("standby"):
            raise NotPrimaryError("reached a standby coordinator; "
                                  "walking the address list")
        if ping.get("deposed"):
            self._term = max(self._term or 0,
                             int(ping.get("deposed_by", 0) or 0))
            raise NotPrimaryError("reached a deposed coordinator; "
                                  "walking the address list")
        epoch = ping.get("epoch")
        fresh_server = epoch != self._server_epoch
        term = ping.get("term")
        if term is None:
            self._term = None  # pre-replication server: fencing disabled
        elif fresh_server:
            self._term = int(term)  # new lineage, new term sequence
        elif self._term is not None and int(term) < self._term:
            # same lineage but an OLDER term than we've already seen: this
            # is the deposed half of a split brain that hasn't noticed yet
            raise NotPrimaryError(
                f"stale primary: term {int(term)} < {self._term} seen; "
                "walking the address list")
        else:
            self._term = int(term)
        # 1. leases: probe-or-regrant. A lease that survived the outage
        # (connection blip, or restart without state wipe within TTL) keeps
        # its id — zero churn; one the server lost is re-granted under a
        # fresh id and the handle relocates in place.
        for lease in list(self._lease_handles):
            if not fresh_server:
                try:
                    await self._call("keepalive", lease=lease.lease_id)
                    continue
                except ConnectionError:
                    raise
                except Exception:
                    pass  # lease not found -> re-grant below
            resp = await self._call("grant_lease", ttl=lease.ttl)
            lease._relocate(int(resp["lease"]))
        # only now: a retry after a partial lease pass must still see the
        # epoch as fresh and re-grant the remainder
        self._server_epoch = epoch
        # 2. resync hooks: replay owner state under the fresh leases
        for hook in list(self._resync_hooks):
            try:
                await hook()
            except (ConnectionError, OSError):
                raise
            except Exception:
                logger.exception("coordinator resync hook failed")
        # 3. watches: re-register, then diff the fresh snapshot against the
        # watcher's last-known state, synthesizing deltas. A state-wiped
        # server restarts its id counter, so a fresh id routinely collides
        # with a sibling's OLD id: detach the registry up front (events that
        # arrive mid-registration park in the orphan buffer instead of
        # hitting a stale same-id entry) and swap the new map in whole.
        watches = [w for w in self._watches.values() if not w.cancelled]
        self._watches = {}
        scans = []
        try:
            for w in watches:
                resp = await self._call("watch_prefix", prefix=w.prefix)
                w.watch_id = int(resp["watch_id"])
                scans.append(resp.get("items", []))
        except BaseException:
            # keep the watch set for the retry; ids from the failed attempt
            # are dead and may collide, so key uniquely (the next attempt
            # iterates values() and re-registers by prefix)
            self._watches = {-i: w for i, w in enumerate(watches, 1)}
            raise
        self._watches = {w.watch_id: w for w in watches if not w.cancelled}
        self._resync_watch_objs = watches  # for the post-connect re-sweep
        for w, items in zip(watches, scans):
            if w.cancelled:
                continue
            # diff first, then the live events that raced the registration
            # (the server's response and a first event can share one TCP
            # segment — same race watch_prefix() drains after registering)
            self._resync_watch(w, items)
            for ev in self._orphan_events.pop(w.watch_id, []):
                w._deliver(ev)
        for w in watches:
            if w.cancelled:
                # cancelled while this resync was re-registering it: the
                # cancel's own unwatch went to the dead connection, so undo
                # the fresh registration or the server streams the prefix
                # into a dropped id forever
                await self._call("unwatch", watch_id=w.watch_id)
                self._orphan_events.pop(w.watch_id, None)
        # 4. subscriptions: re-subscribe under fresh server-side ids (same
        # detach/swap/drain dance as watches)
        subs = [s for s in self._subs.values() if not s.cancelled]
        self._subs = {}
        try:
            for s in subs:
                resp = await self._call("subscribe", subject=s.subject,
                                        queue_group=s.queue_group)
                s.sub_id = int(resp["sub_id"])
        except BaseException:
            self._subs = {-i: s for i, s in enumerate(subs, 1)}
            raise
        self._subs = {s.sub_id: s for s in subs if not s.cancelled}
        self._resync_sub_objs = subs  # for the post-connect re-sweep
        # drain BEFORE the sweep's awaits: once the swap is live, new
        # messages go straight to the queues, and a message orphaned during
        # re-registration must not be delivered after one that arrived later
        for s in self._subs.values():
            for item in self._orphan_msgs.pop(s.sub_id, []):
                s.queue.put_nowait(item)
        for s in subs:
            if s.cancelled:  # see the watch sweep above
                await self._call("unsubscribe", sub_id=s.sub_id)
                self._orphan_msgs.pop(s.sub_id, None)

    def _resync_watch(self, w: Watch, items: List[Dict[str, Any]]) -> None:
        new = {i["key"]: (i["value"], i.get("lease", 0)) for i in items}
        old = dict(w.state)
        for key in sorted(new):
            value, lease_id = new[key]
            prev = old.get(key)
            if prev is None or prev[0] != value or prev[1] != lease_id:
                w._deliver(WatchEvent("put", key, value, lease_id))
        missing = {k: old[k] for k in old if k not in new}
        if not missing:
            return
        if self.resync_grace_s <= 0:
            for key, (_value, lease_id) in sorted(missing.items()):
                w._deliver(WatchEvent("delete", key, None, lease_id))
            return
        # stale-read window: a key absent right after a restart is usually a
        # peer that simply hasn't resynced yet (its re-put is racing ours) —
        # report the delete only if it stays gone past the grace window, so
        # consumers (instance discovery, model cards) never flap through
        # empty during a restart
        task = asyncio.create_task(self._deferred_deletes(w, missing))
        self._deferred.add(task)
        task.add_done_callback(self._deferred.discard)

    async def _deferred_deletes(
            self, w: Watch,
            missing: Dict[str, Tuple[Optional[bytes], int]]) -> None:
        await asyncio.sleep(self.resync_grace_s)
        for key, stamp in sorted(missing.items()):
            # identity check: a re-put (even of an equal value) stored a new
            # tuple; only untouched-since-the-outage keys get the delete
            if w.state.get(key) is stamp and self._watches.get(w.watch_id) is w:
                w._deliver(WatchEvent("delete", key, None, stamp[1]))

    async def _call(self, op: str, **kw: Any) -> Dict[str, Any]:
        if self._writer is None:
            raise ConnectionError("not connected")
        if self.closed.is_set():
            raise ConnectionError("coordinator connection lost")
        if (not self._connected.is_set()
                and asyncio.current_task() is not self._resync_task):
            # disconnected: fail fast so callers keep serving from cached
            # state instead of hanging on a dead socket (the resync task
            # itself is exempt — it runs before connected is set)
            raise ConnectionError("coordinator disconnected "
                                  "(reconnect in progress)")
        rid = next(self._rids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        frame = {"op": op, "rid": rid, **kw}
        if self._term is not None and op in _WRITE_OPS:
            frame["term"] = self._term  # fencing stamp (see module doc)
        async with self._wlock:
            await send_frame(self._writer, frame)
        # A dead connection may accept the write (TCP buffering) while the
        # read loop has already torn down — or tears down after we register
        # the future but before the reply. Racing against `closed` turns
        # every such case into a prompt ConnectionError instead of a hang.
        closed_wait = asyncio.ensure_future(self.closed.wait())
        try:
            done, _ = await asyncio.wait({fut, closed_wait},
                                         return_when=asyncio.FIRST_COMPLETED)
            if fut not in done:
                self._pending.pop(rid, None)
                raise ConnectionError("coordinator connection lost")
            resp = fut.result()
        finally:
            closed_wait.cancel()
            if not fut.done():
                # cancelled from outside (e.g. a wait_for-bounded caller):
                # drop the entry or a half-open connection accrues one per
                # attempt; the read loop tolerates replies to unknown rids
                self._pending.pop(rid, None)
        if not resp.get("ok"):
            self._raise_rejection(op, resp)
        return resp

    def _raise_rejection(self, op: str, resp: Dict[str, Any]) -> None:
        """Turn a not-ok response into the right exception.  A fenced or
        standby bounce means this server is no longer the primary: adopt
        the newer term, drop the connection so supervision walks the
        address list, and surface a ConnectionError (callers already treat
        those as a survivable outage)."""
        if resp.get("fenced") or resp.get("standby"):
            t = resp.get("term")
            if t is not None:
                self._term = max(self._term or 0, int(t))
            kind = "fenced" if resp.get("fenced") else "standby"
            logger.warning(
                "coordinator %s:%d bounced %s (%s, term %s); re-pointing",
                self.host, self.port, op, kind, t)
            if self._writer is not None:
                try:
                    self._writer.close()  # read loop EOF -> reconnect walk
                except Exception:
                    pass
            raise ConnectionError(
                f"coordinator re-pointed ({kind}): {resp.get('error')}")
        raise RuntimeError(f"coordinator {op} failed: {resp.get('error')}")

    # -- KV API ------------------------------------------------------------

    async def put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        await self._call("put", key=key, value=value, lease=lease_id)

    async def put_if_absent(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        resp = await self._call("put_if_absent", key=key, value=value, lease=lease_id)
        return bool(resp["created"])

    async def get(self, key: str) -> Optional[bytes]:
        resp = await self._call("get", key=key)
        return resp.get("value")

    async def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        resp = await self._call("get_prefix", prefix=prefix)
        return [(i["key"], i["value"]) for i in resp["items"]]

    async def delete(self, key: str) -> int:
        return (await self._call("delete", key=key))["deleted"]

    async def delete_prefix(self, prefix: str) -> int:
        return (await self._call("delete_prefix", prefix=prefix))["deleted"]

    # -- leases ------------------------------------------------------------

    async def grant_lease(self, ttl: float = 10.0, keepalive: bool = True) -> Lease:
        resp = await self._call("grant_lease", ttl=ttl)
        lease = Lease(self, resp["lease"], resp["ttl"])
        self._lease_handles.add(lease)  # resync re-probes / re-grants it
        if keepalive:
            lease.start_keepalive()
        return lease

    async def keepalive(self, lease_id: int) -> None:
        await self._call("keepalive", lease=lease_id)

    async def revoke(self, lease_id: int) -> None:
        await self._call("revoke", lease=lease_id)

    # -- watches -----------------------------------------------------------

    async def watch_prefix(self, prefix: str) -> Watch:
        resp = await self._call("watch_prefix", prefix=prefix)
        w = Watch(self, resp["watch_id"], resp.get("items", []), prefix=prefix)
        self._watches[w.watch_id] = w
        # drain events that arrived between the server registering the watch
        # and us registering the Watch object (no await between these lines)
        for ev in self._orphan_events.pop(w.watch_id, []):
            w._deliver(ev)
        return w

    async def unwatch(self, watch_id: int) -> None:
        self._watches.pop(watch_id, None)
        self._orphan_events.pop(watch_id, None)  # drop in-flight stragglers
        try:
            await self._call("unwatch", watch_id=watch_id)
        except ConnectionError:
            pass  # disconnected: the dead server session is gone anyway,
            # and the resync protocol won't re-establish a popped watch; a
            # cancel racing a mid-flight resync (which may already hold a
            # LIVE registration for this watch) is undone by the
            # post-connect re-sweep of _resync_watch_objs

    # -- pub/sub -----------------------------------------------------------

    async def publish(self, subject: str, payload: bytes) -> int:
        return (await self._call("publish", subject=subject, payload=payload))["delivered"]

    async def subscribe(self, subject: str,
                        queue_group: Optional[str] = None) -> Subscription:
        resp = await self._call("subscribe", subject=subject, queue_group=queue_group)
        s = Subscription(self, resp["sub_id"], subject=subject,
                         queue_group=queue_group)
        self._subs[s.sub_id] = s
        for item in self._orphan_msgs.pop(s.sub_id, []):
            s.queue.put_nowait(item)
        return s

    async def unsubscribe(self, sub_id: int) -> None:
        self._subs.pop(sub_id, None)
        self._orphan_msgs.pop(sub_id, None)
        try:
            await self._call("unsubscribe", sub_id=sub_id)
        except ConnectionError:
            pass  # popped subs are not resynced; see unwatch for the
            # mid-resync race the post-connect re-sweep covers

    # -- object store ------------------------------------------------------
    # (reference: NATS object store carrying model-card artifacts,
    # ``transports/nats.rs:123-176``.) Implemented client-side on the KV
    # plane: ``obj/{bucket}/{name}/meta`` + ``/c{i}`` chunk keys. Chunking
    # keeps any single KV value (and coordinator frame) small even for
    # multi-MB artifacts like inlined tokenizers.

    OBJ_CHUNK = 1 << 20  # 1 MiB per chunk

    @staticmethod
    def _obj_prefix(bucket: str, name: str) -> str:
        return f"obj/{bucket}/{name}/"

    async def obj_put(self, bucket: str, name: str, data: bytes,
                      lease_id: int = 0) -> int:
        """Store an object as chunked KV entries; returns chunk count.
        Attach a lease to make the object vanish with its owner."""
        prefix = self._obj_prefix(bucket, name)
        n = max(1, -(-len(data) // self.OBJ_CHUNK))
        for i in range(n):
            chunk = data[i * self.OBJ_CHUNK:(i + 1) * self.OBJ_CHUNK]
            await self.put(f"{prefix}c{i:06d}", chunk, lease_id=lease_id)
        import json as _json
        await self.put(f"{prefix}meta",
                       _json.dumps({"size": len(data),
                                    "chunks": n}).encode(),
                       lease_id=lease_id)
        return n

    async def obj_get(self, bucket: str, name: str) -> Optional[bytes]:
        """Fetch a stored object, or None when absent/incomplete."""
        prefix = self._obj_prefix(bucket, name)
        meta_raw = await self.get(f"{prefix}meta")
        if meta_raw is None:
            return None
        import json as _json
        meta = _json.loads(meta_raw)
        parts = []
        for i in range(int(meta["chunks"])):
            c = await self.get(f"{prefix}c{i:06d}")
            if c is None:
                return None  # torn write/expiry mid-read
            parts.append(c)
        data = b"".join(parts)
        return data if len(data) == int(meta["size"]) else None

    async def obj_delete(self, bucket: str, name: str) -> int:
        return await self.delete_prefix(self._obj_prefix(bucket, name))

    # -- work queues -------------------------------------------------------

    async def queue_push(self, queue: str, payload: bytes) -> int:
        """Push one job; returns post-push depth (0 = a puller took it)."""
        return (await self._call("queue_push", queue=queue,
                                 payload=payload))["depth"]

    async def queue_pull(self, queue: str,
                         timeout: Optional[float] = None
                         ) -> Optional[Tuple[bytes, float]]:
        """Pull the oldest job, parking server-side until one arrives;
        returns (payload, age_s) — ``age_s`` is time spent queued by the
        coordinator's clock — or None on timeout.

        Timeout protocol: the parked pull is explicitly cancelled
        (``queue_cancel``). If the cancel races a delivery already in
        flight, the client waits for it and pushes the job BACK, so a
        timed-out puller can never swallow a job. External CANCELLATION of
        this coroutine fires the same best-effort server-side cancel so a
        parked pull on a still-live connection cannot swallow a later push
        into an orphaned future."""
        if self._writer is None:
            raise ConnectionError("not connected")
        if self.closed.is_set() or not self._connected.is_set():
            raise ConnectionError("coordinator connection lost")
        rid = next(self._rids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        frame = {"op": "queue_pull", "rid": rid, "queue": queue}
        if self._term is not None:
            frame["term"] = self._term
        async with self._wlock:
            await send_frame(self._writer, frame)
        closed_wait = asyncio.ensure_future(self.closed.wait())
        try:
            done, _ = await asyncio.wait(
                {fut, closed_wait}, timeout=timeout,
                return_when=asyncio.FIRST_COMPLETED)
            if fut in done:
                resp = fut.result()
                if not resp.get("ok"):
                    self._raise_rejection("queue_pull", resp)
                return resp["payload"], float(resp.get("age_s", 0.0))
            if closed_wait in done:
                self._pending.pop(rid, None)
                raise ConnectionError("coordinator connection lost")
            # timeout: cancel the parked pull server-side
            resp = await self._call("queue_cancel", queue=queue,
                                    pull_rid=rid)
            if not resp.get("cancelled", False):
                # delivery already in flight — take it and give it back
                payload = (await fut)["payload"]
                await self.queue_push(queue, payload)
            self._pending.pop(rid, None)
            return None
        except asyncio.CancelledError:
            self._pending.pop(rid, None)
            if not self.closed.is_set():
                # fire-and-forget: unpark server-side (conn teardown covers
                # the closing case)
                asyncio.get_running_loop().create_task(
                    self._queue_cancel_quiet(queue, rid))
            raise
        finally:
            closed_wait.cancel()

    async def _queue_cancel_quiet(self, queue: str, pull_rid: int) -> None:
        try:
            await self._call("queue_cancel", queue=queue, pull_rid=pull_rid)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass

    async def queue_depth(self, queue: str) -> Tuple[int, int]:
        """(queued jobs, parked pullers) — the planner's prefill-pressure
        signal (reference: JetStream consumer info on the prefill queue)."""
        resp = await self._call("queue_depth", queue=queue)
        return resp["depth"], resp.get("pullers", 0)

    async def ping(self) -> float:
        return (await self._call("ping"))["time"]


def main() -> None:
    """Standalone coordinator process (``python -m
    dynamo_tpu.runtime.coordinator --port 6650``).

    Running the control plane as its own process is what makes the
    crash/restart drills in docs/deployment.md ("Control-plane outages")
    real: kill -9 this and start a fresh one on the same port — every
    supervised ``CoordClient`` reconnects and resyncs its state.

    Replication: run a second process with ``--standby-of host:6650`` (its
    own ``--port``) and give clients both addresses; the standby
    self-promotes after ``--promote-after`` seconds of primary silence
    (SIGUSR1 promotes immediately — the manual-failover path).  With
    ``DYN_SYSTEM_ENABLED=1`` a system server exposes ``dynamo_coord_role``/
    ``dynamo_coord_failovers_total``/``dynamo_coord_replication_lag_ops``
    on /metrics (port ``DYN_SYSTEM_PORT``)."""
    import argparse
    import contextlib
    import signal

    from dynamo_tpu.utils.logging import configure_logging

    parser = argparse.ArgumentParser(description="dynamo_tpu coordinator")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=6650)
    parser.add_argument("--standby-of", default=None, metavar="HOST:PORT",
                        help="run as a hot standby replicating this "
                             "primary; promotes on its failure")
    parser.add_argument("--promote-after", type=float, default=None,
                        help="standby self-promotes after this many "
                             "seconds of primary silence (default "
                             "DYN_COORD_PROMOTE_AFTER_S or "
                             f"{DEFAULT_PROMOTE_AFTER_S}; <=0 = manual "
                             "promotion only)")
    args = parser.parse_args()
    configure_logging()

    async def _run() -> None:
        coord = await Coordinator(host=args.host, port=args.port,
                                  standby_of=args.standby_of,
                                  promote_after_s=args.promote_after).start()
        loop = asyncio.get_running_loop()
        with contextlib.suppress(NotImplementedError, AttributeError):
            loop.add_signal_handler(
                signal.SIGUSR1, lambda: coord.promote("SIGUSR1"))
        system = None
        try:
            from prometheus_client import CollectorRegistry

            from dynamo_tpu.http.metrics import CoordinatorMetrics
            from dynamo_tpu.runtime.system_server import SystemServer
            registry = CollectorRegistry()
            CoordinatorMetrics(coord, registry=registry)
            system = SystemServer.from_env(registry=registry)
            if system is not None:
                system.attach_coord(coord)
                await system.start()
        except Exception:  # noqa: BLE001 — observability never gates serving
            logger.exception("coordinator system server unavailable")
        print(f"coordinator listening on {coord.address} ({coord.role})",
              flush=True)
        try:
            await asyncio.Event().wait()  # serve until killed
        finally:
            if system is not None:
                await system.stop()
            await coord.stop()

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()


__all__ = ["Coordinator", "CoordClient", "Watch", "WatchEvent", "Subscription",
           "Lease", "NotPrimaryError"]
