"""Coordinator: the framework's self-contained control-plane service.

One asyncio TCP server provides what the reference gets from two external
services (SURVEY.md §2.1, L0):

- **KV plane** (etcd-equivalent; reference ``lib/runtime/src/transports/etcd.rs``):
  put/get/delete with prefix queries, *leases* with TTL + keep-alive (all keys
  attached to a lease vanish when it expires — that is the liveness mechanism),
  and *prefix watches* that stream put/delete events to clients.
- **Event plane** (NATS-equivalent; reference ``transports/nats.rs``):
  subject-based pub/sub with trailing-wildcard subscriptions (``a.b.>``), used
  for KV-cache events, metrics broadcasts and the prefill queue.
- **Object store** (reference uses NATS object store for model-card artifacts):
  named buckets of binary blobs, implemented on the KV plane with chunking.
- **Barrier** (reference ``utils/leader_worker_barrier.rs``): implemented
  client-side on KV + watch (see ``LeaderWorkerBarrier`` in barrier.py).

Wire protocol: length-prefixed msgpack frames (codec.py).  Requests carry a
client-assigned ``rid`` and are answered with ``{"rid", "ok", ...}``; server-
initiated traffic (watch events, pub/sub messages) carries ``evt`` instead.

The coordinator is deliberately a single-threaded asyncio process: control
plane operations are low-rate (registrations, watches, metrics) while the hot
request path rides direct worker TCP connections and never touches it.
"""

from __future__ import annotations

import asyncio
import fnmatch
import itertools
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Tuple

from dynamo_tpu.runtime.codec import read_frame, send_frame, write_frame
from dynamo_tpu.utils.aio import reap_task

logger = logging.getLogger(__name__)

LEASE_SCAN_INTERVAL = 0.5  # seconds between lease-expiry scans


def _subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style match: exact, or trailing ``>`` wildcard matching the rest."""
    if pattern == subject:
        return True
    if pattern.endswith(".>"):
        return subject.startswith(pattern[:-1])  # keep the dot
    if pattern == ">":
        return True
    return False


@dataclass
class _KvEntry:
    value: bytes
    lease_id: int = 0
    version: int = 1


@dataclass
class _Lease:
    lease_id: int
    ttl: float
    expires_at: float
    keys: set = field(default_factory=set)


@dataclass
class _Watch:
    watch_id: int
    prefix: str
    conn: "_Conn"


@dataclass
class _Subscription:
    sub_id: int
    pattern: str
    conn: "_Conn"
    queue_group: Optional[str] = None


class _Conn:
    """Server-side state for one client connection."""

    def __init__(self, server: "Coordinator", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.watches: Dict[int, _Watch] = {}
        self.subs: Dict[int, _Subscription] = {}
        self.leases: set = set()
        self.alive = True
        self._wlock = asyncio.Lock()

    async def send(self, obj: Any) -> None:
        if not self.alive:
            return
        try:
            async with self._wlock:
                await send_frame(self.writer, obj)
        except (ConnectionError, RuntimeError):
            self.alive = False


class Coordinator:
    """The control-plane server.  ``async with Coordinator(port=0) as c: ...``"""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._kv: Dict[str, _KvEntry] = {}
        self._leases: Dict[int, _Lease] = {}
        self._watches: Dict[int, _Watch] = {}
        # subscriptions indexed for O(matching) publish fan-out: exact
        # subjects in a dict, the (few) trailing-wildcard patterns in a
        # list — per-page KV events at fleet scale must not pay an
        # O(all subscriptions) scan per message (VERDICT r2 weak #6)
        self._subs_exact: Dict[str, List[_Subscription]] = {}
        self._subs_wild: List[_Subscription] = []
        self._queue_rr: Dict[Tuple[str, str], int] = {}  # (pattern, group) -> rr counter
        # work queues (JetStream-queue role; the reference's prefill queue
        # rides a NATS JetStream consumer group, rust/llm/nats.rs:109):
        # FIFO per name, pulls park until an item arrives
        self._queues: Dict[str, "deque[bytes]"] = {}
        self._queue_pulls: Dict[str, "deque[Tuple[_Conn, Any]]"] = {}
        self._ids = itertools.count(1)
        self._server: Optional[asyncio.base_events.Server] = None
        self._lease_task: Optional[asyncio.Task] = None
        self._conns: set = set()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> "Coordinator":
        self._server = await asyncio.start_server(self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._lease_task = asyncio.create_task(self._lease_scanner())
        logger.info("coordinator listening on %s:%d", self.host, self.port)
        return self

    async def stop(self) -> None:
        await reap_task(self._lease_task)
        if self._server:
            self._server.close()
        # close live connections BEFORE wait_closed(): on py3.12 wait_closed
        # blocks until every connection handler exits, and handlers sit in
        # read() until their socket dies — the old order deadlocked when a
        # client was still attached (e.g. killing a coordinator under load)
        for conn in list(self._conns):
            conn.alive = False
            try:
                conn.writer.close()
            except Exception:
                pass
        if self._server:
            await self._server.wait_closed()

    async def __aenter__(self) -> "Coordinator":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- connection handling ----------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        conn = _Conn(self, reader, writer)
        self._conns.add(conn)
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                try:
                    await self._dispatch(conn, frame)
                except Exception as e:  # protocol error -> report, keep conn
                    logger.exception("coordinator dispatch error")
                    rid = frame.get("rid") if isinstance(frame, dict) else None
                    if rid is not None:
                        await conn.send({"rid": rid, "ok": False, "error": str(e)})
        finally:
            conn.alive = False
            self._conns.discard(conn)
            for w in list(conn.watches.values()):
                self._watches.pop(w.watch_id, None)
            self._drop_conn_subs(conn)
            for pulls in self._queue_pulls.values():
                # drop this connection's parked queue pulls
                for item in [p for p in pulls if p[0] is conn]:
                    pulls.remove(item)
            # leases owned by a dropped connection keep ticking until TTL expiry
            # (matches etcd semantics: reconnect within TTL keeps instances alive)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, conn: _Conn, f: Dict[str, Any]) -> None:
        op = f.get("op")
        rid = f.get("rid")
        if op == "put":
            await self._op_put(f["key"], f["value"], f.get("lease", 0))
            await conn.send({"rid": rid, "ok": True})
        elif op == "get":
            e = self._kv.get(f["key"])
            await conn.send({"rid": rid, "ok": True,
                             "value": e.value if e else None,
                             "lease": e.lease_id if e else 0})
        elif op == "get_prefix":
            items = [
                {"key": k, "value": e.value, "lease": e.lease_id}
                for k, e in sorted(self._kv.items()) if k.startswith(f["prefix"])
            ]
            await conn.send({"rid": rid, "ok": True, "items": items})
        elif op == "delete":
            n = await self._op_delete(f["key"])
            await conn.send({"rid": rid, "ok": True, "deleted": n})
        elif op == "delete_prefix":
            keys = [k for k in self._kv if k.startswith(f["prefix"])]
            for k in keys:
                await self._op_delete(k)
            await conn.send({"rid": rid, "ok": True, "deleted": len(keys)})
        elif op == "put_if_absent":
            if f["key"] in self._kv:
                await conn.send({"rid": rid, "ok": True, "created": False})
            else:
                await self._op_put(f["key"], f["value"], f.get("lease", 0))
                await conn.send({"rid": rid, "ok": True, "created": True})
        elif op == "grant_lease":
            lease = self._grant_lease(float(f.get("ttl", 10.0)))
            conn.leases.add(lease.lease_id)
            await conn.send({"rid": rid, "ok": True, "lease": lease.lease_id,
                             "ttl": lease.ttl})
        elif op == "keepalive":
            lease = self._leases.get(f["lease"])
            if lease is None:
                await conn.send({"rid": rid, "ok": False, "error": "lease not found"})
            else:
                lease.expires_at = time.monotonic() + lease.ttl
                await conn.send({"rid": rid, "ok": True})
        elif op == "revoke":
            await self._revoke_lease(f["lease"])
            await conn.send({"rid": rid, "ok": True})
        elif op == "watch_prefix":
            watch_id = next(self._ids)
            w = _Watch(watch_id=watch_id, prefix=f["prefix"], conn=conn)
            self._watches[watch_id] = w
            conn.watches[watch_id] = w
            # initial snapshot rides the response so watchers never miss state
            items = [
                {"key": k, "value": e.value, "lease": e.lease_id}
                for k, e in sorted(self._kv.items()) if k.startswith(f["prefix"])
            ]
            await conn.send({"rid": rid, "ok": True, "watch_id": watch_id,
                             "items": items})
        elif op == "unwatch":
            w = conn.watches.pop(f["watch_id"], None)
            if w:
                self._watches.pop(w.watch_id, None)
            await conn.send({"rid": rid, "ok": True})
        elif op == "publish":
            n = await self._op_publish(f["subject"], f["payload"])
            await conn.send({"rid": rid, "ok": True, "delivered": n})
        elif op == "subscribe":
            sub_id = next(self._ids)
            sub = _Subscription(sub_id=sub_id, pattern=f["subject"], conn=conn,
                                queue_group=f.get("queue_group"))
            self._add_sub(sub)
            conn.subs[sub_id] = sub
            await conn.send({"rid": rid, "ok": True, "sub_id": sub_id})
        elif op == "unsubscribe":
            sub = conn.subs.pop(f["sub_id"], None)
            if sub:
                self._remove_sub(sub)
            await conn.send({"rid": rid, "ok": True})
        elif op == "queue_push":
            depth = await self._op_queue_push(f["queue"], f["payload"])
            await conn.send({"rid": rid, "ok": True, "depth": depth})
        elif op == "queue_pull":
            await self._op_queue_pull(conn, rid, f["queue"])
        elif op == "queue_cancel":
            pulls = self._queue_pulls.get(f["queue"], deque())
            target = [(c, r) for (c, r) in pulls
                      if c is conn and r == f["pull_rid"]]
            for item in target:
                pulls.remove(item)
            await conn.send({"rid": rid, "ok": True,
                             "cancelled": bool(target)})
        elif op == "queue_depth":
            q = self._queues.get(f["queue"])
            await conn.send({"rid": rid, "ok": True,
                             "depth": len(q) if q else 0,
                             "pullers": len(self._queue_pulls.get(
                                 f["queue"], ()))})
        elif op == "ping":
            await conn.send({"rid": rid, "ok": True, "time": time.time()})
        else:
            await conn.send({"rid": rid, "ok": False, "error": f"unknown op {op!r}"})

    # -- KV ----------------------------------------------------------------

    async def _op_put(self, key: str, value: bytes, lease_id: int) -> None:
        if lease_id:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise ValueError(f"lease {lease_id} not found")
            lease.keys.add(key)
        prev = self._kv.get(key)
        self._kv[key] = _KvEntry(value=value, lease_id=lease_id,
                                 version=(prev.version + 1) if prev else 1)
        await self._notify_watchers("put", key, value, lease_id)

    async def _op_delete(self, key: str) -> int:
        e = self._kv.pop(key, None)
        if e is None:
            return 0
        if e.lease_id and e.lease_id in self._leases:
            self._leases[e.lease_id].keys.discard(key)
        await self._notify_watchers("delete", key, None, e.lease_id)
        return 1

    async def _notify_watchers(self, etype: str, key: str,
                               value: Optional[bytes], lease_id: int) -> None:
        for w in list(self._watches.values()):
            if key.startswith(w.prefix):
                await w.conn.send({"evt": "watch", "watch_id": w.watch_id,
                                   "type": etype, "key": key, "value": value,
                                   "lease": lease_id})

    # -- leases ------------------------------------------------------------

    def _grant_lease(self, ttl: float) -> _Lease:
        lease_id = next(self._ids)
        lease = _Lease(lease_id=lease_id, ttl=ttl,
                       expires_at=time.monotonic() + ttl)
        self._leases[lease_id] = lease
        return lease

    async def _revoke_lease(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            await self._op_delete(key)

    async def _lease_scanner(self) -> None:
        while True:
            await asyncio.sleep(LEASE_SCAN_INTERVAL)
            now = time.monotonic()
            expired = [lid for lid, l in self._leases.items() if l.expires_at < now]
            for lid in expired:
                logger.info("lease %d expired; revoking %d keys",
                            lid, len(self._leases[lid].keys))
                await self._revoke_lease(lid)

    # -- work queues -------------------------------------------------------

    async def _op_queue_push(self, queue: str, payload: bytes) -> int:
        """FIFO push; delivers straight to a parked puller when one waits.

        Delivery is at-most-once (no acks): the prefill flow tolerates a
        lost job because the decode side times out and falls back to local
        prefill. Returns the post-push depth (0 = handed to a puller).

        Each delivery carries ``age_s`` — time spent queued by THE
        COORDINATOR'S clock — so consumers can expire stale jobs without
        comparing wall clocks across hosts (clock skew immune)."""
        pulls = self._queue_pulls.get(queue)
        while pulls:
            conn, rid = pulls.popleft()
            if conn.alive:
                await conn.send({"rid": rid, "ok": True, "payload": payload,
                                 "age_s": 0.0, "depth": 0})
                return 0
        q = self._queues.setdefault(queue, deque())
        q.append((payload, time.monotonic()))
        return len(q)

    async def _op_queue_pull(self, conn: _Conn, rid: Any, queue: str) -> None:
        """Answer with the oldest item now, or park until a push arrives.
        A parked pull on a dying connection is skipped at delivery time."""
        q = self._queues.get(queue)
        if q:
            payload, t_in = q.popleft()
            await conn.send({"rid": rid, "ok": True, "payload": payload,
                             "age_s": time.monotonic() - t_in,
                             "depth": len(q)})
            return
        self._queue_pulls.setdefault(queue, deque()).append((conn, rid))

    # -- pub/sub -----------------------------------------------------------

    @staticmethod
    def _is_wild(pattern: str) -> bool:
        return pattern == ">" or pattern.endswith(".>")

    def _add_sub(self, sub: _Subscription) -> None:
        if self._is_wild(sub.pattern):
            self._subs_wild.append(sub)
        else:
            self._subs_exact.setdefault(sub.pattern, []).append(sub)

    def _remove_sub(self, sub: _Subscription) -> None:
        if self._is_wild(sub.pattern):
            self._subs_wild = [s for s in self._subs_wild
                               if s.sub_id != sub.sub_id]
        else:
            lst = self._subs_exact.get(sub.pattern, [])
            lst[:] = [s for s in lst if s.sub_id != sub.sub_id]
            if not lst:
                self._subs_exact.pop(sub.pattern, None)

    def _drop_conn_subs(self, conn: _Conn) -> None:
        for sub in list(conn.subs.values()):
            self._remove_sub(sub)

    def _matching_subs(self, subject: str):
        # snapshot copies: fan-out awaits between sends, and an
        # unsubscribe/disconnect during an await mutates these lists —
        # iterating the live list would skip a subscriber
        yield from list(self._subs_exact.get(subject, ()))
        for s in list(self._subs_wild):
            if _subject_matches(s.pattern, subject):
                yield s

    async def _op_publish(self, subject: str, payload: bytes) -> int:
        delivered = 0
        # queue groups: of the members subscribed with the same (pattern, group),
        # exactly one receives each message (NATS queue semantics — the
        # reference uses this for the JetStream prefill queue).
        groups: Dict[Tuple[str, str], List[_Subscription]] = {}
        for s in self._matching_subs(subject):
            if not s.conn.alive:
                continue
            if s.queue_group:
                groups.setdefault((s.pattern, s.queue_group), []).append(s)
            else:
                await s.conn.send({"evt": "msg", "sub_id": s.sub_id,
                                   "subject": subject, "payload": payload})
                delivered += 1
        for gkey, members in groups.items():
            idx = self._queue_rr.get(gkey, 0) % len(members)
            self._queue_rr[gkey] = idx + 1
            s = members[idx]
            await s.conn.send({"evt": "msg", "sub_id": s.sub_id,
                               "subject": subject, "payload": payload})
            delivered += 1
        return delivered


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class WatchEvent:
    __slots__ = ("type", "key", "value", "lease_id")

    def __init__(self, type: str, key: str, value: Optional[bytes], lease_id: int = 0):
        self.type = type
        self.key = key
        self.value = value
        self.lease_id = lease_id

    def __repr__(self) -> str:
        return f"WatchEvent({self.type}, {self.key!r})"


class Watch:
    """A live prefix watch: initial snapshot + async iterator of events."""

    def __init__(self, client: "CoordClient", watch_id: int,
                 snapshot: List[Dict[str, Any]]):
        self._client = client
        self.watch_id = watch_id
        self.snapshot = [(i["key"], i["value"]) for i in snapshot]
        self.queue: asyncio.Queue = asyncio.Queue()

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self.queue.get()
        if ev is None:
            raise StopAsyncIteration
        return ev

    async def cancel(self) -> None:
        await self._client.unwatch(self.watch_id)


class Subscription:
    """A live pub/sub subscription: async iterator of (subject, payload)."""

    def __init__(self, client: "CoordClient", sub_id: int):
        self._client = client
        self.sub_id = sub_id
        self.queue: asyncio.Queue = asyncio.Queue()

    def __aiter__(self) -> "Subscription":
        return self

    async def __anext__(self) -> Tuple[str, bytes]:
        item = await self.queue.get()
        if item is None:
            raise StopAsyncIteration
        return item

    async def cancel(self) -> None:
        await self._client.unsubscribe(self.sub_id)


class Lease:
    """Client-side lease handle with automatic keep-alive task."""

    def __init__(self, client: "CoordClient", lease_id: int, ttl: float):
        self.client = client
        self.lease_id = lease_id
        self.ttl = ttl
        self._task: Optional[asyncio.Task] = None
        self.lost = asyncio.Event()

    def start_keepalive(self) -> None:
        self._task = asyncio.create_task(self._keepalive_loop())

    async def _keepalive_loop(self) -> None:
        # (no CancelledError catch: see utils/aio.reap_task)
        interval = max(self.ttl / 3.0, 0.1)
        while True:
            await asyncio.sleep(interval)
            try:
                await self.client.keepalive(self.lease_id)
            except Exception:
                logger.warning("lease %d keep-alive failed", self.lease_id)
                self.lost.set()
                return

    async def revoke(self) -> None:
        if self._task:
            self._task.cancel()
        try:
            await self.client.revoke(self.lease_id)
        except Exception:
            pass


class CoordClient:
    """Async client for the Coordinator."""

    def __init__(self, address: str):
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._rids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watches: Dict[int, Watch] = {}
        self._subs: Dict[int, Subscription] = {}
        # events/messages that raced ahead of watch/subscription registration
        # (the server's response and a first event can share one TCP segment)
        self._orphan_events: Dict[int, list] = {}
        self._orphan_msgs: Dict[int, list] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._wlock: Optional[asyncio.Lock] = None
        self.closed = asyncio.Event()

    async def connect(self) -> "CoordClient":
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._wlock = asyncio.Lock()
        self._reader_task = asyncio.create_task(self._read_loop())
        return self

    async def close(self) -> None:
        await reap_task(self._reader_task)
        if self._writer:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass
        self.closed.set()

    async def __aenter__(self) -> "CoordClient":
        return await self.connect()

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame is None:
                    break
                if "rid" in frame and frame["rid"] is not None:
                    fut = self._pending.pop(frame["rid"], None)
                    if fut and not fut.done():
                        fut.set_result(frame)
                elif frame.get("evt") == "watch":
                    ev = WatchEvent(frame["type"], frame["key"],
                                    frame.get("value"), frame.get("lease", 0))
                    w = self._watches.get(frame["watch_id"])
                    if w:
                        w.queue.put_nowait(ev)
                    else:
                        buf = self._orphan_events.setdefault(frame["watch_id"], [])
                        if len(buf) < 10_000:
                            buf.append(ev)
                elif frame.get("evt") == "msg":
                    item = (frame["subject"], frame["payload"])
                    s = self._subs.get(frame["sub_id"])
                    if s:
                        s.queue.put_nowait(item)
                    else:
                        buf = self._orphan_msgs.setdefault(frame["sub_id"], [])
                        if len(buf) < 10_000:
                            buf.append(item)
        except ConnectionError:
            pass  # CancelledError must propagate (see utils/aio.reap_task)
        finally:
            self.closed.set()
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("coordinator connection lost"))
            self._pending.clear()
            for w in self._watches.values():
                w.queue.put_nowait(None)
            for s in self._subs.values():
                s.queue.put_nowait(None)

    async def _call(self, op: str, **kw: Any) -> Dict[str, Any]:
        if self._writer is None:
            raise ConnectionError("not connected")
        if self.closed.is_set():
            raise ConnectionError("coordinator connection lost")
        rid = next(self._rids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        frame = {"op": op, "rid": rid, **kw}
        async with self._wlock:
            await send_frame(self._writer, frame)
        # A dead connection may accept the write (TCP buffering) while the
        # read loop has already torn down — or tears down after we register
        # the future but before the reply. Racing against `closed` turns
        # every such case into a prompt ConnectionError instead of a hang.
        closed_wait = asyncio.ensure_future(self.closed.wait())
        try:
            done, _ = await asyncio.wait({fut, closed_wait},
                                         return_when=asyncio.FIRST_COMPLETED)
            if fut not in done:
                self._pending.pop(rid, None)
                raise ConnectionError("coordinator connection lost")
            resp = fut.result()
        finally:
            closed_wait.cancel()
        if not resp.get("ok"):
            raise RuntimeError(f"coordinator {op} failed: {resp.get('error')}")
        return resp

    # -- KV API ------------------------------------------------------------

    async def put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        await self._call("put", key=key, value=value, lease=lease_id)

    async def put_if_absent(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        resp = await self._call("put_if_absent", key=key, value=value, lease=lease_id)
        return bool(resp["created"])

    async def get(self, key: str) -> Optional[bytes]:
        resp = await self._call("get", key=key)
        return resp.get("value")

    async def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        resp = await self._call("get_prefix", prefix=prefix)
        return [(i["key"], i["value"]) for i in resp["items"]]

    async def delete(self, key: str) -> int:
        return (await self._call("delete", key=key))["deleted"]

    async def delete_prefix(self, prefix: str) -> int:
        return (await self._call("delete_prefix", prefix=prefix))["deleted"]

    # -- leases ------------------------------------------------------------

    async def grant_lease(self, ttl: float = 10.0, keepalive: bool = True) -> Lease:
        resp = await self._call("grant_lease", ttl=ttl)
        lease = Lease(self, resp["lease"], resp["ttl"])
        if keepalive:
            lease.start_keepalive()
        return lease

    async def keepalive(self, lease_id: int) -> None:
        await self._call("keepalive", lease=lease_id)

    async def revoke(self, lease_id: int) -> None:
        await self._call("revoke", lease=lease_id)

    # -- watches -----------------------------------------------------------

    async def watch_prefix(self, prefix: str) -> Watch:
        resp = await self._call("watch_prefix", prefix=prefix)
        w = Watch(self, resp["watch_id"], resp.get("items", []))
        self._watches[w.watch_id] = w
        # drain events that arrived between the server registering the watch
        # and us registering the Watch object (no await between these lines)
        for ev in self._orphan_events.pop(w.watch_id, []):
            w.queue.put_nowait(ev)
        return w

    async def unwatch(self, watch_id: int) -> None:
        self._watches.pop(watch_id, None)
        await self._call("unwatch", watch_id=watch_id)
        self._orphan_events.pop(watch_id, None)  # drop in-flight stragglers

    # -- pub/sub -----------------------------------------------------------

    async def publish(self, subject: str, payload: bytes) -> int:
        return (await self._call("publish", subject=subject, payload=payload))["delivered"]

    async def subscribe(self, subject: str,
                        queue_group: Optional[str] = None) -> Subscription:
        resp = await self._call("subscribe", subject=subject, queue_group=queue_group)
        s = Subscription(self, resp["sub_id"])
        self._subs[s.sub_id] = s
        for item in self._orphan_msgs.pop(s.sub_id, []):
            s.queue.put_nowait(item)
        return s

    async def unsubscribe(self, sub_id: int) -> None:
        self._subs.pop(sub_id, None)
        await self._call("unsubscribe", sub_id=sub_id)
        self._orphan_msgs.pop(sub_id, None)

    # -- object store ------------------------------------------------------
    # (reference: NATS object store carrying model-card artifacts,
    # ``transports/nats.rs:123-176``.) Implemented client-side on the KV
    # plane: ``obj/{bucket}/{name}/meta`` + ``/c{i}`` chunk keys. Chunking
    # keeps any single KV value (and coordinator frame) small even for
    # multi-MB artifacts like inlined tokenizers.

    OBJ_CHUNK = 1 << 20  # 1 MiB per chunk

    @staticmethod
    def _obj_prefix(bucket: str, name: str) -> str:
        return f"obj/{bucket}/{name}/"

    async def obj_put(self, bucket: str, name: str, data: bytes,
                      lease_id: int = 0) -> int:
        """Store an object as chunked KV entries; returns chunk count.
        Attach a lease to make the object vanish with its owner."""
        prefix = self._obj_prefix(bucket, name)
        n = max(1, -(-len(data) // self.OBJ_CHUNK))
        for i in range(n):
            chunk = data[i * self.OBJ_CHUNK:(i + 1) * self.OBJ_CHUNK]
            await self.put(f"{prefix}c{i:06d}", chunk, lease_id=lease_id)
        import json as _json
        await self.put(f"{prefix}meta",
                       _json.dumps({"size": len(data),
                                    "chunks": n}).encode(),
                       lease_id=lease_id)
        return n

    async def obj_get(self, bucket: str, name: str) -> Optional[bytes]:
        """Fetch a stored object, or None when absent/incomplete."""
        prefix = self._obj_prefix(bucket, name)
        meta_raw = await self.get(f"{prefix}meta")
        if meta_raw is None:
            return None
        import json as _json
        meta = _json.loads(meta_raw)
        parts = []
        for i in range(int(meta["chunks"])):
            c = await self.get(f"{prefix}c{i:06d}")
            if c is None:
                return None  # torn write/expiry mid-read
            parts.append(c)
        data = b"".join(parts)
        return data if len(data) == int(meta["size"]) else None

    async def obj_delete(self, bucket: str, name: str) -> int:
        return await self.delete_prefix(self._obj_prefix(bucket, name))

    # -- work queues -------------------------------------------------------

    async def queue_push(self, queue: str, payload: bytes) -> int:
        """Push one job; returns post-push depth (0 = a puller took it)."""
        return (await self._call("queue_push", queue=queue,
                                 payload=payload))["depth"]

    async def queue_pull(self, queue: str,
                         timeout: Optional[float] = None
                         ) -> Optional[Tuple[bytes, float]]:
        """Pull the oldest job, parking server-side until one arrives;
        returns (payload, age_s) — ``age_s`` is time spent queued by the
        coordinator's clock — or None on timeout.

        Timeout protocol: the parked pull is explicitly cancelled
        (``queue_cancel``). If the cancel races a delivery already in
        flight, the client waits for it and pushes the job BACK, so a
        timed-out puller can never swallow a job. External CANCELLATION of
        this coroutine fires the same best-effort server-side cancel so a
        parked pull on a still-live connection cannot swallow a later push
        into an orphaned future."""
        if self._writer is None:
            raise ConnectionError("not connected")
        rid = next(self._rids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._wlock:
            await send_frame(self._writer,
                             {"op": "queue_pull", "rid": rid, "queue": queue})
        closed_wait = asyncio.ensure_future(self.closed.wait())
        try:
            done, _ = await asyncio.wait(
                {fut, closed_wait}, timeout=timeout,
                return_when=asyncio.FIRST_COMPLETED)
            if fut in done:
                resp = fut.result()
                return resp["payload"], float(resp.get("age_s", 0.0))
            if closed_wait in done:
                self._pending.pop(rid, None)
                raise ConnectionError("coordinator connection lost")
            # timeout: cancel the parked pull server-side
            resp = await self._call("queue_cancel", queue=queue,
                                    pull_rid=rid)
            if not resp.get("cancelled", False):
                # delivery already in flight — take it and give it back
                payload = (await fut)["payload"]
                await self.queue_push(queue, payload)
            self._pending.pop(rid, None)
            return None
        except asyncio.CancelledError:
            self._pending.pop(rid, None)
            if not self.closed.is_set():
                # fire-and-forget: unpark server-side (conn teardown covers
                # the closing case)
                asyncio.get_running_loop().create_task(
                    self._queue_cancel_quiet(queue, rid))
            raise
        finally:
            closed_wait.cancel()

    async def _queue_cancel_quiet(self, queue: str, pull_rid: int) -> None:
        try:
            await self._call("queue_cancel", queue=queue, pull_rid=pull_rid)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass

    async def queue_depth(self, queue: str) -> Tuple[int, int]:
        """(queued jobs, parked pullers) — the planner's prefill-pressure
        signal (reference: JetStream consumer info on the prefill queue)."""
        resp = await self._call("queue_depth", queue=queue)
        return resp["depth"], resp.get("pullers", 0)

    async def ping(self) -> float:
        return (await self._call("ping"))["time"]


__all__ = ["Coordinator", "CoordClient", "Watch", "WatchEvent", "Subscription",
           "Lease"]
