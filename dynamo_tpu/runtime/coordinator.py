"""Coordinator: the framework's self-contained control-plane service.

One asyncio TCP server provides what the reference gets from two external
services (SURVEY.md §2.1, L0):

- **KV plane** (etcd-equivalent; reference ``lib/runtime/src/transports/etcd.rs``):
  put/get/delete with prefix queries, *leases* with TTL + keep-alive (all keys
  attached to a lease vanish when it expires — that is the liveness mechanism),
  and *prefix watches* that stream put/delete events to clients.
- **Event plane** (NATS-equivalent; reference ``transports/nats.rs``):
  subject-based pub/sub with trailing-wildcard subscriptions (``a.b.>``), used
  for KV-cache events, metrics broadcasts and the prefill queue.
- **Object store** (reference uses NATS object store for model-card artifacts):
  named buckets of binary blobs, implemented on the KV plane with chunking.
- **Barrier** (reference ``utils/leader_worker_barrier.rs``): implemented
  client-side on KV + watch (see ``LeaderWorkerBarrier`` in barrier.py).

Wire protocol: length-prefixed msgpack frames (codec.py).  Requests carry a
client-assigned ``rid`` and are answered with ``{"rid", "ok", ...}``; server-
initiated traffic (watch events, pub/sub messages) carries ``evt`` instead.

The coordinator is deliberately a single-threaded asyncio process: control
plane operations are low-rate (registrations, watches, metrics) while the hot
request path rides direct worker TCP connections and never touches it.
"""

from __future__ import annotations

import asyncio
import fnmatch
import itertools
import logging
import os
import random
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (Any, AsyncIterator, Awaitable, Callable, Dict, List,
                    Optional, Tuple)

from dynamo_tpu.runtime.codec import read_frame, send_frame, write_frame
from dynamo_tpu.utils.aio import decorrelated_jitter, reap_task

logger = logging.getLogger(__name__)

LEASE_SCAN_INTERVAL = 0.5  # seconds between lease-expiry scans


def _subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style match: exact, or trailing ``>`` wildcard matching the rest."""
    if pattern == subject:
        return True
    if pattern.endswith(".>"):
        return subject.startswith(pattern[:-1])  # keep the dot
    if pattern == ">":
        return True
    return False


@dataclass
class _KvEntry:
    value: bytes
    lease_id: int = 0
    version: int = 1


@dataclass
class _Lease:
    lease_id: int
    ttl: float
    expires_at: float
    keys: set = field(default_factory=set)


@dataclass
class _Watch:
    watch_id: int
    prefix: str
    conn: "_Conn"


@dataclass
class _Subscription:
    sub_id: int
    pattern: str
    conn: "_Conn"
    queue_group: Optional[str] = None


class _Conn:
    """Server-side state for one client connection."""

    def __init__(self, server: "Coordinator", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.watches: Dict[int, _Watch] = {}
        self.subs: Dict[int, _Subscription] = {}
        self.leases: set = set()
        self.alive = True
        self._wlock = asyncio.Lock()

    async def send(self, obj: Any) -> None:
        if not self.alive:
            return
        try:
            async with self._wlock:
                await send_frame(self.writer, obj)
        except (ConnectionError, RuntimeError):
            self.alive = False


class Coordinator:
    """The control-plane server.  ``async with Coordinator(port=0) as c: ...``"""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._kv: Dict[str, _KvEntry] = {}
        self._leases: Dict[int, _Lease] = {}
        self._watches: Dict[int, _Watch] = {}
        # subscriptions indexed for O(matching) publish fan-out: exact
        # subjects in a dict, the (few) trailing-wildcard patterns in a
        # list — per-page KV events at fleet scale must not pay an
        # O(all subscriptions) scan per message (VERDICT r2 weak #6)
        self._subs_exact: Dict[str, List[_Subscription]] = {}
        self._subs_wild: List[_Subscription] = []
        self._queue_rr: Dict[Tuple[str, str], int] = {}  # (pattern, group) -> rr counter
        # work queues (JetStream-queue role; the reference's prefill queue
        # rides a NATS JetStream consumer group, rust/llm/nats.rs:109):
        # FIFO per name, pulls park until an item arrives
        self._queues: Dict[str, "deque[bytes]"] = {}
        self._queue_pulls: Dict[str, "deque[Tuple[_Conn, Any]]"] = {}
        self._ids = itertools.count(1)
        # boot epoch: lets a resyncing client tell "same server, state
        # intact" from "fresh/wiped server" — the id counter restarts on a
        # real process restart, so a probed lease id may EXIST yet belong
        # to another client's re-grant; epoch mismatch forces re-grants
        self._epoch = random.getrandbits(63)
        self._server: Optional[asyncio.base_events.Server] = None
        self._lease_task: Optional[asyncio.Task] = None
        self._conns: set = set()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> "Coordinator":
        self._server = await asyncio.start_server(self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._lease_task = asyncio.create_task(self._lease_scanner())
        logger.info("coordinator listening on %s:%d", self.host, self.port)
        return self

    async def stop(self) -> None:
        await reap_task(self._lease_task)
        if self._server:
            self._server.close()
        # close live connections BEFORE wait_closed(): on py3.12 wait_closed
        # blocks until every connection handler exits, and handlers sit in
        # read() until their socket dies — the old order deadlocked when a
        # client was still attached (e.g. killing a coordinator under load)
        for conn in list(self._conns):
            conn.alive = False
            try:
                conn.writer.close()
            except Exception:
                pass
        if self._server:
            await self._server.wait_closed()

    async def __aenter__(self) -> "Coordinator":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- connection handling ----------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        conn = _Conn(self, reader, writer)
        self._conns.add(conn)
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                try:
                    await self._dispatch(conn, frame)
                except Exception as e:  # protocol error -> report, keep conn
                    logger.exception("coordinator dispatch error")
                    rid = frame.get("rid") if isinstance(frame, dict) else None
                    if rid is not None:
                        await conn.send({"rid": rid, "ok": False, "error": str(e)})
        finally:
            conn.alive = False
            self._conns.discard(conn)
            for w in list(conn.watches.values()):
                self._watches.pop(w.watch_id, None)
            self._drop_conn_subs(conn)
            for pulls in self._queue_pulls.values():
                # drop this connection's parked queue pulls
                for item in [p for p in pulls if p[0] is conn]:
                    pulls.remove(item)
            # leases owned by a dropped connection keep ticking until TTL expiry
            # (matches etcd semantics: reconnect within TTL keeps instances alive)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, conn: _Conn, f: Dict[str, Any]) -> None:
        op = f.get("op")
        rid = f.get("rid")
        if op == "put":
            await self._op_put(f["key"], f["value"], f.get("lease", 0))
            await conn.send({"rid": rid, "ok": True})
        elif op == "get":
            e = self._kv.get(f["key"])
            await conn.send({"rid": rid, "ok": True,
                             "value": e.value if e else None,
                             "lease": e.lease_id if e else 0})
        elif op == "get_prefix":
            items = [
                {"key": k, "value": e.value, "lease": e.lease_id}
                for k, e in sorted(self._kv.items()) if k.startswith(f["prefix"])
            ]
            await conn.send({"rid": rid, "ok": True, "items": items})
        elif op == "delete":
            n = await self._op_delete(f["key"])
            await conn.send({"rid": rid, "ok": True, "deleted": n})
        elif op == "delete_prefix":
            keys = [k for k in self._kv if k.startswith(f["prefix"])]
            for k in keys:
                await self._op_delete(k)
            await conn.send({"rid": rid, "ok": True, "deleted": len(keys)})
        elif op == "put_if_absent":
            if f["key"] in self._kv:
                await conn.send({"rid": rid, "ok": True, "created": False})
            else:
                await self._op_put(f["key"], f["value"], f.get("lease", 0))
                await conn.send({"rid": rid, "ok": True, "created": True})
        elif op == "grant_lease":
            lease = self._grant_lease(float(f.get("ttl", 10.0)))
            conn.leases.add(lease.lease_id)
            await conn.send({"rid": rid, "ok": True, "lease": lease.lease_id,
                             "ttl": lease.ttl})
        elif op == "keepalive":
            lease = self._leases.get(f["lease"])
            if lease is None:
                await conn.send({"rid": rid, "ok": False, "error": "lease not found"})
            else:
                lease.expires_at = time.monotonic() + lease.ttl
                await conn.send({"rid": rid, "ok": True})
        elif op == "revoke":
            await self._revoke_lease(f["lease"])
            await conn.send({"rid": rid, "ok": True})
        elif op == "watch_prefix":
            watch_id = next(self._ids)
            w = _Watch(watch_id=watch_id, prefix=f["prefix"], conn=conn)
            self._watches[watch_id] = w
            conn.watches[watch_id] = w
            # initial snapshot rides the response so watchers never miss state
            items = [
                {"key": k, "value": e.value, "lease": e.lease_id}
                for k, e in sorted(self._kv.items()) if k.startswith(f["prefix"])
            ]
            await conn.send({"rid": rid, "ok": True, "watch_id": watch_id,
                             "items": items})
        elif op == "unwatch":
            w = conn.watches.pop(f["watch_id"], None)
            if w:
                self._watches.pop(w.watch_id, None)
            await conn.send({"rid": rid, "ok": True})
        elif op == "publish":
            n = await self._op_publish(f["subject"], f["payload"])
            await conn.send({"rid": rid, "ok": True, "delivered": n})
        elif op == "subscribe":
            sub_id = next(self._ids)
            sub = _Subscription(sub_id=sub_id, pattern=f["subject"], conn=conn,
                                queue_group=f.get("queue_group"))
            self._add_sub(sub)
            conn.subs[sub_id] = sub
            await conn.send({"rid": rid, "ok": True, "sub_id": sub_id})
        elif op == "unsubscribe":
            sub = conn.subs.pop(f["sub_id"], None)
            if sub:
                self._remove_sub(sub)
            await conn.send({"rid": rid, "ok": True})
        elif op == "queue_push":
            depth = await self._op_queue_push(f["queue"], f["payload"])
            await conn.send({"rid": rid, "ok": True, "depth": depth})
        elif op == "queue_pull":
            await self._op_queue_pull(conn, rid, f["queue"])
        elif op == "queue_cancel":
            pulls = self._queue_pulls.get(f["queue"], deque())
            target = [(c, r) for (c, r) in pulls
                      if c is conn and r == f["pull_rid"]]
            for item in target:
                pulls.remove(item)
            await conn.send({"rid": rid, "ok": True,
                             "cancelled": bool(target)})
        elif op == "queue_depth":
            q = self._queues.get(f["queue"])
            await conn.send({"rid": rid, "ok": True,
                             "depth": len(q) if q else 0,
                             "pullers": len(self._queue_pulls.get(
                                 f["queue"], ()))})
        elif op == "ping":
            await conn.send({"rid": rid, "ok": True, "time": time.time(),
                             "epoch": self._epoch})
        else:
            await conn.send({"rid": rid, "ok": False, "error": f"unknown op {op!r}"})

    # -- KV ----------------------------------------------------------------

    async def _op_put(self, key: str, value: bytes, lease_id: int) -> None:
        if lease_id:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise ValueError(f"lease {lease_id} not found")
            lease.keys.add(key)
        prev = self._kv.get(key)
        self._kv[key] = _KvEntry(value=value, lease_id=lease_id,
                                 version=(prev.version + 1) if prev else 1)
        await self._notify_watchers("put", key, value, lease_id)

    async def _op_delete(self, key: str) -> int:
        e = self._kv.pop(key, None)
        if e is None:
            return 0
        if e.lease_id and e.lease_id in self._leases:
            self._leases[e.lease_id].keys.discard(key)
        await self._notify_watchers("delete", key, None, e.lease_id)
        return 1

    async def _notify_watchers(self, etype: str, key: str,
                               value: Optional[bytes], lease_id: int) -> None:
        for w in list(self._watches.values()):
            if key.startswith(w.prefix):
                await w.conn.send({"evt": "watch", "watch_id": w.watch_id,
                                   "type": etype, "key": key, "value": value,
                                   "lease": lease_id})

    # -- leases ------------------------------------------------------------

    def _grant_lease(self, ttl: float) -> _Lease:
        lease_id = next(self._ids)
        lease = _Lease(lease_id=lease_id, ttl=ttl,
                       expires_at=time.monotonic() + ttl)
        self._leases[lease_id] = lease
        return lease

    async def _revoke_lease(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            await self._op_delete(key)

    async def _lease_scanner(self) -> None:
        while True:
            await asyncio.sleep(LEASE_SCAN_INTERVAL)
            now = time.monotonic()
            expired = [lid for lid, l in self._leases.items() if l.expires_at < now]
            for lid in expired:
                logger.info("lease %d expired; revoking %d keys",
                            lid, len(self._leases[lid].keys))
                await self._revoke_lease(lid)

    # -- work queues -------------------------------------------------------

    async def _op_queue_push(self, queue: str, payload: bytes) -> int:
        """FIFO push; delivers straight to a parked puller when one waits.

        Delivery is at-most-once (no acks): the prefill flow tolerates a
        lost job because the decode side times out and falls back to local
        prefill. Returns the post-push depth (0 = handed to a puller).

        Each delivery carries ``age_s`` — time spent queued by THE
        COORDINATOR'S clock — so consumers can expire stale jobs without
        comparing wall clocks across hosts (clock skew immune)."""
        pulls = self._queue_pulls.get(queue)
        while pulls:
            conn, rid = pulls.popleft()
            if conn.alive:
                await conn.send({"rid": rid, "ok": True, "payload": payload,
                                 "age_s": 0.0, "depth": 0})
                return 0
        q = self._queues.setdefault(queue, deque())
        q.append((payload, time.monotonic()))
        return len(q)

    async def _op_queue_pull(self, conn: _Conn, rid: Any, queue: str) -> None:
        """Answer with the oldest item now, or park until a push arrives.
        A parked pull on a dying connection is skipped at delivery time."""
        q = self._queues.get(queue)
        if q:
            payload, t_in = q.popleft()
            await conn.send({"rid": rid, "ok": True, "payload": payload,
                             "age_s": time.monotonic() - t_in,
                             "depth": len(q)})
            return
        self._queue_pulls.setdefault(queue, deque()).append((conn, rid))

    # -- pub/sub -----------------------------------------------------------

    @staticmethod
    def _is_wild(pattern: str) -> bool:
        return pattern == ">" or pattern.endswith(".>")

    def _add_sub(self, sub: _Subscription) -> None:
        if self._is_wild(sub.pattern):
            self._subs_wild.append(sub)
        else:
            self._subs_exact.setdefault(sub.pattern, []).append(sub)

    def _remove_sub(self, sub: _Subscription) -> None:
        if self._is_wild(sub.pattern):
            self._subs_wild = [s for s in self._subs_wild
                               if s.sub_id != sub.sub_id]
        else:
            lst = self._subs_exact.get(sub.pattern, [])
            lst[:] = [s for s in lst if s.sub_id != sub.sub_id]
            if not lst:
                self._subs_exact.pop(sub.pattern, None)

    def _drop_conn_subs(self, conn: _Conn) -> None:
        for sub in list(conn.subs.values()):
            self._remove_sub(sub)

    def _matching_subs(self, subject: str):
        # snapshot copies: fan-out awaits between sends, and an
        # unsubscribe/disconnect during an await mutates these lists —
        # iterating the live list would skip a subscriber
        yield from list(self._subs_exact.get(subject, ()))
        for s in list(self._subs_wild):
            if _subject_matches(s.pattern, subject):
                yield s

    async def _op_publish(self, subject: str, payload: bytes) -> int:
        delivered = 0
        # queue groups: of the members subscribed with the same (pattern, group),
        # exactly one receives each message (NATS queue semantics — the
        # reference uses this for the JetStream prefill queue).
        groups: Dict[Tuple[str, str], List[_Subscription]] = {}
        for s in self._matching_subs(subject):
            if not s.conn.alive:
                continue
            if s.queue_group:
                groups.setdefault((s.pattern, s.queue_group), []).append(s)
            else:
                await s.conn.send({"evt": "msg", "sub_id": s.sub_id,
                                   "subject": subject, "payload": payload})
                delivered += 1
        for gkey, members in groups.items():
            idx = self._queue_rr.get(gkey, 0) % len(members)
            self._queue_rr[gkey] = idx + 1
            s = members[idx]
            await s.conn.send({"evt": "msg", "sub_id": s.sub_id,
                               "subject": subject, "payload": payload})
            delivered += 1
        return delivered


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class WatchEvent:
    __slots__ = ("type", "key", "value", "lease_id")

    def __init__(self, type: str, key: str, value: Optional[bytes], lease_id: int = 0):
        self.type = type
        self.key = key
        self.value = value
        self.lease_id = lease_id

    def __repr__(self) -> str:
        return f"WatchEvent({self.type}, {self.key!r})"


class Watch:
    """A live prefix watch: initial snapshot + async iterator of events.

    The watch survives coordinator reconnects: ``state`` tracks the
    last-delivered view of the prefix, and on resync the client re-scans the
    prefix and diffs against it, synthesizing put/delete events so consumers
    see one consistent stream instead of EOF (see CoordClient._resync)."""

    def __init__(self, client: "CoordClient", watch_id: int,
                 snapshot: List[Dict[str, Any]], prefix: str = ""):
        self._client = client
        self.watch_id = watch_id
        self.prefix = prefix
        self.snapshot = [(i["key"], i["value"]) for i in snapshot]
        # last-known view: key -> (value, lease_id); tuples are stored by
        # identity so the resync grace pass can tell "unchanged since the
        # outage" from "re-put with the same value"
        self.state: Dict[str, Tuple[Optional[bytes], int]] = {
            i["key"]: (i["value"], i.get("lease", 0)) for i in snapshot}
        self.queue: asyncio.Queue = asyncio.Queue()
        self.cancelled = False

    def _deliver(self, ev: WatchEvent) -> None:
        if ev.type == "put":
            self.state[ev.key] = (ev.value, ev.lease_id)
        elif ev.type == "delete":
            self.state.pop(ev.key, None)
        self.queue.put_nowait(ev)

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self.queue.get()
        if ev is None:
            raise StopAsyncIteration
        return ev

    async def cancel(self) -> None:
        # flag first: a resync running concurrently must not resurrect
        # this watch when it swaps in the re-registered id map
        self.cancelled = True
        await self._client.unwatch(self.watch_id)


class Subscription:
    """A live pub/sub subscription: async iterator of (subject, payload).

    Remembers its subject/queue_group so the client can re-subscribe it
    transparently after a coordinator reconnect."""

    def __init__(self, client: "CoordClient", sub_id: int, subject: str = "",
                 queue_group: Optional[str] = None):
        self._client = client
        self.sub_id = sub_id
        self.subject = subject
        self.queue_group = queue_group
        self.queue: asyncio.Queue = asyncio.Queue()
        self.cancelled = False

    def __aiter__(self) -> "Subscription":
        return self

    async def __anext__(self) -> Tuple[str, bytes]:
        item = await self.queue.get()
        if item is None:
            raise StopAsyncIteration
        return item

    async def cancel(self) -> None:
        self.cancelled = True  # see Watch.cancel
        await self._client.unsubscribe(self.sub_id)


class Lease:
    """Client-side lease handle with automatic keep-alive task.

    Robustness contract (vs. the original fail-on-first-error loop):

    - transient keep-alive failures retry within the TTL budget instead of
      declaring the lease lost immediately;
    - a coordinator disconnect parks the loop until the supervised client
      reconnects — the resync protocol re-probes the lease and, if the
      server no longer knows it, re-grants it under a NEW id, mutating
      ``lease_id`` in place and firing ``on_relocated`` callbacks;
    - ``lost`` fires only when the lease is genuinely unrecoverable: the
      client closed (or gave up reconnecting), or keep-alives kept failing
      past a full TTL while connected.
    """

    def __init__(self, client: "CoordClient", lease_id: int, ttl: float):
        self.client = client
        self.lease_id = lease_id
        self.ttl = ttl
        self._task: Optional[asyncio.Task] = None
        self.lost = asyncio.Event()
        self._relocated_cbs: List[Callable[[int, int], None]] = []
        self._last_ok = time.monotonic()

    def on_relocated(self, cb: Callable[[int, int], None]) -> None:
        """Register ``cb(old_id, new_id)``, fired when a coordinator resync
        re-grants this lease under a fresh id."""
        self._relocated_cbs.append(cb)

    def _relocate(self, new_id: int) -> None:
        old, self.lease_id = self.lease_id, new_id
        self._last_ok = time.monotonic()
        logger.info("lease %d relocated to %d by coordinator resync",
                    old, new_id)
        for cb in list(self._relocated_cbs):
            try:
                cb(old, new_id)
            except Exception:
                logger.exception("lease relocated callback failed")

    def _mark_lost(self) -> None:
        # deregister before signalling: a later resync must not re-grant a
        # lease nobody keeps alive any more
        self.client._lease_handles.discard(self)
        self.lost.set()

    def start_keepalive(self) -> None:
        self._task = asyncio.create_task(self._keepalive_loop())

    async def _keepalive_loop(self) -> None:
        # (no CancelledError catch: see utils/aio.reap_task)
        interval = max(self.ttl / 3.0, 0.1)
        retry_sleep = max(min(interval / 4.0, 0.25), 0.02)
        self._last_ok = time.monotonic()
        while True:
            await asyncio.sleep(interval)
            while True:
                if self.client.closed.is_set():
                    self._mark_lost()
                    return
                if not self.client.connected:
                    # outage: the resync protocol re-probes / re-grants this
                    # lease as part of reconnecting, so just wait it out
                    try:
                        await self.client.wait_connected()
                    except ConnectionError:
                        logger.warning("lease %d lost: coordinator client "
                                       "closed", self.lease_id)
                        self._mark_lost()
                        return
                    self._last_ok = time.monotonic()
                    break
                try:
                    # bounded: a half-open connection (blackholed but not
                    # reset — read loop never errors) must not hang the RPC
                    # forever, or the lease silently expires server-side
                    # while this loop still believes it is healthy; a hang
                    # lands in the TTL-budget branch below like any other
                    # transient failure
                    await asyncio.wait_for(
                        self.client.keepalive(self.lease_id),
                        timeout=interval)
                    self._last_ok = time.monotonic()
                    break
                except ConnectionError:
                    # the write side can fail before the read loop marks the
                    # connection down; yield briefly so we land in the
                    # disconnected branch above instead of spinning
                    await asyncio.sleep(retry_sleep)
                    continue
                except Exception:
                    # transient server-side refusal (e.g. "lease not found"
                    # racing an in-flight relocation): retry inside the TTL
                    # budget before giving the lease up for dead
                    if time.monotonic() - self._last_ok >= self.ttl:
                        logger.warning(
                            "lease %d keep-alive failed past its %.1fs TTL "
                            "budget; lost", self.lease_id, self.ttl)
                        self._mark_lost()
                        return
                    await asyncio.sleep(retry_sleep)

    async def revoke(self) -> None:
        if self._task:
            self._task.cancel()
        # deregister first so a concurrent resync can't resurrect it
        self.client._lease_handles.discard(self)
        try:
            await self.client.revoke(self.lease_id)
        except Exception:
            pass


def replay_registry(client: Any, attr: str, factory: Callable[[], Any],
                    replay: Callable[[Any], Awaitable[None]]) -> Any:
    """Owner-replay registry cached on ``client`` under ``attr``, with ONE
    resync hook replaying its contents after every reconnect.

    Handles are constructed per call-site (kv buckets, model registrations),
    so a hook per handle would accumulate on the client forever and replay
    superseded state; a shared registry gives replace-not-accumulate
    semantics. Only the first caller's ``replay`` is attached; ``client``
    may be any duck-typed store — no hook on ones without resync support."""
    reg = getattr(client, attr, None)
    if reg is None:
        reg = factory()
        setattr(client, attr, reg)
        if hasattr(client, "add_resync_hook"):
            async def _replay_hook() -> None:
                await replay(reg)

            client.add_resync_hook(_replay_hook)
    return reg


class CoordClient:
    """Async client for the Coordinator, with a supervised connection.

    A coordinator crash/restart is transparent to consumers (parity with how
    the reference's etcd/NATS clients survive server restarts):

    - on disconnect, in-flight calls fail fast with ``ConnectionError`` but
      watches, subscriptions and leases are KEPT; a background task retries
      the connection with decorrelated-jitter backoff;
    - on reconnect, a **resync protocol** runs: live leases are probed and
      re-granted (new ids) where the server lost them, registered *resync
      hooks* replay owner state (instance registrations, model cards,
      barrier check-ins), every watch re-scans its prefix and diffs against
      its last-known state to synthesize put/delete deltas, and event
      subscriptions are re-established;
    - ``closed`` now means *permanently* closed: ``close()`` was called, or
      the reconnect give-up window (``DYN_COORD_RECONNECT_MAX_S``) elapsed.

    Knobs (env, or constructor overrides): ``DYN_COORD_RECONNECT`` (0
    disables supervision and restores fail-on-first-disconnect),
    ``DYN_COORD_RECONNECT_BASE_S`` / ``_CAP_S`` (backoff),
    ``DYN_COORD_RECONNECT_MAX_S`` (0 = retry forever) and
    ``DYN_COORD_RESYNC_GRACE_S`` (stale-read window before a key missing
    from the post-restart scan is reported deleted).
    """

    def __init__(self, address: str, reconnect: Optional[bool] = None,
                 reconnect_base_s: Optional[float] = None,
                 reconnect_cap_s: Optional[float] = None,
                 reconnect_max_s: Optional[float] = None,
                 resync_grace_s: Optional[float] = None,
                 resync_timeout_s: Optional[float] = None):
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        env = os.environ.get
        self.reconnect = (env("DYN_COORD_RECONNECT", "1").lower()
                          not in ("0", "false", "no")
                          if reconnect is None else reconnect)
        self.reconnect_base_s = (float(env("DYN_COORD_RECONNECT_BASE_S",
                                           "0.05"))
                                 if reconnect_base_s is None
                                 else reconnect_base_s)
        self.reconnect_cap_s = (float(env("DYN_COORD_RECONNECT_CAP_S", "2.0"))
                                if reconnect_cap_s is None else reconnect_cap_s)
        self.reconnect_max_s = (float(env("DYN_COORD_RECONNECT_MAX_S", "0"))
                                if reconnect_max_s is None else reconnect_max_s)
        self.resync_grace_s = (float(env("DYN_COORD_RESYNC_GRACE_S", "5.0"))
                               if resync_grace_s is None else resync_grace_s)
        self.resync_timeout_s = (float(env("DYN_COORD_RESYNC_TIMEOUT_S",
                                           "30.0"))
                                 if resync_timeout_s is None
                                 else resync_timeout_s)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._rids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watches: Dict[int, Watch] = {}
        self._subs: Dict[int, Subscription] = {}
        self._lease_handles: set = set()
        self._resync_hooks: List[Callable] = []
        # events/messages that raced ahead of watch/subscription registration
        # (the server's response and a first event can share one TCP segment)
        self._orphan_events: Dict[int, list] = {}
        self._orphan_msgs: Dict[int, list] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._reconnect_task: Optional[asyncio.Task] = None
        self._resync_task: Optional[asyncio.Task] = None
        # what the last resync attempt re-registered on the live connection;
        # re-swept for late cancels once connected. Objects, not ids: a
        # wiped server's restarted id counter reuses NUMBERS, so an id alone
        # cannot say whose registration it names
        self._resync_watch_objs: List[Watch] = []
        self._resync_sub_objs: List[Subscription] = []
        self._deferred: set = set()  # grace-delayed delete tasks
        self._wlock: Optional[asyncio.Lock] = None
        self._connected = asyncio.Event()
        self._closing = False
        self._disconnected_at: Optional[float] = None
        self._server_epoch: Optional[int] = None
        self._conn_lost_flag = False  # current connection died (see below)
        self.closed = asyncio.Event()
        # observability (exported via http/metrics.CoordClientMetrics)
        self.reconnects_total = 0
        self.resyncs_total = 0
        self.last_outage_s = 0.0

    # -- connection supervision --------------------------------------------

    @property
    def connected(self) -> bool:
        return self._connected.is_set() and not self.closed.is_set()

    async def wait_connected(self, timeout: Optional[float] = None) -> None:
        """Block until the connection is up and resynced; raises
        ``ConnectionError`` when the client is permanently closed (or on
        ``timeout``)."""
        if self.closed.is_set():
            raise ConnectionError("coordinator client closed")
        if self._connected.is_set():
            return
        conn = asyncio.ensure_future(self._connected.wait())
        clo = asyncio.ensure_future(self.closed.wait())
        try:
            done, _ = await asyncio.wait({conn, clo}, timeout=timeout,
                                         return_when=asyncio.FIRST_COMPLETED)
            if conn in done:
                return
            if clo in done:
                raise ConnectionError("coordinator client closed")
            raise ConnectionError(
                "timed out waiting for coordinator reconnect")
        finally:
            conn.cancel()
            clo.cancel()

    def add_resync_hook(self, hook: Callable) -> Callable:
        """Register an async callable invoked after leases are re-established
        on every reconnect, BEFORE watches are re-scanned — re-put owner
        state (instance registrations, model cards, barrier keys) here so
        the resync diff already sees it. Returns ``hook`` for symmetry with
        ``remove_resync_hook``."""
        self._resync_hooks.append(hook)
        return hook

    def remove_resync_hook(self, hook: Callable) -> None:
        try:
            self._resync_hooks.remove(hook)
        except ValueError:
            pass

    async def connect(self) -> "CoordClient":
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._wlock = asyncio.Lock()
        self._connected.set()
        self._reader_task = asyncio.create_task(self._read_loop(self._reader))
        # baseline boot epoch: resync compares against it to tell a blipped
        # server (state intact, probe leases) from a fresh one (re-grant)
        try:
            # bounded like resync: a server that accepts TCP but never
            # answers must not hang startup forever
            self._server_epoch = (await asyncio.wait_for(
                self._call("ping"),
                timeout=self.resync_timeout_s or None)).get("epoch")
        except BaseException:
            # a half-opened connection (server died mid-handshake) must not
            # leave a background reconnect loop running on an object the
            # caller is about to abandon — connect() either works or is void
            await self.close()
            raise
        return self

    async def close(self) -> None:
        self._closing = True
        if self._reconnect_task is not None:
            await reap_task(self._reconnect_task)
            self._reconnect_task = None
        await reap_task(self._reader_task)
        if self._writer:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass
        self._finalize_closed()

    async def __aenter__(self) -> "CoordClient":
        return await self.connect()

    async def __aexit__(self, *exc) -> None:
        await self.close()

    def _dispatch_frame(self, frame: Dict[str, Any]) -> None:
        if "rid" in frame and frame["rid"] is not None:
            fut = self._pending.pop(frame["rid"], None)
            if fut and not fut.done():
                fut.set_result(frame)
        elif frame.get("evt") == "watch":
            ev = WatchEvent(frame["type"], frame["key"],
                            frame.get("value"), frame.get("lease", 0))
            w = self._watches.get(frame["watch_id"])
            if w:
                w._deliver(ev)
            else:
                buf = self._orphan_events.setdefault(frame["watch_id"], [])
                if len(buf) < 10_000:
                    buf.append(ev)
        elif frame.get("evt") == "msg":
            item = (frame["subject"], frame["payload"])
            s = self._subs.get(frame["sub_id"])
            if s:
                s.queue.put_nowait(item)
            else:
                buf = self._orphan_msgs.setdefault(frame["sub_id"], [])
                if len(buf) < 10_000:
                    buf.append(item)

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                self._dispatch_frame(frame)
        except (ConnectionError, OSError):
            pass  # CancelledError must propagate (see utils/aio.reap_task)
        finally:
            self._on_conn_lost(reader)

    def _on_conn_lost(self, reader: asyncio.StreamReader) -> None:
        if reader is not self._reader:
            return  # a stale loop from a superseded connection
        # a still-running reconnect task (below we early-return rather than
        # double-supervise) must not declare success on this dead
        # connection: it re-checks this flag after its resync completes
        self._conn_lost_flag = True
        self._connected.clear()
        # in-flight calls fail fast (callers retry or surface the outage);
        # orphan buffers are connection-scoped — clear them so events from a
        # dead watch registration can't accumulate forever (nor leak into a
        # reconnected session whose server assigns fresh ids)
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(
                    ConnectionError("coordinator connection lost"))
        self._pending.clear()
        self._orphan_events.clear()
        self._orphan_msgs.clear()
        # registrations from the dead connection need no undo
        self._resync_watch_objs = []
        self._resync_sub_objs = []
        # a pending grace-window delete must not fire while offline: no
        # events arrive to refute it (the owner may have re-put the key on
        # the server, invisibly to us), and the next resync re-diffs anyway
        for t in list(self._deferred):
            t.cancel()
        if self._closing or not self.reconnect:
            self._finalize_closed()
            return
        if self._reconnect_task is not None and not self._reconnect_task.done():
            return  # supervision already running; it retries on its own
        self._disconnected_at = time.monotonic()
        logger.warning("coordinator connection %s:%d lost; reconnecting",
                       self.host, self.port)
        self._reconnect_task = asyncio.create_task(self._reconnect_loop())

    def _finalize_closed(self) -> None:
        """Permanent teardown: fail everything and end every iterator."""
        if self.closed.is_set():
            return
        self.closed.set()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(
                    ConnectionError("coordinator connection lost"))
        self._pending.clear()
        for t in list(self._deferred):
            t.cancel()
        for w in self._watches.values():
            w.queue.put_nowait(None)
        for s in self._subs.values():
            s.queue.put_nowait(None)

    async def _reconnect_loop(self) -> None:
        sleep_s = self.reconnect_base_s

        def backoff() -> float:
            # a fleet of clients must not stampede the restarted
            # coordinator in lockstep (same helper as push_router failover)
            return decorrelated_jitter(sleep_s, self.reconnect_base_s,
                                       self.reconnect_cap_s)

        while True:
            if self._closing:
                return
            down_for = time.monotonic() - (self._disconnected_at
                                           or time.monotonic())
            if self.reconnect_max_s and down_for > self.reconnect_max_s:
                logger.error(
                    "giving up on coordinator %s:%d after %.1fs offline",
                    self.host, self.port, down_for)
                self._finalize_closed()
                return
            try:
                # bounded attempt: a blackholed address must not park the
                # loop for the kernel connect timeout (minutes) — backoff
                # pacing and the give-up window only advance between tries
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(self.host, self.port),
                    timeout=max(self.reconnect_cap_s, 1.0))
            except (OSError, asyncio.TimeoutError):
                sleep_s = backoff()
                await asyncio.sleep(sleep_s)
                continue
            old_writer, self._reader, self._writer = \
                self._writer, reader, writer
            self._conn_lost_flag = False  # tracking the NEW connection now
            if old_writer is not None:
                try:
                    old_writer.close()
                except Exception:
                    pass
            self._reader_task = asyncio.create_task(self._read_loop(reader))
            try:
                # bounded: a server that accepts the connection but never
                # answers (frozen / blackholed half-open) must not park
                # supervision forever — the give-up window is only checked
                # between attempts. wait_for runs _resync in its OWN task,
                # so _call's disconnected-fail-fast exemption tracks it.
                # attempts, not completions: divergence from
                # reconnects_total below is the retried-resync signal
                self.resyncs_total += 1
                self._resync_task = asyncio.ensure_future(self._resync())
                try:
                    await asyncio.wait_for(self._resync_task,
                                           timeout=self.resync_timeout_s
                                           or None)
                finally:
                    self._resync_task = None
                if self._conn_lost_flag:
                    # the connection died during resync, after answering the
                    # last call — the read loop's _on_conn_lost deferred to
                    # this (still-running) task, so the retry is on us:
                    # declaring success would wedge the client forever
                    raise ConnectionError("connection lost during resync")
            except Exception as e:  # noqa: BLE001 — any resync failure
                # (connection died again, server error) restarts supervision
                logger.warning("coordinator resync failed (%s); retrying", e)
                try:
                    writer.close()
                except Exception:
                    pass
                sleep_s = backoff()
                await asyncio.sleep(sleep_s)
                continue
            outage = time.monotonic() - (self._disconnected_at
                                         or time.monotonic())
            self._disconnected_at = None
            self.reconnects_total += 1
            self.last_outage_s = outage
            self._connected.set()
            # re-sweep for cancels that raced the resync after its own
            # cancelled-sweep passed: their unwatch RPC failed fast while
            # the resync held the connection, but the registration is LIVE.
            # Each object's current id was issued to IT on this connection,
            # so this can never hit a sibling (unlike sweeping raw ids —
            # a wiped server's restarted counter reuses numbers); an id the
            # resync's own sweep already dropped errors harmlessly
            for w in self._resync_watch_objs:
                if w.cancelled:
                    self._orphan_events.pop(w.watch_id, None)
                    try:
                        await self._call("unwatch", watch_id=w.watch_id)
                    except Exception:
                        pass
            self._resync_watch_objs = []
            for s in self._resync_sub_objs:
                if s.cancelled:
                    self._orphan_msgs.pop(s.sub_id, None)
                    try:
                        await self._call("unsubscribe", sub_id=s.sub_id)
                    except Exception:
                        pass
            self._resync_sub_objs = []
            logger.info(
                "coordinator %s:%d reconnected after %.2fs outage; resynced "
                "%d lease(s), %d watch(es), %d subscription(s), %d hook(s)",
                self.host, self.port, outage, len(self._lease_handles),
                len(self._watches), len(self._subs),
                len(self._resync_hooks))
            return

    async def _resync(self) -> None:
        """Rebuild server-side session state on a fresh connection.

        Order matters: leases first (hooks attach keys to them), then the
        resync hooks (owners re-put their state so the watch re-scan below
        already includes it), then watches (prefix re-scan + diff against
        each watch's last-known state), then subscriptions."""
        # 0. boot epoch: a changed epoch means a fresh/wiped process whose
        # restarted id counter may have RE-ISSUED our old lease ids to other
        # clients — an existence probe would then adopt a foreign lease
        # (and die with it when its real owner revokes). Same epoch means
        # the server's state survived and probing is trustworthy.
        epoch = (await self._call("ping")).get("epoch")
        fresh_server = epoch != self._server_epoch
        # 1. leases: probe-or-regrant. A lease that survived the outage
        # (connection blip, or restart without state wipe within TTL) keeps
        # its id — zero churn; one the server lost is re-granted under a
        # fresh id and the handle relocates in place.
        for lease in list(self._lease_handles):
            if not fresh_server:
                try:
                    await self._call("keepalive", lease=lease.lease_id)
                    continue
                except ConnectionError:
                    raise
                except Exception:
                    pass  # lease not found -> re-grant below
            resp = await self._call("grant_lease", ttl=lease.ttl)
            lease._relocate(int(resp["lease"]))
        # only now: a retry after a partial lease pass must still see the
        # epoch as fresh and re-grant the remainder
        self._server_epoch = epoch
        # 2. resync hooks: replay owner state under the fresh leases
        for hook in list(self._resync_hooks):
            try:
                await hook()
            except (ConnectionError, OSError):
                raise
            except Exception:
                logger.exception("coordinator resync hook failed")
        # 3. watches: re-register, then diff the fresh snapshot against the
        # watcher's last-known state, synthesizing deltas. A state-wiped
        # server restarts its id counter, so a fresh id routinely collides
        # with a sibling's OLD id: detach the registry up front (events that
        # arrive mid-registration park in the orphan buffer instead of
        # hitting a stale same-id entry) and swap the new map in whole.
        watches = [w for w in self._watches.values() if not w.cancelled]
        self._watches = {}
        scans = []
        try:
            for w in watches:
                resp = await self._call("watch_prefix", prefix=w.prefix)
                w.watch_id = int(resp["watch_id"])
                scans.append(resp.get("items", []))
        except BaseException:
            # keep the watch set for the retry; ids from the failed attempt
            # are dead and may collide, so key uniquely (the next attempt
            # iterates values() and re-registers by prefix)
            self._watches = {-i: w for i, w in enumerate(watches, 1)}
            raise
        self._watches = {w.watch_id: w for w in watches if not w.cancelled}
        self._resync_watch_objs = watches  # for the post-connect re-sweep
        for w, items in zip(watches, scans):
            if w.cancelled:
                continue
            # diff first, then the live events that raced the registration
            # (the server's response and a first event can share one TCP
            # segment — same race watch_prefix() drains after registering)
            self._resync_watch(w, items)
            for ev in self._orphan_events.pop(w.watch_id, []):
                w._deliver(ev)
        for w in watches:
            if w.cancelled:
                # cancelled while this resync was re-registering it: the
                # cancel's own unwatch went to the dead connection, so undo
                # the fresh registration or the server streams the prefix
                # into a dropped id forever
                await self._call("unwatch", watch_id=w.watch_id)
                self._orphan_events.pop(w.watch_id, None)
        # 4. subscriptions: re-subscribe under fresh server-side ids (same
        # detach/swap/drain dance as watches)
        subs = [s for s in self._subs.values() if not s.cancelled]
        self._subs = {}
        try:
            for s in subs:
                resp = await self._call("subscribe", subject=s.subject,
                                        queue_group=s.queue_group)
                s.sub_id = int(resp["sub_id"])
        except BaseException:
            self._subs = {-i: s for i, s in enumerate(subs, 1)}
            raise
        self._subs = {s.sub_id: s for s in subs if not s.cancelled}
        self._resync_sub_objs = subs  # for the post-connect re-sweep
        # drain BEFORE the sweep's awaits: once the swap is live, new
        # messages go straight to the queues, and a message orphaned during
        # re-registration must not be delivered after one that arrived later
        for s in self._subs.values():
            for item in self._orphan_msgs.pop(s.sub_id, []):
                s.queue.put_nowait(item)
        for s in subs:
            if s.cancelled:  # see the watch sweep above
                await self._call("unsubscribe", sub_id=s.sub_id)
                self._orphan_msgs.pop(s.sub_id, None)

    def _resync_watch(self, w: Watch, items: List[Dict[str, Any]]) -> None:
        new = {i["key"]: (i["value"], i.get("lease", 0)) for i in items}
        old = dict(w.state)
        for key in sorted(new):
            value, lease_id = new[key]
            prev = old.get(key)
            if prev is None or prev[0] != value or prev[1] != lease_id:
                w._deliver(WatchEvent("put", key, value, lease_id))
        missing = {k: old[k] for k in old if k not in new}
        if not missing:
            return
        if self.resync_grace_s <= 0:
            for key, (_value, lease_id) in sorted(missing.items()):
                w._deliver(WatchEvent("delete", key, None, lease_id))
            return
        # stale-read window: a key absent right after a restart is usually a
        # peer that simply hasn't resynced yet (its re-put is racing ours) —
        # report the delete only if it stays gone past the grace window, so
        # consumers (instance discovery, model cards) never flap through
        # empty during a restart
        task = asyncio.create_task(self._deferred_deletes(w, missing))
        self._deferred.add(task)
        task.add_done_callback(self._deferred.discard)

    async def _deferred_deletes(
            self, w: Watch,
            missing: Dict[str, Tuple[Optional[bytes], int]]) -> None:
        await asyncio.sleep(self.resync_grace_s)
        for key, stamp in sorted(missing.items()):
            # identity check: a re-put (even of an equal value) stored a new
            # tuple; only untouched-since-the-outage keys get the delete
            if w.state.get(key) is stamp and self._watches.get(w.watch_id) is w:
                w._deliver(WatchEvent("delete", key, None, stamp[1]))

    async def _call(self, op: str, **kw: Any) -> Dict[str, Any]:
        if self._writer is None:
            raise ConnectionError("not connected")
        if self.closed.is_set():
            raise ConnectionError("coordinator connection lost")
        if (not self._connected.is_set()
                and asyncio.current_task() is not self._resync_task):
            # disconnected: fail fast so callers keep serving from cached
            # state instead of hanging on a dead socket (the resync task
            # itself is exempt — it runs before connected is set)
            raise ConnectionError("coordinator disconnected "
                                  "(reconnect in progress)")
        rid = next(self._rids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        frame = {"op": op, "rid": rid, **kw}
        async with self._wlock:
            await send_frame(self._writer, frame)
        # A dead connection may accept the write (TCP buffering) while the
        # read loop has already torn down — or tears down after we register
        # the future but before the reply. Racing against `closed` turns
        # every such case into a prompt ConnectionError instead of a hang.
        closed_wait = asyncio.ensure_future(self.closed.wait())
        try:
            done, _ = await asyncio.wait({fut, closed_wait},
                                         return_when=asyncio.FIRST_COMPLETED)
            if fut not in done:
                self._pending.pop(rid, None)
                raise ConnectionError("coordinator connection lost")
            resp = fut.result()
        finally:
            closed_wait.cancel()
            if not fut.done():
                # cancelled from outside (e.g. a wait_for-bounded caller):
                # drop the entry or a half-open connection accrues one per
                # attempt; the read loop tolerates replies to unknown rids
                self._pending.pop(rid, None)
        if not resp.get("ok"):
            raise RuntimeError(f"coordinator {op} failed: {resp.get('error')}")
        return resp

    # -- KV API ------------------------------------------------------------

    async def put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        await self._call("put", key=key, value=value, lease=lease_id)

    async def put_if_absent(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        resp = await self._call("put_if_absent", key=key, value=value, lease=lease_id)
        return bool(resp["created"])

    async def get(self, key: str) -> Optional[bytes]:
        resp = await self._call("get", key=key)
        return resp.get("value")

    async def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        resp = await self._call("get_prefix", prefix=prefix)
        return [(i["key"], i["value"]) for i in resp["items"]]

    async def delete(self, key: str) -> int:
        return (await self._call("delete", key=key))["deleted"]

    async def delete_prefix(self, prefix: str) -> int:
        return (await self._call("delete_prefix", prefix=prefix))["deleted"]

    # -- leases ------------------------------------------------------------

    async def grant_lease(self, ttl: float = 10.0, keepalive: bool = True) -> Lease:
        resp = await self._call("grant_lease", ttl=ttl)
        lease = Lease(self, resp["lease"], resp["ttl"])
        self._lease_handles.add(lease)  # resync re-probes / re-grants it
        if keepalive:
            lease.start_keepalive()
        return lease

    async def keepalive(self, lease_id: int) -> None:
        await self._call("keepalive", lease=lease_id)

    async def revoke(self, lease_id: int) -> None:
        await self._call("revoke", lease=lease_id)

    # -- watches -----------------------------------------------------------

    async def watch_prefix(self, prefix: str) -> Watch:
        resp = await self._call("watch_prefix", prefix=prefix)
        w = Watch(self, resp["watch_id"], resp.get("items", []), prefix=prefix)
        self._watches[w.watch_id] = w
        # drain events that arrived between the server registering the watch
        # and us registering the Watch object (no await between these lines)
        for ev in self._orphan_events.pop(w.watch_id, []):
            w._deliver(ev)
        return w

    async def unwatch(self, watch_id: int) -> None:
        self._watches.pop(watch_id, None)
        self._orphan_events.pop(watch_id, None)  # drop in-flight stragglers
        try:
            await self._call("unwatch", watch_id=watch_id)
        except ConnectionError:
            pass  # disconnected: the dead server session is gone anyway,
            # and the resync protocol won't re-establish a popped watch; a
            # cancel racing a mid-flight resync (which may already hold a
            # LIVE registration for this watch) is undone by the
            # post-connect re-sweep of _resync_watch_objs

    # -- pub/sub -----------------------------------------------------------

    async def publish(self, subject: str, payload: bytes) -> int:
        return (await self._call("publish", subject=subject, payload=payload))["delivered"]

    async def subscribe(self, subject: str,
                        queue_group: Optional[str] = None) -> Subscription:
        resp = await self._call("subscribe", subject=subject, queue_group=queue_group)
        s = Subscription(self, resp["sub_id"], subject=subject,
                         queue_group=queue_group)
        self._subs[s.sub_id] = s
        for item in self._orphan_msgs.pop(s.sub_id, []):
            s.queue.put_nowait(item)
        return s

    async def unsubscribe(self, sub_id: int) -> None:
        self._subs.pop(sub_id, None)
        self._orphan_msgs.pop(sub_id, None)
        try:
            await self._call("unsubscribe", sub_id=sub_id)
        except ConnectionError:
            pass  # popped subs are not resynced; see unwatch for the
            # mid-resync race the post-connect re-sweep covers

    # -- object store ------------------------------------------------------
    # (reference: NATS object store carrying model-card artifacts,
    # ``transports/nats.rs:123-176``.) Implemented client-side on the KV
    # plane: ``obj/{bucket}/{name}/meta`` + ``/c{i}`` chunk keys. Chunking
    # keeps any single KV value (and coordinator frame) small even for
    # multi-MB artifacts like inlined tokenizers.

    OBJ_CHUNK = 1 << 20  # 1 MiB per chunk

    @staticmethod
    def _obj_prefix(bucket: str, name: str) -> str:
        return f"obj/{bucket}/{name}/"

    async def obj_put(self, bucket: str, name: str, data: bytes,
                      lease_id: int = 0) -> int:
        """Store an object as chunked KV entries; returns chunk count.
        Attach a lease to make the object vanish with its owner."""
        prefix = self._obj_prefix(bucket, name)
        n = max(1, -(-len(data) // self.OBJ_CHUNK))
        for i in range(n):
            chunk = data[i * self.OBJ_CHUNK:(i + 1) * self.OBJ_CHUNK]
            await self.put(f"{prefix}c{i:06d}", chunk, lease_id=lease_id)
        import json as _json
        await self.put(f"{prefix}meta",
                       _json.dumps({"size": len(data),
                                    "chunks": n}).encode(),
                       lease_id=lease_id)
        return n

    async def obj_get(self, bucket: str, name: str) -> Optional[bytes]:
        """Fetch a stored object, or None when absent/incomplete."""
        prefix = self._obj_prefix(bucket, name)
        meta_raw = await self.get(f"{prefix}meta")
        if meta_raw is None:
            return None
        import json as _json
        meta = _json.loads(meta_raw)
        parts = []
        for i in range(int(meta["chunks"])):
            c = await self.get(f"{prefix}c{i:06d}")
            if c is None:
                return None  # torn write/expiry mid-read
            parts.append(c)
        data = b"".join(parts)
        return data if len(data) == int(meta["size"]) else None

    async def obj_delete(self, bucket: str, name: str) -> int:
        return await self.delete_prefix(self._obj_prefix(bucket, name))

    # -- work queues -------------------------------------------------------

    async def queue_push(self, queue: str, payload: bytes) -> int:
        """Push one job; returns post-push depth (0 = a puller took it)."""
        return (await self._call("queue_push", queue=queue,
                                 payload=payload))["depth"]

    async def queue_pull(self, queue: str,
                         timeout: Optional[float] = None
                         ) -> Optional[Tuple[bytes, float]]:
        """Pull the oldest job, parking server-side until one arrives;
        returns (payload, age_s) — ``age_s`` is time spent queued by the
        coordinator's clock — or None on timeout.

        Timeout protocol: the parked pull is explicitly cancelled
        (``queue_cancel``). If the cancel races a delivery already in
        flight, the client waits for it and pushes the job BACK, so a
        timed-out puller can never swallow a job. External CANCELLATION of
        this coroutine fires the same best-effort server-side cancel so a
        parked pull on a still-live connection cannot swallow a later push
        into an orphaned future."""
        if self._writer is None:
            raise ConnectionError("not connected")
        if self.closed.is_set() or not self._connected.is_set():
            raise ConnectionError("coordinator connection lost")
        rid = next(self._rids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._wlock:
            await send_frame(self._writer,
                             {"op": "queue_pull", "rid": rid, "queue": queue})
        closed_wait = asyncio.ensure_future(self.closed.wait())
        try:
            done, _ = await asyncio.wait(
                {fut, closed_wait}, timeout=timeout,
                return_when=asyncio.FIRST_COMPLETED)
            if fut in done:
                resp = fut.result()
                return resp["payload"], float(resp.get("age_s", 0.0))
            if closed_wait in done:
                self._pending.pop(rid, None)
                raise ConnectionError("coordinator connection lost")
            # timeout: cancel the parked pull server-side
            resp = await self._call("queue_cancel", queue=queue,
                                    pull_rid=rid)
            if not resp.get("cancelled", False):
                # delivery already in flight — take it and give it back
                payload = (await fut)["payload"]
                await self.queue_push(queue, payload)
            self._pending.pop(rid, None)
            return None
        except asyncio.CancelledError:
            self._pending.pop(rid, None)
            if not self.closed.is_set():
                # fire-and-forget: unpark server-side (conn teardown covers
                # the closing case)
                asyncio.get_running_loop().create_task(
                    self._queue_cancel_quiet(queue, rid))
            raise
        finally:
            closed_wait.cancel()

    async def _queue_cancel_quiet(self, queue: str, pull_rid: int) -> None:
        try:
            await self._call("queue_cancel", queue=queue, pull_rid=pull_rid)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass

    async def queue_depth(self, queue: str) -> Tuple[int, int]:
        """(queued jobs, parked pullers) — the planner's prefill-pressure
        signal (reference: JetStream consumer info on the prefill queue)."""
        resp = await self._call("queue_depth", queue=queue)
        return resp["depth"], resp.get("pullers", 0)

    async def ping(self) -> float:
        return (await self._call("ping"))["time"]


def main() -> None:
    """Standalone coordinator process (``python -m
    dynamo_tpu.runtime.coordinator --port 6650``).

    Running the control plane as its own process is what makes the
    crash/restart drills in docs/deployment.md ("Control-plane outages")
    real: kill -9 this and start a fresh one on the same port — every
    supervised ``CoordClient`` reconnects and resyncs its state."""
    import argparse

    from dynamo_tpu.utils.logging import configure_logging

    parser = argparse.ArgumentParser(description="dynamo_tpu coordinator")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=6650)
    args = parser.parse_args()
    configure_logging()

    async def _run() -> None:
        coord = await Coordinator(host=args.host, port=args.port).start()
        print(f"coordinator listening on {coord.address}", flush=True)
        try:
            await asyncio.Event().wait()  # serve until killed
        finally:
            await coord.stop()

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()


__all__ = ["Coordinator", "CoordClient", "Watch", "WatchEvent", "Subscription",
           "Lease"]
