"""Bulk data plane: raw-socket block transfer beside the RPC plane.

The reference moves KV blocks on a DEDICATED transport (NIXL RDMA —
``lib/llm/src/block_manager/block/transfer/nixl.rs``, the ``nixl_connect``
SDK) rather than its NATS/TCP request plane, because request-plane framing
tops out far below link speed. The same is true here: asyncio stream
framing measures ~1.5 GB/s on loopback while plain sockets do ~6 GB/s. So
bulk KV bytes get their own tiny protocol on blocking sockets in worker
threads, and the RPC plane keeps carrying control traffic.

Protocol (all integers big-endian):

  request:   [u32 len][msgpack {"endpoint": str, "payload": any}]
  response:  frames of [u32 meta_len][msgpack meta][u32 raw_len][raw bytes]
             until a frame whose meta has "final": true (raw_len 0).
             Handler errors arrive as meta {"error": str}.

A server handler is a SYNCHRONOUS callable ``handler(payload) ->
Iterable[(meta_dict, buffer_or_None)]`` run in the connection's thread;
use ``asyncio.run_coroutine_threadsafe`` inside the handler to coordinate
with an event loop (the KV exporter does, via ``engine.run_exclusive``).

The receive side reads raw bytes with ``recv_into`` straight into one
preallocated buffer per frame — one copy off the kernel, no reassembly.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from dynamo_tpu.runtime.codec import (
    MAX_FRAME,
    byte_view,
    buf_get as _buf_get,
    pack,
    release_buffer,
    unpack,
)

logger = logging.getLogger(__name__)

_U32 = struct.Struct(">I")

BulkHandler = Callable[[Any], Iterable[Tuple[Dict[str, Any], Optional[Any]]]]

# Explicit socket buffer sizing, both ends: kernel autotuning starts tiny
# and takes tens of MB to ramp (the cold-connection penalty measured
# below); asking for generous buffers up front starts the connection near
# its steady rate. Best-effort — a kernel may clamp (rmem_max/wmem_max).
_SOCK_BUF_BYTES = 8 * 1024 * 1024


def _tune_socket(s: socket.socket) -> None:
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, _SOCK_BUF_BYTES)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, _SOCK_BUF_BYTES)
    except OSError:
        pass
    if s.family != socket.AF_UNIX:  # TCP of either family
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # pooled connections sit idle between fetches: OS keepalive
            # probes keep NAT/conntrack state alive and surface a dead
            # peer as a pool-eviction instead of a stalled fetch
            s.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        except OSError:
            pass


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    got = 0
    n = len(view)
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("bulk peer closed mid-frame")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return bytes(buf)


# sanity cap on msgpack meta parts: metas are block lists + a few scalars
# (well under a MB even at thousands of blocks); a corrupted length byte
# must not provoke a multi-hundred-MB allocation before the desync is
# noticed
_MAX_META = 16 * 1024 * 1024


def _read_u32(sock: socket.socket, limit: int = MAX_FRAME) -> int:
    (v,) = _U32.unpack(_recv_exact(sock, 4))
    if v > limit:
        raise ValueError(f"bulk frame length {v} exceeds cap {limit}")
    return v


class BulkServer:
    """Accept-loop in a daemon thread; one thread per connection.

    Connections are sequential request/response — no stream multiplexing.
    A client that wants parallel fetches opens parallel connections.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 unix_path: Optional[str] = None, ident: str = ""):
        self.host = host
        self.port = port
        # identity token (the worker's instance/lease id): clients send the
        # ident they EXPECT with each request, so a connection that landed
        # on the wrong server (e.g. a same-path unix socket of another
        # colocated worker after a PID collision) errors instead of
        # silently serving misses
        self.ident = ident
        # same-host transfers ride AF_UNIX when offered: loopback TCP in
        # virtualized kernels can cap near 1 GB/s while unix sockets do
        # ~6 GB/s (measured here) — and colocated prefill/decode workers
        # are the common single-host disagg topology
        self.unix_path = unix_path
        # built-in warmup endpoint: streams zeros so clients can ramp a
        # fresh connection's kernel buffers before the first real fetch
        self._handlers: Dict[str, BulkHandler] = {"_warm": _warm_handler}
        self._socks: List[socket.socket] = []
        self._threads: List[threading.Thread] = []
        self._stopping = threading.Event()
        self._conns: set = set()
        self.bytes_sent = 0  # diagnostics

    def register(self, endpoint: str, handler: BulkHandler) -> None:
        self._handlers[endpoint] = handler

    def start(self) -> "BulkServer":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(16)
        self.port = s.getsockname()[1]
        self._socks.append(s)
        if self.unix_path:
            import os
            try:
                os.unlink(self.unix_path)
            except FileNotFoundError:
                pass
            u = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            u.bind(self.unix_path)
            u.listen(16)
            self._socks.append(u)
        for sk in self._socks:
            t = threading.Thread(target=self._accept_loop, args=(sk,),
                                 name="bulk-accept", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stopping.set()
        for sk in self._socks:
            try:
                sk.close()
            except OSError:
                pass
        if self.unix_path:
            import os
            try:
                os.unlink(self.unix_path)
            except OSError:
                pass
        for c in list(self._conns):
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)

    @property
    def address(self) -> str:
        """Comma-separated endpoints, unix (if any) first: clients try the
        same-host fast path and fall back to TCP."""
        tcp = f"{self.host}:{self.port}"
        return f"unix:{self.unix_path},{tcp}" if self.unix_path else tcp

    def _accept_loop(self, listen_sock: socket.socket) -> None:
        while not self._stopping.is_set():
            try:
                conn, _addr = listen_sock.accept()
            except OSError:
                return  # socket closed
            _tune_socket(conn)
            self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="bulk-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stopping.is_set():
                try:
                    req_len = _read_u32(conn)
                except (ConnectionError, ValueError, OSError):
                    return
                req = unpack(_recv_exact(conn, req_len))
                self._handle_one(conn, req)
        except (ConnectionError, OSError):
            pass
        except Exception:
            logger.exception("bulk connection handler died")
        finally:
            self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _send_frame(self, conn: socket.socket, meta: Dict[str, Any],
                    raw: Optional[Any]) -> None:
        mb = pack(meta)
        if raw is None:
            conn.sendall(_U32.pack(len(mb)) + mb + _U32.pack(0))
            return
        view = byte_view(raw)
        conn.sendall(_U32.pack(len(mb)) + mb + _U32.pack(view.nbytes))
        conn.sendall(view)  # zero-copy from the source buffer to the kernel
        self.bytes_sent += view.nbytes

    def _handle_one(self, conn: socket.socket, req: Dict[str, Any]) -> None:
        want = req.get("ident", "")
        if want and self.ident and want != self.ident:
            self._send_frame(conn, {"final": True,
                                    "error": f"bulk ident mismatch: "
                                             f"server={self.ident} "
                                             f"requested={want}"}, None)
            return
        handler = self._handlers.get(req.get("endpoint", ""))
        if handler is None:
            self._send_frame(conn, {"final": True,
                                    "error": "no such bulk endpoint"}, None)
            return
        try:
            for meta, raw in handler(req.get("payload")):
                self._send_frame(conn, meta, raw)
        except Exception as e:  # noqa: BLE001 — relay to the peer
            logger.exception("bulk handler error")
            try:
                self._send_frame(conn, {"final": True, "error": str(e)}, None)
            except (ConnectionError, OSError):
                pass
            return
        self._send_frame(conn, {"final": True}, None)


def _connect(address: str, timeout: float) -> socket.socket:
    """Connect to one endpoint of a bulk address. A comma-separated list is
    tried in order — the unix endpoint (listed first by the server) only
    works on the same machine, so remote clients naturally fall through to
    TCP."""
    last_err: Optional[Exception] = None
    for ep in address.split(","):
        ep = ep.strip()
        try:
            if ep.startswith("unix:"):
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(timeout)
                s.connect(ep[len("unix:"):])
                _tune_socket(s)
                return s
            host, port = ep.rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=timeout)
            _tune_socket(s)
            return s
        except OSError as e:
            last_err = e
    raise ConnectionError(f"no reachable bulk endpoint in {address!r}: "
                          f"{last_err}")


# The receive-buffer freelist lives in runtime/codec.py (shared with the
# RPC plane's large two-part trailers); ``release_buffer`` is re-exported
# here because bulk consumers import it from this module.


# warmup stream: enough bytes to ramp the kernel's per-connection buffer
# autotuning (the first tens of MB through a fresh socket move at ~1/3 of
# the steady rate on this host class), capped so a misbehaving client
# can't turn the endpoint into a bandwidth sink
_WARM_CHUNK = None  # lazily-built 4 MiB zero buffer, shared by all conns
PREWARM_BYTES = 32 * 1024 * 1024
_WARM_MAX_BYTES = 256 * 1024 * 1024


def _warm_handler(payload):
    global _WARM_CHUNK
    if _WARM_CHUNK is None:
        import numpy as _np
        _WARM_CHUNK = _np.zeros(4 * 1024 * 1024, _np.uint8)
    want = min(int((payload or {}).get("nbytes", PREWARM_BYTES)),
               _WARM_MAX_BYTES)
    sent = 0
    while sent < want:
        n = min(want - sent, _WARM_CHUNK.nbytes)
        yield {"warm": True}, _WARM_CHUNK[:n]
        sent += n


def prewarm(address: str, ident: str = "", nbytes: int = PREWARM_BYTES,
            conns: int = 1, timeout: float = 30.0) -> int:
    """Open ``conns`` fresh connections to ``address``, stream ``nbytes``
    of warmup traffic through each (ramping the kernel's per-connection
    buffer autotuning), and PARK them in the client pool — subsequent
    ``bulk_fetch`` calls to the address skip both the connection setup and
    the cold-buffer penalty. Synchronous (run via a thread from async
    code; see ``prewarm_async``). Returns connections successfully warmed
    and pooled.

    A server without the ``_warm`` endpoint (pre-knob builds) answers with
    a clean error frame: the connection is still pooled — connection reuse
    alone is most of the win."""
    ok = 0
    for _ in range(conns):
        try:
            s = _connect(address, timeout)
        except (ConnectionError, OSError):
            break  # peer unreachable: later fetches will report properly
        try:
            def sink(meta, raw):
                if raw is not None and hasattr(raw, "nbytes"):
                    release_buffer(raw)
            _fetch_on(s, "_warm", {"nbytes": int(nbytes)}, ident, sink,
                      None)
        except RuntimeError:
            pass  # old server: error frame arrived on a clean boundary
        except (ConnectionError, OSError, ValueError):
            try:
                s.close()
            except OSError:
                pass
            continue
        _pool_put(address, s)
        ok += 1
    return ok


def prewarm_async(address: str, ident: str = "",
                  nbytes: int = PREWARM_BYTES, conns: int = 1,
                  on_fail: Optional[Callable[[], None]] = None) -> None:
    """Fire-and-forget ``prewarm`` in a daemon thread (callable from any
    context, including the event loop). ``on_fail`` runs (in the thread)
    when not a single connection warmed — callers use it to mark the
    address un-warmed so a later attempt retries."""

    def run():
        ok = 0
        try:
            ok = prewarm(address, ident, nbytes, conns)
        except Exception:  # noqa: BLE001 — warmup must never surface
            logger.debug("bulk prewarm of %s failed", address, exc_info=True)
        if not ok and on_fail is not None:
            try:
                on_fail()
            except Exception:  # noqa: BLE001 — callback best-effort
                pass

    threading.Thread(target=run, name="bulk-prewarm", daemon=True).start()


def _fetch_on(s: socket.socket, endpoint: str, payload: Any, ident: str,
              on_frame, stop) -> List[Tuple[Dict[str, Any], bytes]]:
    """One request/response exchange on an established connection."""
    out: List[Tuple[Dict[str, Any], bytes]] = []
    body = pack({"endpoint": endpoint, "payload": payload, "ident": ident})
    s.sendall(_U32.pack(len(body)) + body)
    while True:
        try:
            mb = _recv_exact(s, _read_u32(s, _MAX_META))
            meta = unpack(mb)
        except ValueError as e:
            # an over-cap length prefix is a desynced/corrupted stream,
            # not a protocol-level error: classify as a transport fault so
            # the caller's retry/resume ladder treats it like a reset
            raise ConnectionError(f"bulk frame desync (bad length): {e}")
        except ConnectionError:
            raise
        except Exception as e:  # noqa: BLE001 — a corrupted byte stream
            # desyncs the framing; same classification as above instead of
            # surfacing a raw msgpack error
            raise ConnectionError(f"bulk frame desync (corrupt meta): {e}")
        if not isinstance(meta, dict):
            raise ConnectionError("bulk frame desync (meta not a map)")
        try:
            raw_len = _read_u32(s)
        except ValueError as e:
            raise ConnectionError(f"bulk frame desync (bad raw length): "
                                  f"{e}")
        raw: Any = b""
        if raw_len:
            raw = _buf_get(raw_len)
            _recv_exact_into(s, memoryview(raw.data).cast("B"))
        if meta.get("error"):
            raise RuntimeError(f"bulk fetch failed: {meta['error']}")
        if meta.get("final"):
            return out
        if stop is not None and stop.is_set():
            # consumer aborted (e.g. injection failed): stop reading
            # instead of streaming the rest into the void
            raise ConnectionError("bulk fetch aborted by consumer")
        if on_frame is not None:
            on_frame(meta, raw)
        else:
            out.append((meta, raw))


# Connection pool, keyed by address. Kernel socket buffers autotune PER
# CONNECTION: the first tens of MB through a fresh unix/TCP socket move at
# ~1/3 of the steady rate (measured 0.7 vs 1.9 GB/s on this class of host),
# and disagg fetches one prefix per request — without reuse every fetch
# pays the warmup. Connections are sequential request/response, so a pooled
# connection is checked OUT for the duration of a fetch; concurrent fetches
# to the same peer each get their own.
_POOL_MAX_PER_ADDR = 4
_pool: Dict[str, List[socket.socket]] = {}
_pool_lock = threading.Lock()


def _pool_get(address: str, timeout: float) -> Tuple[socket.socket, bool]:
    """-> (connection, was_pooled)."""
    with _pool_lock:
        conns = _pool.get(address)
        if conns:
            return conns.pop(), True
    return _connect(address, timeout), False


def _pool_put(address: str, s: socket.socket) -> None:
    with _pool_lock:
        conns = _pool.setdefault(address, [])
        if len(conns) < _POOL_MAX_PER_ADDR:
            conns.append(s)
            return
    try:
        s.close()
    except OSError:
        pass


def bulk_fetch(address: str, endpoint: str, payload: Any,
               ident: str = "", timeout: float = 60.0,
               on_frame: Optional[Callable[[Dict[str, Any], Any], None]]
               = None,
               stop: Optional[threading.Event] = None
               ) -> List[Tuple[Dict[str, Any], bytes]]:
    """Synchronous bulk fetch (run via ``asyncio.to_thread`` from async
    code). ``ident`` is the server identity the caller expects (the
    instance id) — a mismatched server refuses instead of silently serving
    misses.

    With ``on_frame`` set, each data frame is handed to the callback AS IT
    ARRIVES (in this thread) and not accumulated — the caller can overlap
    downstream work (KV injection) with the remaining network transfer
    instead of buffering the whole prefix in RAM. Returns the accumulated
    [(meta, raw_bytes)] list (empty in callback mode); raises on handler
    error.

    Connections are pooled per address and reused across fetches (warm
    kernel buffers); a fetch that errors mid-stream closes its connection
    instead of returning it, and a STALE pooled connection (peer restarted)
    is retried once on a fresh one before the error propagates."""
    frames_seen = 0

    def counting(meta, raw):
        nonlocal frames_seen
        frames_seen += 1
        if on_frame is not None:
            on_frame(meta, raw)

    cb = counting if on_frame is not None else None
    s, was_pooled = _pool_get(address, timeout)
    try:
        out = _fetch_on(s, endpoint, payload, ident, cb, stop)
    except (ConnectionError, OSError):
        try:
            s.close()
        except OSError:
            pass
        # Retry once on a fresh connection ONLY for a stale pooled
        # connection failing before any frame arrived — a mid-stream retry
        # would replay frames into a side-effecting on_frame callback.
        if (not was_pooled or frames_seen
                or (stop is not None and stop.is_set())):
            raise
        s = _connect(address, timeout)
        try:
            out = _fetch_on(s, endpoint, payload, ident, cb, stop)
        except BaseException:
            try:
                s.close()
            except OSError:
                pass
            raise
    except BaseException:
        try:
            s.close()
        except OSError:
            pass
        raise
    _pool_put(address, s)
    return out


__all__ = ["BulkServer", "bulk_fetch", "release_buffer", "BulkHandler",
           "prewarm", "prewarm_async", "PREWARM_BYTES"]
