"""Component model: Namespace -> Component -> Endpoint naming tree + instances.

Parity: reference ``lib/runtime/src/component.rs`` (633 LoC) and
``component/{endpoint,namespace,client}.rs``.  We mirror the instance-key
scheme: a served endpoint writes
``instances/{namespace}/{component}/{endpoint}:{lease_id:x}`` into the
coordinator KV under its primary lease; clients discover instances by prefix
watch on ``instances/{namespace}/{component}/{endpoint}``.  Event subjects use
``{namespace}.{component}.{endpoint}`` dotted naming.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, TYPE_CHECKING

from dynamo_tpu.runtime.rpc import Handler

if TYPE_CHECKING:
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.runtime.client import Client

logger = logging.getLogger(__name__)

INSTANCE_ROOT = "instances/"
MODEL_ROOT = "models/"  # ModelEntry registrations (reference MODEL_ROOT_PATH)


@dataclass(frozen=True)
class Instance:
    """One live served endpoint (serialized into the coordinator KV)."""

    namespace: str
    component: str
    endpoint: str
    instance_id: int  # == lease id, like the reference (component.rs:379-386)
    address: str  # host:port of the worker's RpcServer
    # endpoints of the worker's bulk data plane (runtime/bulk.py) when it
    # serves one — the NIXL-role transport KV blocks ride instead of RPC
    bulk_address: str = ""
    # jax transfer-server address (engine/transfer.DeviceTransferPlane)
    # when the worker serves device-direct KV pulls — blocks move
    # device-to-device with no host bounce (the NIXL RDMA role proper)
    direct_address: str = ""
    # graceful drain: a draining instance stops receiving NEW requests
    # (routers exclude it from selection the moment their watch delivers
    # the re-put) but stays directly addressable — its in-flight streams
    # are migrating out and survivors still pull its pinned KV from it
    draining: bool = False

    @property
    def etcd_key(self) -> str:
        return (f"{INSTANCE_ROOT}{self.namespace}/{self.component}/"
                f"{self.endpoint}:{self.instance_id:x}")

    @property
    def subject(self) -> str:
        return f"{self.namespace}.{self.component}.{self.endpoint}"

    def to_json(self) -> bytes:
        d = {
            "namespace": self.namespace,
            "component": self.component,
            "endpoint": self.endpoint,
            "instance_id": self.instance_id,
            "address": self.address,
        }
        if self.bulk_address:
            d["bulk_address"] = self.bulk_address
        if self.direct_address:
            d["direct_address"] = self.direct_address
        if self.draining:
            d["draining"] = True
        return json.dumps(d).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "Instance":
        d = json.loads(data)
        return cls(
            namespace=d["namespace"], component=d["component"],
            endpoint=d["endpoint"], instance_id=d["instance_id"],
            address=d["address"], bulk_address=d.get("bulk_address", ""),
            direct_address=d.get("direct_address", ""),
            draining=bool(d.get("draining", False)))


class Namespace:
    def __init__(self, drt: "DistributedRuntime", name: str):
        self._drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self._drt, self.name, name)

    def event_subject(self, suffix: str) -> str:
        return f"{self.name}.{suffix}"

    def __repr__(self) -> str:
        return f"Namespace({self.name})"


class Component:
    def __init__(self, drt: "DistributedRuntime", namespace: str, name: str):
        self._drt = drt
        self.namespace = namespace
        self.name = name

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self._drt, self.namespace, self.name, name)

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.name}"

    def event_subject(self, suffix: str) -> str:
        """Subject for component-scoped events, e.g. ``kv_events``."""
        return f"{self.namespace}.{self.name}.{suffix}"

    async def list_instances(self) -> List[Instance]:
        """All live instances of all endpoints of this component."""
        items = await self._drt.coord.get_prefix(f"{INSTANCE_ROOT}{self.path}/")
        return [Instance.from_json(v) for _, v in items]

    async def scrape_stats(self) -> Dict[int, Dict[str, Any]]:
        """Scrape ``__stats__`` from every live instance of this component.

        Parity: NATS ``$SRV.STATS`` scraping (reference
        ``kv_router/metrics_aggregator.rs``). Returns {instance_id: stats}.
        """
        out: Dict[int, Dict[str, Any]] = {}
        for inst in await self.list_instances():
            try:
                conn = await self._drt.rpc_pool.get(inst.address)
                stream = await conn.request("__stats__", None)
                async for payload in stream:
                    out[inst.instance_id] = payload
            except (ConnectionError, RuntimeError) as e:
                logger.debug("stats scrape of %s failed: %s", inst.address, e)
        return out

    def __repr__(self) -> str:
        return f"Component({self.path})"


class Endpoint:
    def __init__(self, drt: "DistributedRuntime", namespace: str,
                 component: str, name: str):
        self._drt = drt
        self.namespace = namespace
        self.component = component
        self.name = name

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.name}"

    @property
    def instance_prefix(self) -> str:
        # the trailing ':' stops a watch for endpoint "gen" from also matching
        # a sibling endpoint named "generate"
        return f"{INSTANCE_ROOT}{self.path}:"

    @property
    def subject(self) -> str:
        return f"{self.namespace}.{self.component}.{self.name}"

    async def serve(self, handler: Handler,
                    stats_provider: Optional[Callable[[], Any]] = None,
                    graceful_shutdown: bool = True,
                    bulk_address: str = "",
                    direct_address: str = "") -> "ServedEndpoint":
        """Register the handler on the local RpcServer and announce the
        instance in the coordinator under the primary lease.

        Parity: reference ``component/endpoint.rs:25-120``
        (``EndpointConfigBuilder::start``) + PushEndpoint.
        """
        drt = self._drt
        server = await drt.ensure_rpc_server()
        rpc_name = f"{self.path}"
        server.register(rpc_name, handler, stats_provider)
        lease = await drt.primary_lease()
        # DYN_RPC_ADVERTISE lets a worker announce an address other than its
        # listening socket — e.g. a ChaosProxy in front of it (fault drills)
        # or a NAT'd / port-forwarded address in containerized deployments
        advertise = os.environ.get("DYN_RPC_ADVERTISE") or server.address
        inst = Instance(
            namespace=self.namespace, component=self.component,
            endpoint=self.name, instance_id=lease.lease_id,
            address=advertise, bulk_address=bulk_address,
            direct_address=direct_address)
        await drt.coord.put(inst.etcd_key, inst.to_json(), lease_id=lease.lease_id)
        logger.info("serving endpoint %s as instance %x at %s",
                    self.path, inst.instance_id, inst.address)
        se = ServedEndpoint(self, inst, rpc_name)
        # tracked for coordinator resync: a restarted (possibly state-wiped)
        # coordinator re-learns this instance via drt._resync_registrations
        drt._served.add(se)
        return se

    async def client(self, **kw: Any) -> "Client":
        from dynamo_tpu.runtime.client import Client
        return await Client.create(self._drt, self, **kw)

    async def list_instances(self) -> List[Instance]:
        items = await self._drt.coord.get_prefix(self.instance_prefix)
        return [Instance.from_json(v) for _, v in items]

    def __repr__(self) -> str:
        return f"Endpoint({self.path})"


async def _delete_when_connected(drt: Any, key: str) -> None:
    """Land a shutdown-time instance delete that failed mid-outage.

    Idempotent against the resync hook's own pending-delete pass (deleting
    a missing key is a no-op); gives up when the client closes for good."""
    while key in drt._pending_deletes:
        try:
            await drt.coord.wait_connected()
            await drt.coord.delete(key)
            drt._pending_deletes.discard(key)
            return
        except ConnectionError:
            if drt.coord.closed.is_set():
                return
            await asyncio.sleep(0.05)  # reconnect raced us; re-park


class ServedEndpoint:
    """Handle for a live served endpoint; ``shutdown()`` deregisters it."""

    def __init__(self, endpoint: Endpoint, instance: Instance, rpc_name: str):
        self.endpoint = endpoint
        self.instance = instance
        self._rpc_name = rpc_name

    def _reannounce(self, lease_id: int) -> None:
        """Rebuild the instance record against the (possibly re-granted)
        primary lease before a resync re-put: instance ids == lease ids, so
        a new lease id means a new instance id and a new KV key."""
        if self.instance.instance_id != lease_id:
            self.instance = dataclasses.replace(self.instance,
                                                instance_id=lease_id)

    async def announce_draining(self) -> None:
        """Re-put the instance record with ``draining`` set so routers
        route around it. The flag lives on ``self.instance``, so a
        coordinator resync racing the drain re-announces it draining too —
        the announcement survives a control-plane blip. Idempotent; a
        put failure is swallowed (the drain proceeds regardless — in the
        worst case racing requests are refused and replayed)."""
        if self.instance.draining:
            return
        self.instance = dataclasses.replace(self.instance, draining=True)
        drt = self.endpoint._drt
        try:
            await drt.coord.put(self.instance.etcd_key,
                                self.instance.to_json(),
                                lease_id=self.instance.instance_id)
            logger.info("instance %x of %s announced draining",
                        self.instance.instance_id, self.endpoint.path)
        except Exception:  # noqa: BLE001 — drain must proceed regardless
            logger.warning("drain announcement for %s failed; routers "
                           "fall back to refusal-and-replay",
                           self.endpoint.path, exc_info=True)

    async def shutdown(self) -> None:
        drt = self.endpoint._drt
        # untrack first so a racing coordinator resync can't re-announce a
        # deliberately shut-down instance; park the key as pending-delete
        # until the delete actually lands — shutting down mid-outage must
        # not leave a ghost instance the (still-alive) primary lease would
        # sustain forever after reconnect
        drt._served.discard(self)
        drt._pending_deletes.add(self.instance.etcd_key)
        try:
            await drt.coord.delete(self.instance.etcd_key)
            drt._pending_deletes.discard(self.instance.etcd_key)
        except Exception:
            # the resync hook retries pending deletes — but a shutdown
            # racing the TAIL of a resync (hooks already ran, connection
            # not yet up) would wait a whole extra outage for the next
            # one, so also retry as soon as the client reconnects
            drt.runtime.spawn(_delete_when_connected(drt, self.instance.etcd_key),
                              name=f"pending-delete-{self.instance.instance_id:x}")
        if drt.rpc_server is not None:
            drt.rpc_server.unregister(self._rpc_name)


__all__ = ["Namespace", "Component", "Endpoint", "Instance", "ServedEndpoint",
           "INSTANCE_ROOT", "MODEL_ROOT"]
