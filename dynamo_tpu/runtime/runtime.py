"""Runtime and DistributedRuntime: process harness + node-level singleton.

Parity: reference ``lib/runtime/src/{runtime,distributed,worker}.rs`` —
``Runtime`` (cancellation tree, task spawning), ``DistributedRuntime`` (etcd +
NATS clients, lazy TCP server, component registry), ``Worker::execute``.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import signal
from typing import Any, Awaitable, Callable, Dict, Optional

from dynamo_tpu.runtime import codec
from dynamo_tpu.runtime.component import Namespace
from dynamo_tpu.runtime.coordinator import CoordClient, Coordinator, Lease, Subscription
from dynamo_tpu.runtime.rpc import RpcClientPool, RpcServer

logger = logging.getLogger(__name__)

DEFAULT_COORDINATOR = os.environ.get("DYN_COORDINATOR", "127.0.0.1:6650")
DEFAULT_LEASE_TTL = float(os.environ.get("DYN_LEASE_TTL", "5.0"))


class Runtime:
    """Process-local runtime: shutdown token + supervised background tasks."""

    def __init__(self) -> None:
        self._shutdown = asyncio.Event()
        self._tasks: set = set()

    @property
    def is_shutdown(self) -> bool:
        return self._shutdown.is_set()

    def shutdown(self) -> None:
        self._shutdown.set()

    async def wait_shutdown(self) -> None:
        await self._shutdown.wait()

    def spawn(self, coro: Awaitable[Any], name: Optional[str] = None) -> asyncio.Task:
        task = asyncio.create_task(coro, name=name)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    def spawn_critical(self, coro: Awaitable[Any],
                       name: Optional[str] = None) -> asyncio.Task:
        """Supervised task: if it raises, the whole runtime shuts down.

        Parity: reference ``CriticalTaskExecutionHandle``
        (``lib/runtime/src/utils/task.rs``).
        """
        async def _wrapped() -> None:
            try:
                await coro
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.exception("critical task %s failed; shutting down", name)
                self.shutdown()
        return self.spawn(_wrapped(), name=name)

    async def drain(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)


class DistributedRuntime:
    """Node-level singleton: coordinator client, lease, RPC server, event bus.

    ``DistributedRuntime.create()`` connects to an existing coordinator (or, in
    ``standalone=True`` mode, embeds one in-process — handy for tests and
    single-node deployments; the reference instead requires external
    etcd+NATS).
    """

    def __init__(self, runtime: Runtime, coord: CoordClient,
                 embedded: Optional[Coordinator] = None):
        self.runtime = runtime
        self.coord = coord
        self._embedded = embedded
        self.rpc_server: Optional[RpcServer] = None
        self.rpc_pool = RpcClientPool()
        self._primary_lease: Optional[Lease] = None
        self._rpc_host = os.environ.get("DYN_RPC_HOST", "127.0.0.1")
        # serialize lazy init: concurrent serve() calls must share one lease
        # and one RpcServer
        self._init_lock = asyncio.Lock()
        # live ServedEndpoints, re-announced on coordinator resync: a
        # restarted (possibly state-wiped) coordinator learns every
        # instance again under the re-established primary lease
        self._served: set = set()
        # instance keys whose shutdown-time delete failed (outage in
        # progress); retried by the resync hook AFTER the re-puts, so a
        # shutdown racing the hook's _served snapshot still wins
        self._pending_deletes: set = set()
        coord.add_resync_hook(self._resync_registrations)

    @classmethod
    async def create(cls, coordinator: str = DEFAULT_COORDINATOR,
                     runtime: Optional[Runtime] = None,
                     standalone: bool = False) -> "DistributedRuntime":
        runtime = runtime or Runtime()
        embedded = None
        if standalone:
            # honor the requested address so other processes can join with
            # the same --coordinator value; a replicated address list embeds
            # the FIRST entry (the primary slot)
            first = coordinator.split(",")[0].strip()
            host, _, port = first.rpartition(":")
            embedded = await Coordinator(host=host or "127.0.0.1",
                                         port=int(port)).start()
            coordinator = embedded.address
        coord = await CoordClient(coordinator).connect()
        return cls(runtime, coord, embedded)

    async def close(self) -> None:
        if self._primary_lease is not None:
            await self._primary_lease.revoke()
            self._primary_lease = None
        await self.rpc_pool.close()
        if self.rpc_server is not None:
            await self.rpc_server.stop()
            self.rpc_server = None
        await self.coord.close()
        if self._embedded is not None:
            await self._embedded.stop()
            self._embedded = None
        await self.runtime.drain()

    async def __aenter__(self) -> "DistributedRuntime":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- naming ------------------------------------------------------------

    def namespace(self, name: str) -> Namespace:
        return Namespace(self, name)

    # -- serving infra -----------------------------------------------------

    async def ensure_rpc_server(self) -> RpcServer:
        async with self._init_lock:
            if self.rpc_server is None:
                port = int(os.environ.get("DYN_RPC_PORT", "0"))
                self.rpc_server = await RpcServer(host=self._rpc_host, port=port).start()
                logger.info("rpc server listening on %s", self.rpc_server.address)
            return self.rpc_server

    async def primary_lease(self) -> Lease:
        """The process's liveness lease: all instance registrations attach to
        it, so losing it (crash, hang) deregisters everything within TTL.
        Parity: reference primary lease (``distributed.rs:45-136``)."""
        async with self._init_lock:
            if self._primary_lease is None:
                self._primary_lease = await self.coord.grant_lease(
                    ttl=DEFAULT_LEASE_TTL, keepalive=True)
                self.runtime.spawn_critical(
                    self._watch_lease(self._primary_lease), name="primary-lease-watch")
            return self._primary_lease

    async def _watch_lease(self, lease: Lease) -> None:
        await lease.lost.wait()
        raise ConnectionError("primary lease lost")

    async def _resync_registrations(self) -> None:
        """Coordinator resync hook: re-announce every served endpoint.

        The primary lease may have been re-granted under a NEW id during the
        resync, and instance ids == lease ids — so each instance record is
        rebuilt against the current lease before the re-put. Clients absorb
        the id churn through their watches (put of the new key now; the old
        key's delete after the stale-read grace window)."""
        lease = self._primary_lease
        if lease is not None:
            for se in list(self._served):
                if se not in self._served:
                    continue  # shut down while we iterated; its own delete
                    # (or _pending_deletes) targets the pre-relocation key
                se._reannounce(lease.lease_id)
                await self.coord.put(se.instance.etcd_key,
                                     se.instance.to_json(),
                                     lease_id=lease.lease_id)
                if se not in self._served:
                    # shutdown raced the put: it parked the OLD key, but we
                    # just re-announced under the relocated id — park the
                    # NEW key too or the live lease sustains a ghost forever
                    self._pending_deletes.add(se.instance.etcd_key)
                    continue
                logger.info("re-registered %s as instance %x after "
                            "coordinator resync", se.endpoint.path,
                            se.instance.instance_id)
        # deletes LAST: a shutdown that raced the snapshot above (or whose
        # delete failed mid-outage) must not leave its ghost behind
        for key in list(self._pending_deletes):
            await self.coord.delete(key)
            self._pending_deletes.discard(key)

    # -- typed event bus ---------------------------------------------------

    def kv_store(self):
        """The pluggable key-value store surface (reference
        ``storage/key_value_store.rs`` trait): buckets with optional TTL,
        backed by the coordinator KV plane."""
        from dynamo_tpu.runtime.kv_store import CoordKeyValueStore
        return CoordKeyValueStore(self.coord)

    async def publish_event(self, subject: str, obj: Any) -> int:
        """Publish a msgpack-encoded event object."""
        return await self.coord.publish(subject, codec.pack(obj))

    async def subscribe_events(self, subject: str,
                               queue_group: Optional[str] = None) -> "TypedSubscription":
        sub = await self.coord.subscribe(subject, queue_group=queue_group)
        return TypedSubscription(sub)


class TypedSubscription:
    """Wraps a raw Subscription, msgpack-decoding payloads."""

    def __init__(self, sub: Subscription):
        self._sub = sub

    def __aiter__(self) -> "TypedSubscription":
        return self

    async def __anext__(self):
        subject, payload = await self._sub.__anext__()
        return subject, codec.unpack(payload)

    async def cancel(self) -> None:
        await self._sub.cancel()


async def worker_main(app: Callable[[DistributedRuntime], Awaitable[None]],
                      coordinator: str = DEFAULT_COORDINATOR,
                      standalone: bool = False) -> None:
    """Process harness: build the DRT, install signal handlers, run ``app``,
    drain on shutdown.  Parity: reference ``Worker::execute`` +
    ``@dynamo_worker()`` decorator."""
    drt = await DistributedRuntime.create(coordinator, standalone=standalone)
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        with contextlib.suppress(NotImplementedError):
            loop.add_signal_handler(sig, drt.runtime.shutdown)
    try:
        app_task = asyncio.create_task(app(drt))
        shutdown_task = asyncio.create_task(drt.runtime.wait_shutdown())
        done, _pending = await asyncio.wait(
            {app_task, shutdown_task}, return_when=asyncio.FIRST_COMPLETED)
        if app_task in done:
            app_task.result()  # propagate app errors
        else:
            app_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await app_task
    finally:
        await drt.close()


__all__ = ["Runtime", "DistributedRuntime", "TypedSubscription", "worker_main",
           "DEFAULT_COORDINATOR"]
