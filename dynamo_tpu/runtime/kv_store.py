"""Pluggable key-value store abstraction (buckets with optional TTL).

Parity: reference ``lib/runtime/src/storage/key_value_store.rs`` — a
``KeyValueStore`` trait with etcd / NATS-KV / in-memory backends, used for
model-card storage and TTL buckets. Here the two backends that exist in
this runtime's world:

- ``MemoryKeyValueStore`` — in-process (static mode, tests);
- ``CoordKeyValueStore`` — namespaced onto the coordinator KV plane
  (``kvstore/{bucket}/{key}``), TTL carried in-band per entry (msgpack
  envelope) with lazy expiry, so it needs no coordinator-side support
  beyond plain put/get/delete.

Both present the same ``KeyValueBucket`` surface, so components written
against it (model-card storage, planner state, user extensions) are
backend-agnostic — the reference's reason for the trait.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from dynamo_tpu.runtime import codec
from dynamo_tpu.runtime.coordinator import replay_registry


class KeyValueBucket:
    """One named bucket. Values are opaque bytes. ``ttl`` (seconds, set at
    bucket creation) applies per entry from its last put."""

    async def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    async def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    async def delete(self, key: str) -> bool:
        raise NotImplementedError

    async def entries(self) -> List[Tuple[str, bytes]]:
        raise NotImplementedError


class KeyValueStore:
    async def bucket(self, name: str,
                     ttl: Optional[float] = None) -> KeyValueBucket:
        raise NotImplementedError


# ---------------------------------------------------------------- memory


class _MemoryBucket(KeyValueBucket):
    def __init__(self, ttl: Optional[float],
                 data: Dict[str, Tuple[bytes, float]]):
        self.ttl = ttl
        self._data = data  # key -> (val, exp); shared per bucket name

    def _live(self, key: str) -> Optional[bytes]:
        item = self._data.get(key)
        if item is None:
            return None
        val, exp = item
        if exp and exp <= time.monotonic():
            del self._data[key]
            return None
        return val

    async def put(self, key: str, value: bytes) -> None:
        exp = (time.monotonic() + self.ttl) if self.ttl else 0.0
        self._data[key] = (bytes(value), exp)

    async def get(self, key: str) -> Optional[bytes]:
        return self._live(key)

    async def delete(self, key: str) -> bool:
        return self._data.pop(key, None) is not None

    async def entries(self) -> List[Tuple[str, bytes]]:
        out = []
        for k in list(self._data):
            v = self._live(k)
            if v is not None:
                out.append((k, v))
        return out


class MemoryKeyValueStore(KeyValueStore):
    def __init__(self) -> None:
        self._datas: Dict[str, Dict[str, Tuple[bytes, float]]] = {}

    async def bucket(self, name: str,
                     ttl: Optional[float] = None) -> KeyValueBucket:
        # handle semantics match the coordinator backend: the DATA is
        # shared per name, the TTL is per handle (each call's ttl applies
        # to the entries written through it)
        data = self._datas.setdefault(name, {})
        return _MemoryBucket(ttl, data)


# ------------------------------------------------------------- coordinator


class _ReplayRegistry(dict):
    """key -> (expiry, raw envelope) this PROCESS wrote. An amortized sweep
    on write keeps it bounded by LIVE entries even for TTL'd keys written
    once and never read again (entries()'s lazy collection never sees
    those)."""

    def __init__(self) -> None:
        super().__init__()
        self._puts = 0

    def record(self, key: str, exp: float, raw: bytes) -> None:
        self[key] = (exp, raw)
        self._puts += 1
        if self._puts >= max(64, len(self)):
            self._puts = 0
            now = time.time()
            for k in [k for k, (e, _raw) in self.items() if e and e <= now]:
                self.pop(k, None)


def _replay_registry(coord) -> _ReplayRegistry:
    """The client's resync-replay registry: entries re-put after a
    coordinator restart (a state-wiped coordinator loses unleased keys
    too). Writer-side ownership keeps replay conflict-free: each process
    re-puts only what it wrote last."""
    async def _replay(reg: _ReplayRegistry) -> None:
        now = time.time()
        for key, (exp, raw) in list(reg.items()):
            if exp and exp <= now:
                reg.pop(key, None)  # expired while we were away
                continue
            await coord.put(key, raw)

    return replay_registry(coord, "_kvstore_replay", _ReplayRegistry, _replay)


class _CoordBucket(KeyValueBucket):
    def __init__(self, coord, name: str, ttl: Optional[float]):
        self._coord = coord
        self._prefix = f"kvstore/{name}/"
        self.ttl = ttl
        self._written = _replay_registry(coord)

    def _wrap(self, value: bytes) -> Tuple[float, bytes]:
        exp = (time.time() + self.ttl) if self.ttl else 0.0
        # the WRITER's ttl rides in the envelope: readers use it as the
        # collection grace window, so a no-TTL read handle can't collect
        # a just-expired entry out from under a racing re-put
        return exp, codec.pack({"e": exp, "v": bytes(value),
                                "t": float(self.ttl or 0.0)})

    def _unwrap(self, raw: bytes) -> Optional[bytes]:
        d = codec.unpack(raw)
        if d["e"] and d["e"] <= time.time():
            return None
        return d["v"]

    async def put(self, key: str, value: bytes) -> None:
        exp, raw = self._wrap(value)
        full = self._prefix + key
        self._written.record(full, exp, raw)
        await self._coord.put(full, raw)

    async def get(self, key: str) -> Optional[bytes]:
        raw = await self._coord.get(self._prefix + key)
        if raw is None:
            return None
        # expired entries are SKIPPED here, not deleted: an unguarded
        # read-then-delete races a concurrent re-put and could drop the
        # fresh value; collection happens in entries() behind a full-TTL
        # grace window instead
        return self._unwrap(raw)

    async def delete(self, key: str) -> bool:
        self._written.pop(self._prefix + key, None)
        return (await self._coord.delete(self._prefix + key)) > 0

    async def entries(self) -> List[Tuple[str, bytes]]:
        out = []
        for k, raw in await self._coord.get_prefix(self._prefix):
            d = codec.unpack(raw)
            # writer's ttl; legacy envelopes (no "t") fall back to this
            # handle's ttl so they keep their pre-upgrade protection
            grace = float(d.get("t", self.ttl or 0.0))
            if d["e"] and d["e"] <= time.time():
                # lazy collection (a bucket used only via entries() must
                # not leak forever), but only past a full extra TTL of
                # grace — a racing re-put within that window would have
                # rewritten the envelope, so the delete-vs-put race is
                # confined to entries dead for >= 2x their TTL
                if d["e"] + grace <= time.time():
                    self._written.pop(k, None)
                    await self._coord.delete(k)
                continue
            out.append((k[len(self._prefix):], d["v"]))
        return out


class CoordKeyValueStore(KeyValueStore):
    def __init__(self, coord) -> None:
        self._coord = coord

    async def bucket(self, name: str,
                     ttl: Optional[float] = None) -> KeyValueBucket:
        return _CoordBucket(self._coord, name, ttl)


__all__ = ["KeyValueStore", "KeyValueBucket", "MemoryKeyValueStore",
           "CoordKeyValueStore"]
