"""PushRouter: load-balancing request fan-out with fault detection.

Parity: reference ``lib/runtime/src/pipeline/network/egress/push_router.rs``
(``RouterMode::{RoundRobin, Random, Direct, KV}``, NoResponders/stream-drop
instance-down marking).  The KV mode lives in ``dynamo_tpu.kv_router`` and
wraps this router.

``RouterMode.COST`` adds the failure-aware policy (``runtime/resilience.py``):
min-cost selection over EWMA TTFT + in-flight + scraped queue depth, gated by
per-instance circuit breakers, with deadline-aware budgeted retries and
optional hedged dispatch.  When no policy is attached the legacy modes run
the exact pre-policy code path — round-robin stays byte-stable as the
fallback.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
import time
from typing import Any, AsyncIterator, Dict, Optional, Tuple

from dynamo_tpu.runtime.client import Client
from dynamo_tpu.runtime.resilience import RouterPolicy
from dynamo_tpu.utils.aio import decorrelated_jitter, reap_task
from dynamo_tpu.runtime.rpc import (
    DEADLINE_HEADER,
    REQUEST_ID_HEADER,
    DeadlineExceededError,
    ResponseStream,
    StreamEndedError,
)

logger = logging.getLogger(__name__)


class RouterMode(enum.Enum):
    ROUND_ROBIN = "round-robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"
    COST = "cost"


class PushRouter:
    """Routes requests across an endpoint's live instances."""

    def __init__(self, client: Client, mode: RouterMode = RouterMode.ROUND_ROBIN,
                 retries: int = 3, backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0,
                 policy: Optional[RouterPolicy] = None):
        self.client = client
        self.mode = mode
        self.retries = retries
        # decorrelated-jitter backoff between failover attempts: during an
        # outage a tight retry loop hammers the surviving instances at the
        # exact moment they're absorbing the failed one's traffic (0 = off)
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._rr = 0
        if policy is None and mode is RouterMode.COST:
            policy = RouterPolicy()
        self.policy = policy
        if policy is not None:
            policy.attach_client(client)
        self._stats_task: Optional[asyncio.Task] = None

    def select_instance(self, exclude: Optional[set] = None) -> int:
        iid, _ = self._select(exclude)
        return iid

    def _select(self, exclude: Optional[set] = None
                ) -> Tuple[int, Optional[Dict[str, Any]]]:
        """Pick an instance; returns (iid, score inputs or None)."""
        ids = sorted(self.client.instance_ids())
        if not ids:
            raise ConnectionError(
                f"no instances available for {self.client.endpoint.path}")
        if exclude:
            remaining = [i for i in ids if i not in exclude]
            ids = remaining or ids  # every instance tried: round again
        if self.policy is not None:
            allowed = [i for i in ids if self.policy.breakers.allow(i)]
            # every breaker open: degrade to the full set rather than refuse —
            # an all-open board means the fleet is sick, not that no work
            # should be attempted
            ids = allowed or ids
            if self.mode is RouterMode.COST:
                return self.policy.select(ids)
        if self.mode == RouterMode.RANDOM:
            return random.choice(ids), None
        chosen = ids[self._rr % len(ids)]
        self._rr += 1
        return chosen, None

    async def _open(self, payload: Any, instance_id: Optional[int],
                    headers: Optional[Dict[str, Any]]
                    ) -> "tuple[int, ResponseStream, Optional[Dict[str, Any]]]":
        """Open a response stream; returns (chosen_instance_id, stream,
        score inputs).

        Connect-level failures on router-selected instances fail over to other
        instances (up to ``retries``) and mark the unreachable one down.  A
        caller-pinned ``instance_id`` is never silently rerouted.  With a
        policy attached, failover re-dispatches spend the frontend-wide retry
        budget and respect the request deadline against the target's EWMA
        TTFT.
        """
        last_err: Optional[Exception] = None
        attempts = max(1, self.retries)
        sleep_s = self.backoff_base_s
        deadline = (headers or {}).get(DEADLINE_HEADER)
        tried: set = set()
        inputs: Optional[Dict[str, Any]] = None
        pol = self.policy
        for attempt in range(attempts):
            if deadline is not None and time.time() >= deadline:
                # failover must not hold a request past its deadline, nor
                # dispatch already-expired work a worker will only drop
                raise DeadlineExceededError(
                    "request deadline exceeded during failover")
            if instance_id is not None:
                iid = instance_id
            else:
                iid, inputs = self._select(exclude=tried)
            if pol is not None:
                if attempt > 0:
                    # a failover re-dispatch is a retry: it must fit the
                    # fleet-wide budget (no retry storms during brownouts)
                    # and the target must plausibly beat the deadline
                    if not pol.budget.try_spend():
                        pol.stats.retries["denied"] += 1
                        raise ConnectionError(
                            f"retry budget exhausted for "
                            f"{self.client.endpoint.path}: {last_err}")
                    if not pol.can_redispatch(iid, deadline):
                        raise DeadlineExceededError(
                            "remaining deadline below target's expected TTFT; "
                            "not re-dispatching")
                    pol.stats.retries["connect"] += 1
                pol.breakers.on_dispatch(iid)
            try:
                return iid, await self.client.direct(payload, iid, headers), inputs
            except ConnectionError as e:
                last_err = e
                tried.add(iid)
                if pol is not None:
                    pol.on_failure(iid, "connect")
                self.client.report_instance_down(iid)
                if instance_id is not None:
                    break  # caller pinned the instance; don't fail over silently
                if attempt + 1 < attempts and self.backoff_base_s > 0:
                    sleep_s = decorrelated_jitter(
                        sleep_s, self.backoff_base_s, self.backoff_cap_s)
                    if deadline is not None:
                        sleep_s = min(sleep_s, max(0.0, deadline - time.time()))
                    await asyncio.sleep(sleep_s)
        raise ConnectionError(
            f"all attempts to reach {self.client.endpoint.path} failed: {last_err}")

    async def generate(self, payload: Any, instance_id: Optional[int] = None,
                       headers: Optional[Dict[str, Any]] = None) -> ResponseStream:
        _iid, stream, _inputs = await self._open(payload, instance_id, headers)
        return stream

    async def generate_stream(self, payload: Any,
                              instance_id: Optional[int] = None,
                              headers: Optional[Dict[str, Any]] = None
                              ) -> AsyncIterator[Any]:
        """Convenience: iterate response payloads; marks the instance down on
        mid-stream drop and re-raises ``StreamEndedError`` for the migration
        operator to handle."""
        if self.policy is None:
            # legacy path, kept verbatim: RouterMode round-robin/random must
            # stay byte-stable as the no-policy fallback
            iid, stream, _ = await self._open(payload, instance_id, headers)
            try:
                async for item in stream:
                    yield item
            except StreamEndedError:
                self.client.report_instance_down(iid)
                raise
            finally:
                # Consumer stopped early (stop string, disconnect,
                # GeneratorExit): tell the worker to abort generation instead
                # of streaming into a queue nobody reads.
                if not stream.finished:
                    await stream.cancel()
            return
        async for item in self._generate_stream_policy(
                payload, instance_id, headers):
            yield item

    # -- policy-path streaming ---------------------------------------------

    async def _generate_stream_policy(self, payload: Any,
                                      instance_id: Optional[int],
                                      headers: Optional[Dict[str, Any]]
                                      ) -> AsyncIterator[Any]:
        pol = self.policy
        self._ensure_stats_loop()
        deadline = (headers or {}).get(DEADLINE_HEADER)
        if instance_id is None:
            # every router-selected first attempt earns the fleet its
            # fractional retry credit
            pol.budget.deposit()
        t0 = time.monotonic()
        iid, stream, inputs = await self._open(payload, instance_id, headers)
        pol.begin(iid)
        if instance_id is None:
            # pinned dispatches (KV mode, migration resume) count their
            # decision at the layer that actually chose the worker
            pol.stats.decisions[self.mode.value] += 1
            self._export_decision(iid, inputs)
        it = stream.__aiter__()
        first: Any = None
        exhausted = False
        got_first = False
        try:
            # hedged dispatch: only for router-selected requests, and never
            # for a migration replay — a hedged replay would run the same
            # resume on two workers and double-count migration_replays
            hedge_ok = (pol.cfg.hedge and instance_id is None
                        and not (isinstance(payload, dict)
                                 and payload.get("migration_attempt")))
            t_first = t0
            if hedge_ok:
                (iid, stream, it, first, exhausted,
                 t_first) = await self._hedged_first(
                    payload, headers, iid, stream, it, deadline, t0)
                got_first = True
            if got_first:
                if first is not None:
                    # dispatch-relative: a hedge winner's EWMA reflects the
                    # worker's own TTFT, not the hedge delay it waited out
                    pol.observe_ttft(iid, time.monotonic() - t_first)
                    yield first
            while not exhausted:
                try:
                    item = await it.__anext__()
                except StopAsyncIteration:
                    break
                if not got_first:
                    got_first = True
                    pol.observe_ttft(iid, time.monotonic() - t0)
                yield item
            pol.on_success(iid, time.monotonic() - t0)
        except StreamEndedError:
            pol.on_failure(iid, "stream_drop")
            self.client.report_instance_down(iid)
            raise
        except DeadlineExceededError:
            pol.on_failure(iid, "timeout")
            raise
        finally:
            pol.end(iid)
            if not stream.finished:
                await stream.cancel()

    async def _hedged_first(self, payload: Any,
                            headers: Optional[Dict[str, Any]], iid: int,
                            stream: ResponseStream, it: Any,
                            deadline: Optional[float], t0: float):
        """Race the primary's first frame against a hedge on the next-best
        instance; first winner cancels the loser.  Returns the winning
        (iid, stream, iterator, first_item, exhausted, dispatch_time)."""
        pol = self.policy
        primary = asyncio.ensure_future(it.__anext__())
        done, _ = await asyncio.wait({primary}, timeout=pol.hedge_delay_s())
        if done:
            first, exhausted = self._unpack_first(primary)
            return iid, stream, it, first, exhausted, t0
        hedge = await self._fire_hedge(payload, headers, iid, deadline)
        if hedge is None:
            return iid, stream, it, *(await self._await_first(primary)), t0
        hiid, hstream = hedge
        t_hedge = time.monotonic()
        pol.begin(hiid)
        hit = hstream.__aiter__()
        htask = asyncio.ensure_future(hit.__anext__())
        arms = {primary: (iid, stream, it, t0),
                htask: (hiid, hstream, hit, t_hedge)}
        pending = {primary, htask}
        errors: Dict[asyncio.Future, BaseException] = {}
        # inflight contract with the caller: the caller began the primary and
        # will end whichever iid this returns; here we end every *other*
        # begun side exactly once (``ended`` guards the double-elimination
        # paths), and on the both-failed raise the primary stays "begun" for
        # the caller's finally
        ended: set = set()
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                # prefer the primary when both finished in the same tick
                winner = None
                for t in sorted(done, key=lambda t: 0 if t is primary else 1):
                    exc = t.exception()
                    if exc is None or isinstance(exc, StopAsyncIteration):
                        winner = t
                        break
                    errors[t] = exc
                    wiid, wstream = arms[t][0], arms[t][1]
                    pol.on_failure(wiid, "stream_drop"
                                   if isinstance(exc, StreamEndedError)
                                   else "connect")
                    if isinstance(exc, (ConnectionError, StreamEndedError)):
                        self.client.report_instance_down(wiid)
                    if t is htask and t not in ended:
                        pol.end(wiid)
                        ended.add(t)
                    if not wstream.finished:
                        await wstream.cancel()
                if winner is None:
                    continue  # a side failed; keep waiting on the other
                # cancel a still-pending loser (slow, not failed: no breaker
                # penalty) and settle inflight for every non-winner side
                for t in pending:
                    t.cancel()
                pending = set()
                loser = htask if winner is primary else primary
                liid, lstream = arms[loser][0], arms[loser][1]
                if loser not in ended:
                    pol.end(liid)
                    ended.add(loser)
                if loser is primary:
                    # the primary produced nothing in this long: feed the
                    # elapsed time to the latency book as a TTFT lower
                    # bound, so the scorer (and slow-call breaker
                    # accounting) learn to route around a consistently
                    # slow instance the hedge keeps beating
                    pol.observe_ttft(liid, time.monotonic() - t0)
                if not lstream.finished:
                    await lstream.cancel()
                pol.stats.hedges["won" if winner is htask else "lost"] += 1
                first, exhausted = self._unpack_first(winner)
                wiid, wstream, wit, t_win = arms[winner]
                return wiid, wstream, wit, first, exhausted, t_win
            # both sides failed: surface the primary's error (the hedge was
            # opportunistic); the caller's finally settles the primary
            raise errors.get(primary) or next(iter(errors.values()))
        finally:
            for t in (primary, htask):
                if not t.done():
                    t.cancel()

    async def _fire_hedge(self, payload: Any,
                          headers: Optional[Dict[str, Any]],
                          primary_iid: int, deadline: Optional[float]
                          ) -> Optional[Tuple[int, ResponseStream]]:
        """Open the hedge stream on the next-best instance, if the fleet,
        deadline, and retry budget allow.  Returns None when no hedge fires."""
        pol = self.policy
        try:
            alt, _ = self._select(exclude={primary_iid})
        except ConnectionError:
            return None
        if alt == primary_iid:
            return None  # single-instance fleet: nowhere to hedge
        if not pol.can_redispatch(alt, deadline):
            # satellite-1 guard: an expired hedge is never dispatched
            pol.stats.hedges["expired"] += 1
            return None
        if not pol.budget.try_spend():
            pol.stats.hedges["denied"] += 1
            return None
        hpayload = payload
        hheaders = headers
        if isinstance(payload, dict) and payload.get("request_id"):
            hpayload = dict(payload)
            hpayload["request_id"] = f"{payload['request_id']}~h1"
        if headers and headers.get(REQUEST_ID_HEADER):
            hheaders = dict(headers)
            hheaders[REQUEST_ID_HEADER] = f"{headers[REQUEST_ID_HEADER]}~h1"
        pol.breakers.on_dispatch(alt)
        try:
            stream = await self.client.direct(hpayload, alt, hheaders)
        except ConnectionError:
            pol.on_failure(alt, "connect")
            self.client.report_instance_down(alt)
            return None
        pol.stats.hedges["fired"] += 1
        span = self._current_span()
        if span is not None:
            span.add_event("hedge", instance=f"{alt:x}",
                           delay_s=round(pol.hedge_delay_s(), 4))
        return alt, stream

    @staticmethod
    def _unpack_first(task: "asyncio.Future") -> Tuple[Any, bool]:
        try:
            return task.result(), False
        except StopAsyncIteration:
            return None, True

    @staticmethod
    async def _await_first(task: "asyncio.Future") -> Tuple[Any, bool]:
        try:
            return await task, False
        except StopAsyncIteration:
            return None, True

    # -- decision tracing ---------------------------------------------------

    @staticmethod
    def _current_span():
        try:
            from dynamo_tpu.utils.tracing import get_tracer
            return get_tracer().current_span()
        except Exception:
            return None

    def _export_decision(self, iid: int,
                         inputs: Optional[Dict[str, Any]]) -> None:
        """Land the decision's score inputs on the request's current span —
        retrievable post-hoc from /v1/traces (the ROADMAP's "debuggable
        post-hoc" requirement)."""
        span = self._current_span()
        if span is None:
            return
        span.set_attr("router.policy", self.mode.value)
        span.set_attr("router.instance", f"{iid:x}")
        for key, value in (inputs or {}).items():
            span.set_attr(f"router.{key}", value)

    # -- stats scrape loop ---------------------------------------------------

    def _ensure_stats_loop(self) -> None:
        """COST mode polls the ``__stats__`` plane for queue depth / active
        slots; started lazily from the first routed request so the router
        needs no explicit async start hook."""
        if (self.mode is not RouterMode.COST or self.policy is None
                or self.policy.cfg.stats_interval_s <= 0):
            return
        if self._stats_task is None or self._stats_task.done():
            self._stats_task = asyncio.create_task(self._stats_loop())

    async def _stats_loop(self) -> None:
        while True:
            try:
                scraped = await self.client.scrape_stats()
                self.policy.ingest_scrape(scraped, self.client.endpoint.path)
                self.policy.prune(set(self.client.instance_ids()))
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.debug("router stats scrape failed", exc_info=True)
            await asyncio.sleep(self.policy.cfg.stats_interval_s)

    async def close(self) -> None:
        await reap_task(self._stats_task)
        self._stats_task = None


__all__ = ["PushRouter", "RouterMode"]
