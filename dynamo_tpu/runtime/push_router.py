"""PushRouter: load-balancing request fan-out with fault detection.

Parity: reference ``lib/runtime/src/pipeline/network/egress/push_router.rs``
(``RouterMode::{RoundRobin, Random, Direct, KV}``, NoResponders/stream-drop
instance-down marking).  The KV mode lives in ``dynamo_tpu.kv_router`` and
wraps this router.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
import time
from typing import Any, AsyncIterator, Dict, Optional

from dynamo_tpu.runtime.client import Client
from dynamo_tpu.utils.aio import decorrelated_jitter
from dynamo_tpu.runtime.rpc import (
    DEADLINE_HEADER,
    DeadlineExceededError,
    ResponseStream,
    StreamEndedError,
)

logger = logging.getLogger(__name__)


class RouterMode(enum.Enum):
    ROUND_ROBIN = "round-robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"


class PushRouter:
    """Routes requests across an endpoint's live instances."""

    def __init__(self, client: Client, mode: RouterMode = RouterMode.ROUND_ROBIN,
                 retries: int = 3, backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0):
        self.client = client
        self.mode = mode
        self.retries = retries
        # decorrelated-jitter backoff between failover attempts: during an
        # outage a tight retry loop hammers the surviving instances at the
        # exact moment they're absorbing the failed one's traffic (0 = off)
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._rr = 0

    def select_instance(self) -> int:
        ids = sorted(self.client.instance_ids())
        if not ids:
            raise ConnectionError(
                f"no instances available for {self.client.endpoint.path}")
        if self.mode == RouterMode.RANDOM:
            return random.choice(ids)
        chosen = ids[self._rr % len(ids)]
        self._rr += 1
        return chosen

    async def _open(self, payload: Any, instance_id: Optional[int],
                    headers: Optional[Dict[str, Any]]
                    ) -> "tuple[int, ResponseStream]":
        """Open a response stream; returns (chosen_instance_id, stream).

        Connect-level failures on router-selected instances fail over to other
        instances (up to ``retries``) and mark the unreachable one down.  A
        caller-pinned ``instance_id`` is never silently rerouted.
        """
        last_err: Optional[Exception] = None
        attempts = max(1, self.retries)
        sleep_s = self.backoff_base_s
        deadline = (headers or {}).get(DEADLINE_HEADER)
        for attempt in range(attempts):
            if deadline is not None and time.time() >= deadline:
                # failover must not hold a request past its deadline, nor
                # dispatch already-expired work a worker will only drop
                raise DeadlineExceededError(
                    "request deadline exceeded during failover")
            iid = instance_id if instance_id is not None else self.select_instance()
            try:
                return iid, await self.client.direct(payload, iid, headers)
            except ConnectionError as e:
                last_err = e
                self.client.report_instance_down(iid)
                if instance_id is not None:
                    break  # caller pinned the instance; don't fail over silently
                if attempt + 1 < attempts and self.backoff_base_s > 0:
                    sleep_s = decorrelated_jitter(
                        sleep_s, self.backoff_base_s, self.backoff_cap_s)
                    if deadline is not None:
                        sleep_s = min(sleep_s, max(0.0, deadline - time.time()))
                    await asyncio.sleep(sleep_s)
        raise ConnectionError(
            f"all attempts to reach {self.client.endpoint.path} failed: {last_err}")

    async def generate(self, payload: Any, instance_id: Optional[int] = None,
                       headers: Optional[Dict[str, Any]] = None) -> ResponseStream:
        _iid, stream = await self._open(payload, instance_id, headers)
        return stream

    async def generate_stream(self, payload: Any,
                              instance_id: Optional[int] = None,
                              headers: Optional[Dict[str, Any]] = None
                              ) -> AsyncIterator[Any]:
        """Convenience: iterate response payloads; marks the instance down on
        mid-stream drop and re-raises ``StreamEndedError`` for the migration
        operator to handle."""
        iid, stream = await self._open(payload, instance_id, headers)
        try:
            async for item in stream:
                yield item
        except StreamEndedError:
            self.client.report_instance_down(iid)
            raise
        finally:
            # Consumer stopped early (stop string, disconnect, GeneratorExit):
            # tell the worker to abort generation instead of streaming into a
            # queue nobody reads.
            if not stream.finished:
                await stream.cancel()


__all__ = ["PushRouter", "RouterMode"]
