"""Per-process system HTTP server: /health, /live, /metrics, /v1/traces.

Parity: reference ``lib/runtime/src/http_server.rs:104-140`` — every process
(worker, frontend, router) can expose a small operational server, enabled by
``DYN_SYSTEM_ENABLED=1`` on port ``DYN_SYSTEM_PORT`` (0 = ephemeral).
Health is endpoint-gated like the reference's ``SystemHealth``: the process
is "ready" once every registered subsystem reports ready.

When constructed with a ``tracer`` (``utils/tracing.Tracer``) the server
also exposes that process's flight recorder: ``GET /v1/traces`` (newest
first, ``?limit=&offset=&request_id=`` pagination/lookup) and
``GET /v1/traces/{trace_id}`` (the full span tree); with a ``steptrace``
(``engine/steptrace.StepRecorder``) it exposes the engine step timeline
on ``GET /v1/steptrace`` — see ``docs/observability.md``.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Dict, Optional

from aiohttp import web
from prometheus_client import CollectorRegistry, generate_latest

logger = logging.getLogger(__name__)


def coord_ready_reasons(coord) -> list:
    """Readiness reasons from a control-plane handle — shared between the
    system server and the HTTP frontend so the LB-facing contract cannot
    drift.  ``coord`` is a ``CoordClient`` (ready while its supervised
    connection is up and resynced) or a server-side ``Coordinator`` (ready
    while it is the acting primary); returns [] when ready."""
    if coord is None:
        return []
    connected = getattr(coord, "connected", None)
    if connected is not None:
        return [] if connected else ["coordinator disconnected"]
    if getattr(coord, "role", "primary") != "primary":
        return [f"coordinator role: {coord.role}"]
    return []


class SystemHealth:
    """Named readiness flags; unhealthy until every flag is set."""

    def __init__(self) -> None:
        self._ready: Dict[str, bool] = {}

    def register(self, name: str, ready: bool = False) -> None:
        self._ready[name] = ready

    def set_ready(self, name: str, ready: bool = True) -> None:
        self._ready[name] = ready

    @property
    def healthy(self) -> bool:
        return all(self._ready.values()) if self._ready else True

    def snapshot(self) -> Dict[str, bool]:
        return dict(self._ready)


class SystemServer:
    def __init__(self, health: Optional[SystemHealth] = None,
                 registry: Optional[CollectorRegistry] = None,
                 extra_metrics: Optional[Callable[[], bytes]] = None,
                 host: str = "0.0.0.0", port: int = 0,
                 tracer=None, steptrace=None):
        self.health = health or SystemHealth()
        self.registry = registry
        self.extra_metrics = extra_metrics
        self.tracer = tracer
        self.steptrace = steptrace
        self.host = host
        self.port = port
        self.app = web.Application()
        self.app.router.add_get("/health", self.handle_health)
        self.app.router.add_get("/live", self.handle_live)
        self.app.router.add_get("/healthz", self.handle_live)
        self.app.router.add_get("/healthz/ready", self.handle_ready)
        self.app.router.add_get("/metrics", self.handle_metrics)
        self.app.router.add_get("/v1/traces", self.handle_traces)
        self.app.router.add_get("/v1/traces/{trace_id}", self.handle_trace)
        self.app.router.add_get("/v1/steptrace", self.handle_steptrace)
        self.app.router.add_post("/drain", self.handle_drain)
        # graceful-drain hook (worker/drain.DrainController): POST /drain
        # triggers it; absent on processes with nothing to drain
        self._drain = None
        # control-plane readiness hook: a CoordClient (readiness follows
        # its supervised connection) or an in-process Coordinator
        # (readiness == acting primary)
        self._coord = None
        self._runner: Optional[web.AppRunner] = None

    def register_drain(self, controller) -> None:
        """Expose a ``DrainController`` on ``POST /drain`` (the operator/
        planner-facing trigger next to SIGTERM)."""
        self._drain = controller

    def attach_coord(self, coord) -> None:
        """Gate ``GET /healthz/ready`` on control-plane state: a
        ``CoordClient`` (ready while its supervised connection is up and
        resynced) or a server-side ``Coordinator`` (ready while it is the
        acting primary)."""
        self._coord = coord

    @classmethod
    def from_env(cls, **kwargs) -> Optional["SystemServer"]:
        """None unless DYN_SYSTEM_ENABLED is truthy."""
        if os.environ.get("DYN_SYSTEM_ENABLED", "").lower() not in (
                "1", "true", "yes"):
            return None
        port = int(os.environ.get("DYN_SYSTEM_PORT", "0"))
        return cls(port=port, **kwargs)

    async def start(self) -> "SystemServer":
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
        logger.info("system server on %s:%d", self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def handle_health(self, request: web.Request) -> web.Response:
        ok = self.health.healthy
        return web.json_response(
            {"status": "healthy" if ok else "unhealthy",
             "subsystems": self.health.snapshot()},
            status=200 if ok else 503)

    async def handle_live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def handle_ready(self, request: web.Request) -> web.Response:
        """Readiness (vs. /healthz liveness): 503 while the control-plane
        connection is down, during a drain, or while a registered
        subsystem is not ready — so load balancers stop routing new work
        into an outage instead of eating 5xx storms.  The process stays
        LIVE (200 on /healthz) the whole time: killing it would only turn
        a reconnect into a cold start."""
        reasons = coord_ready_reasons(self._coord)
        if self._drain is not None and self._drain.draining:
            reasons.append(f"draining ({self._drain.state})")
        if not self.health.healthy:
            reasons.append("subsystems not ready")
        ready = not reasons
        return web.json_response(
            {"ready": ready, "reasons": reasons,
             "subsystems": self.health.snapshot()},
            status=200 if ready else 503)

    async def handle_metrics(self, request: web.Request) -> web.Response:
        body = b""
        if self.registry is not None:
            body += generate_latest(self.registry)
        if self.extra_metrics is not None:
            body += self.extra_metrics()
        return web.Response(body=body, content_type="text/plain")

    async def handle_drain(self, request: web.Request) -> web.Response:
        if self._drain is None:
            return web.json_response(
                {"error": "this process has no drainable endpoint"},
                status=404)
        # fire-and-return: the drain (freeze + lease-ack wait) can take up
        # to DYN_DRAIN_TIMEOUT_S — the caller polls state via repeat POSTs
        # or the dynamo_worker_drain_state gauge
        self._drain.trigger("POST /drain")
        return web.json_response({"state": self._drain.state,
                                  "counts": self._drain.counts})

    async def handle_traces(self, request: web.Request) -> web.Response:
        return trace_list_response(self.tracer, request)

    async def handle_trace(self, request: web.Request) -> web.Response:
        return trace_get_response(self.tracer,
                                  request.match_info["trace_id"])

    async def handle_steptrace(self, request: web.Request) -> web.Response:
        return steptrace_response(self.steptrace, request)


def trace_list_response(tracer, request: web.Request) -> web.Response:
    """``GET /v1/traces`` body from a flight recorder — shared between the
    system server and the HTTP frontend so the surface cannot drift."""
    if tracer is None:
        return web.json_response(
            {"error": "tracing is not enabled on this process"}, status=404)
    try:
        limit = int(request.query.get("limit", "50"))
        offset = int(request.query.get("offset", "0"))
    except ValueError:
        return web.json_response(
            {"error": "limit/offset must be integers"}, status=400)
    return web.json_response(tracer.traces(
        limit=limit, offset=offset,
        request_id=request.query.get("request_id", "")))


def steptrace_response(recorder, request: web.Request) -> web.Response:
    """``GET /v1/steptrace`` body from an engine step flight recorder
    (``engine/steptrace.StepRecorder``): newest-first StepRecords with
    ``?limit=&offset=`` pagination."""
    if recorder is None:
        return web.json_response(
            {"error": "step tracing is not enabled on this process"},
            status=404)
    try:
        limit = int(request.query.get("limit", "100"))
        offset = int(request.query.get("offset", "0"))
    except ValueError:
        return web.json_response(
            {"error": "limit/offset must be integers"}, status=400)
    return web.json_response(recorder.snapshot(limit=limit, offset=offset))


def trace_get_response(tracer, trace_id: str) -> web.Response:
    if tracer is None:
        return web.json_response(
            {"error": "tracing is not enabled on this process"}, status=404)
    record = tracer.get_trace(trace_id)
    if record is None:
        return web.json_response(
            {"error": f"no such trace: {trace_id} (evicted or sampled "
                      "out of the flight recorder)"}, status=404)
    return web.json_response(record)


__all__ = ["SystemServer", "SystemHealth", "coord_ready_reasons",
           "trace_list_response", "trace_get_response",
           "steptrace_response"]
