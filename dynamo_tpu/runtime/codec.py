"""Length-prefixed msgpack framing over asyncio streams.

Every frame on a control- or data-plane connection is ``<u32 big-endian
length><msgpack payload>``.  Parity in spirit with the reference's two-part
codec (``lib/runtime/src/pipeline/network/codec/two_part.rs``): a frame is a
msgpack map whose "header" fields (op, ids) and "payload" (bin) travel
together; msgpack bin avoids a second length-prefix layer.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Optional

import msgpack

MAX_FRAME = 512 * 1024 * 1024  # 512 MiB hard cap (KV block transfers ride this)

_LEN = struct.Struct(">I")


def pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


async def read_frame(reader: asyncio.StreamReader) -> Optional[Any]:
    """Read one frame; returns None on clean EOF."""
    try:
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = _LEN.unpack(hdr)
    if length > MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds cap {MAX_FRAME}")
    try:
        body = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return unpack(body)


def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    """Queue one frame on the writer (call ``await writer.drain()`` for backpressure)."""
    body = pack(obj)
    writer.write(_LEN.pack(len(body)) + body)


async def send_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    write_frame(writer, obj)
    await writer.drain()


__all__ = ["pack", "unpack", "read_frame", "write_frame", "send_frame", "MAX_FRAME"]
