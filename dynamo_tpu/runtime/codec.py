"""Length-prefixed msgpack framing over asyncio streams.

Every frame on a control- or data-plane connection is ``<u32 big-endian
length><msgpack payload>``.  Parity in spirit with the reference's two-part
codec (``lib/runtime/src/pipeline/network/codec/two_part.rs``): a frame is a
msgpack map whose "header" fields (op, ids) and "payload" (bin) travel
together; msgpack bin avoids a second length-prefix layer.

TWO-PART frames carry bulk binary (KV block transfers) without msgpack
re-copies: the u32 length has its high bit set, the msgpack part holds the
metadata, and a ``<u32 raw length><raw bytes>`` trailer follows. The raw
bytes are written straight from the source buffer (a numpy view — no
``tobytes``/msgpack/concat copies on the send side) and surface on the
receive side under the ``"_raw"`` key of the decoded map. This is the
replacement for the reference codec's header+payload split that NIXL-bound
block data rode (``block/transfer/nixl.rs``).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Optional

import msgpack

MAX_FRAME = 512 * 1024 * 1024  # 512 MiB hard cap (KV block transfers ride this)

_LEN = struct.Struct(">I")
_RAW_BIT = 0x8000_0000


class Raw:
    """A stream item whose bulk bytes should ride a two-part frame.

    Handlers yield ``Raw(meta_dict, buffer)``; the RPC layer sends the
    metadata as the msgpack part and the buffer as the raw trailer. The
    receiving side sees ``meta_dict`` with ``"_raw"`` holding the bytes.
    """

    __slots__ = ("obj", "raw")

    def __init__(self, obj: Any, raw: Any):
        self.obj = obj
        self.raw = raw


def byte_view(raw: Any) -> memoryview:
    """Flat byte view of any buffer, including numpy arrays of extension
    dtypes (bfloat16 etc.) that reject the buffer protocol — those are
    reinterpreted as uint8 first (zero-copy for contiguous arrays)."""
    try:
        return memoryview(raw).cast("B")
    except (TypeError, ValueError):
        import numpy as np

        arr = np.ascontiguousarray(np.asarray(raw))
        return memoryview(arr.view(np.uint8)).cast("B")


def pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


async def read_frame(reader: asyncio.StreamReader) -> Optional[Any]:
    """Read one frame; returns None on clean EOF. A two-part frame's raw
    trailer is attached to the decoded map as ``obj["_raw"]``."""
    try:
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = _LEN.unpack(hdr)
    raw_follows = bool(length & _RAW_BIT)
    length &= ~_RAW_BIT
    if length > MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds cap {MAX_FRAME}")
    try:
        body = await reader.readexactly(length)
        obj = unpack(body)
        if raw_follows:
            (raw_len,) = _LEN.unpack(await reader.readexactly(4))
            if raw_len > MAX_FRAME:
                raise ValueError(f"raw length {raw_len} exceeds cap")
            obj["_raw"] = await reader.readexactly(raw_len)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return obj


def write_frame(writer: asyncio.StreamWriter, obj: Any,
                raw: Optional[Any] = None) -> None:
    """Queue one frame on the writer (call ``await writer.drain()`` for
    backpressure). ``raw`` (bytes/memoryview/numpy buffer) rides as a
    two-part trailer with zero intermediate copies on this side."""
    body = pack(obj)
    if raw is None:
        writer.write(_LEN.pack(len(body)))
        writer.write(body)
        return
    view = byte_view(raw)
    writer.write(_LEN.pack(len(body) | _RAW_BIT))
    writer.write(body)
    writer.write(_LEN.pack(view.nbytes))
    writer.write(view)


async def send_frame(writer: asyncio.StreamWriter, obj: Any,
                     raw: Optional[Any] = None) -> None:
    write_frame(writer, obj, raw)
    await writer.drain()


__all__ = ["pack", "unpack", "read_frame", "write_frame", "send_frame",
           "MAX_FRAME", "Raw", "byte_view"]
