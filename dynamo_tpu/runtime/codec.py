"""Length-prefixed msgpack framing over asyncio streams.

Every frame on a control- or data-plane connection is ``<u32 big-endian
length><msgpack payload>``.  Parity in spirit with the reference's two-part
codec (``lib/runtime/src/pipeline/network/codec/two_part.rs``): a frame is a
msgpack map whose "header" fields (op, ids) and "payload" (bin) travel
together; msgpack bin avoids a second length-prefix layer.

TWO-PART frames carry bulk binary (KV block transfers) without msgpack
re-copies: the u32 length has its high bit set, the msgpack part holds the
metadata, and a ``<u32 raw length><raw bytes>`` trailer follows. The raw
bytes are written straight from the source buffer (a numpy view — no
``tobytes``/msgpack/concat copies on the send side) and surface on the
receive side under the ``"_raw"`` key of the decoded map — as a POOLED
uint8 buffer for multi-MB trailers (chunked reads skip the StreamReader
join copy; consumers may ``release_buffer`` it for warm reuse). This is
the replacement for the reference codec's header+payload split that
NIXL-bound block data rode (``block/transfer/nixl.rs``).

Ceiling note (VERDICT r4 weak 3): even pooled, asyncio stream framing
tops out ~1.3-1.5 GB/s on loopback; the bulk plane (``runtime/bulk.py``,
raw sockets + recv_into) does ~2+ GB/s and is ALWAYS advertised by
prefill workers — this RPC path is the control plane and the cross-host
fallback, not the default KV data plane.
"""

from __future__ import annotations

import asyncio
import struct
import threading
from typing import Any, Dict, List, Optional

import msgpack

MAX_FRAME = 512 * 1024 * 1024  # 512 MiB hard cap (KV block transfers ride this)

_LEN = struct.Struct(">I")
_RAW_BIT = 0x8000_0000

# Receive-buffer freelist, shared by this codec's large-trailer reads and
# the bulk plane (runtime/bulk.py). Faulting in fresh anonymous pages for
# every multi-MB frame costs more than the socket itself (measured:
# 1.9 GB/s into a warm buffer vs 0.7 into a fresh one on this host class).
# Buffers are np.empty so pages are NOT memset; a consumer that is done
# with a frame calls ``release_buffer(raw)`` and the next receive of the
# same frame size reuses the warm pages. Unreleased buffers are simply
# garbage-collected — release is an optimization, never a correctness
# requirement.
_BUF_POOL_PER_SIZE = 4
_buf_pool: Dict[int, List[Any]] = {}
_buf_lock = threading.Lock()


def buf_get(nbytes: int):
    import numpy as _np

    with _buf_lock:
        free = _buf_pool.get(nbytes)
        if free:
            return free.pop()
    return _np.empty(nbytes, _np.uint8)


def release_buffer(raw: Any) -> None:
    """Return a frame buffer (from ``bulk_fetch`` or a two-part RPC frame's
    ``_raw``) to the freelist after the consumer has fully copied/used it.
    Double-releasing the same buffer is ignored — pooling one ndarray twice
    would hand it to two concurrent fetches and interleave their frames."""
    if not hasattr(raw, "nbytes"):
        return
    with _buf_lock:
        free = _buf_pool.setdefault(raw.nbytes, [])
        if len(free) < _BUF_POOL_PER_SIZE \
                and not any(b is raw for b in free):
            free.append(raw)


# trailers at least this large read into a pooled buffer via chunked
# ``reader.read`` instead of ``readexactly`` — skipping the StreamReader's
# join copy is worth ~25% of wire throughput at KV-block sizes (the small
# frames stay plain bytes: hashable, cheap, and pooling them would churn)
_POOLED_RAW_MIN = 1024 * 1024
_POOLED_READ_CHUNK = 4 * 1024 * 1024


class Raw:
    """A stream item whose bulk bytes should ride a two-part frame.

    Handlers yield ``Raw(meta_dict, buffer)``; the RPC layer sends the
    metadata as the msgpack part and the buffer as the raw trailer. The
    receiving side sees ``meta_dict`` with ``"_raw"`` holding the bytes.
    """

    __slots__ = ("obj", "raw")

    def __init__(self, obj: Any, raw: Any):
        self.obj = obj
        self.raw = raw


def byte_view(raw: Any) -> memoryview:
    """Flat byte view of any buffer, including numpy arrays of extension
    dtypes (bfloat16 etc.) that reject the buffer protocol — those are
    reinterpreted as uint8 first (zero-copy for contiguous arrays)."""
    try:
        return memoryview(raw).cast("B")
    except (TypeError, ValueError):
        import numpy as np

        arr = np.ascontiguousarray(np.asarray(raw))
        return memoryview(arr.view(np.uint8)).cast("B")


def pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


async def read_frame(reader: asyncio.StreamReader) -> Optional[Any]:
    """Read one frame; returns None on clean EOF. A two-part frame's raw
    trailer is attached to the decoded map as ``obj["_raw"]``."""
    try:
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = _LEN.unpack(hdr)
    raw_follows = bool(length & _RAW_BIT)
    length &= ~_RAW_BIT
    if length > MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds cap {MAX_FRAME}")
    try:
        body = await reader.readexactly(length)
        obj = unpack(body)
        if raw_follows:
            (raw_len,) = _LEN.unpack(await reader.readexactly(4))
            if raw_len > MAX_FRAME:
                raise ValueError(f"raw length {raw_len} exceeds cap")
            if raw_len >= _POOLED_RAW_MIN:
                # large trailer: chunked reads straight into a pooled
                # uint8 buffer (consumers may release_buffer() it back)
                buf = buf_get(raw_len)
                mv = memoryview(buf)
                got = 0
                while got < raw_len:
                    chunk = await reader.read(
                        min(raw_len - got, _POOLED_READ_CHUNK))
                    if not chunk:
                        return None  # mid-frame EOF, like IncompleteRead
                    mv[got:got + len(chunk)] = chunk
                    got += len(chunk)
                obj["_raw"] = buf
            else:
                obj["_raw"] = await reader.readexactly(raw_len)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return obj


def write_frame(writer: asyncio.StreamWriter, obj: Any,
                raw: Optional[Any] = None) -> None:
    """Queue one frame on the writer (call ``await writer.drain()`` for
    backpressure). ``raw`` (bytes/memoryview/numpy buffer) rides as a
    two-part trailer with zero intermediate copies on this side."""
    body = pack(obj)
    if raw is None:
        writer.write(_LEN.pack(len(body)))
        writer.write(body)
        return
    view = byte_view(raw)
    writer.write(_LEN.pack(len(body) | _RAW_BIT))
    writer.write(body)
    writer.write(_LEN.pack(view.nbytes))
    writer.write(view)


async def send_frame(writer: asyncio.StreamWriter, obj: Any,
                     raw: Optional[Any] = None) -> None:
    write_frame(writer, obj, raw)
    await writer.drain()


__all__ = ["pack", "unpack", "read_frame", "write_frame", "send_frame",
           "MAX_FRAME", "Raw", "byte_view", "buf_get", "release_buffer"]
