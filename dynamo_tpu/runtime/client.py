"""Endpoint client: watches live instances and issues streamed requests.

Parity: reference ``lib/runtime/src/component/client.rs`` (264 LoC) —
``Client::new_dynamic`` with an etcd prefix watch keeping an atomic snapshot of
instance ids, plus ``report_instance_down`` local pruning ahead of lease
expiry.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable, Dict, List, Optional

from dynamo_tpu.runtime.component import Endpoint, Instance
from dynamo_tpu.runtime.rpc import ResponseStream
from dynamo_tpu.utils.aio import reap_task

logger = logging.getLogger(__name__)


class Client:
    """Dynamic client for one endpoint."""

    def __init__(self, drt, endpoint: Endpoint):
        self._drt = drt
        self.endpoint = endpoint
        self._instances: Dict[int, Instance] = {}
        self._down: set = set()  # locally-reported-down instance ids
        self._watch = None
        self._watch_task: Optional[asyncio.Task] = None
        self._changed = asyncio.Event()
        # instance-id down listeners (router policy breakers): fired once per
        # report, from both the keepalive path and explicit error reports
        self._down_listeners: List[Callable[[int], None]] = []

    @classmethod
    async def create(cls, drt, endpoint: Endpoint, static: bool = False) -> "Client":
        self = cls(drt, endpoint)
        if static:
            for inst in await endpoint.list_instances():
                self._instances[inst.instance_id] = inst
        else:
            self._watch = await drt.coord.watch_prefix(endpoint.instance_prefix)
            for _key, value in self._watch.snapshot:
                inst = Instance.from_json(value)
                self._instances[inst.instance_id] = inst
            self._watch_task = asyncio.create_task(self._watch_loop())
        # keepalive/conn-death notifications from the shared pool: a
        # connection that dies without an explicit drop marks its backing
        # instance(s) down here, ahead of lease expiry
        drt.rpc_pool.add_down_listener(self._on_address_down)
        return self

    async def _watch_loop(self) -> None:
        # NOTE: never catch CancelledError here — swallowing it breaks
        # cancellation of any task awaiting this one (asyncio delegates
        # A.cancel() to B.cancel() when A awaits B).
        #
        # A coordinator crash does NOT end this stream: the supervised
        # CoordClient re-establishes the watch on reconnect and synthesizes
        # put/delete deltas from a prefix re-scan (including the instance-id
        # churn of re-granted leases). While the coordinator is down, no
        # events arrive and routing continues from the cached ``_instances``
        # snapshot. The stream ends only when the client is permanently
        # closed — at that point discovery is frozen on the last snapshot.
        async for ev in self._watch:
            if ev.type == "put" and ev.value is not None:
                inst = Instance.from_json(ev.value)
                self._instances[inst.instance_id] = inst
                self._down.discard(inst.instance_id)
            elif ev.type == "delete":
                iid = self._id_from_key(ev.key)
                if iid is not None:
                    self._instances.pop(iid, None)
                    self._down.discard(iid)
            self._changed.set()
            self._changed = asyncio.Event()
        logger.warning(
            "instance watch for %s ended (coordinator client closed); "
            "discovery frozen on %d cached instance(s)",
            self.endpoint.path, len(self._instances))

    @staticmethod
    def _id_from_key(key: str) -> Optional[int]:
        _, _, hexid = key.rpartition(":")
        try:
            return int(hexid, 16)
        except ValueError:
            return None

    # -- instance visibility ----------------------------------------------

    def instance_ids(self) -> List[int]:
        # draining instances are excluded from SELECTION (routers stop
        # sending new work the moment the drain announcement lands) but
        # stay directly addressable via get_instance/direct — in-flight
        # migrations still pull their pinned KV from them
        return [i for i, v in self._instances.items()
                if i not in self._down and not v.draining]

    def instances(self) -> List[Instance]:
        return [v for k, v in self._instances.items()
                if k not in self._down and not v.draining]

    def get_instance(self, instance_id: int) -> Optional[Instance]:
        if instance_id in self._down:
            return None
        return self._instances.get(instance_id)

    def report_instance_down(self, instance_id: int) -> None:
        """Locally mark an instance dead before the lease expiry catches up."""
        if instance_id in self._instances:
            logger.warning("instance %x of %s reported down",
                           instance_id, self.endpoint.path)
            self._down.add(instance_id)
            inst = self._instances.get(instance_id)
            if inst is not None:
                self._drt.rpc_pool.drop(inst.address)
            for cb in list(self._down_listeners):
                try:
                    cb(instance_id)
                except Exception:
                    logger.exception("instance-down listener failed")

    def add_down_listener(self, cb: Callable[[int], None]) -> None:
        """Subscribe to instance-down reports (called with the instance id).
        Both keepalive miss-budget exhaustion and router error reports
        funnel through ``report_instance_down``, so one hook sees both."""
        self._down_listeners.append(cb)

    def remove_down_listener(self, cb: Callable[[int], None]) -> None:
        try:
            self._down_listeners.remove(cb)
        except ValueError:
            pass

    def _on_address_down(self, address: str) -> None:
        """Pool notification: a connection died unexpectedly (remote crash or
        keepalive miss-budget exhaustion) — mark the instance(s) at that
        address down.  ``report_instance_down``'s pool drop is a no-op here
        (the pool already evicted the dead connection), so no recursion."""
        for iid, inst in list(self._instances.items()):
            if inst.address == address and iid not in self._down:
                self.report_instance_down(iid)

    async def wait_for_instances(self, n: int = 1, timeout: float = 30.0) -> List[Instance]:
        """Block until at least ``n`` instances are visible."""
        deadline = asyncio.get_running_loop().time() + timeout
        while len(self.instance_ids()) < n:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"timed out waiting for {n} instances of {self.endpoint.path} "
                    f"(have {len(self.instance_ids())})")
            changed = self._changed
            try:
                await asyncio.wait_for(changed.wait(), timeout=min(remaining, 0.5))
            except asyncio.TimeoutError:
                pass
        return self.instances()

    # -- request issuing ---------------------------------------------------

    async def scrape_stats(self) -> Dict[int, Any]:
        """Poll the ``__stats__`` plane of every live instance (queue depth /
        in-flight for the routing cost model).  Unreachable instances are
        simply absent from the result — the scorer treats missing stats as
        unknown, and the request path's own error handling marks them down."""
        out: Dict[int, Any] = {}
        for inst in self.instances():
            try:
                conn = await self._drt.rpc_pool.get(inst.address)
                stream = await conn.request("__stats__", None)
                async for item in stream:
                    out[inst.instance_id] = item
                    break
                if not stream.finished:
                    await stream.cancel()
            except Exception:
                continue
        return out

    async def direct(self, payload: Any, instance_id: int,
                     headers: Optional[Dict[str, Any]] = None) -> ResponseStream:
        """Issue a request to a specific instance."""
        inst = self._instances.get(instance_id)
        if inst is None or instance_id in self._down:
            raise ConnectionError(
                f"instance {instance_id:x} of {self.endpoint.path} not available")
        conn = await self._drt.rpc_pool.get(inst.address)
        return await conn.request(f"{self.endpoint.path}", payload, headers)

    async def close(self) -> None:
        self._drt.rpc_pool.remove_down_listener(self._on_address_down)
        await reap_task(self._watch_task)
        if self._watch is not None:
            try:
                await self._watch.cancel()
            except Exception:
                pass


__all__ = ["Client"]
