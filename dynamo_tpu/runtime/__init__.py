"""Core distributed runtime: coordination, discovery, streaming RPC, routing.

Parity: reference ``lib/runtime/`` (Rust, ~19k LoC) — see SURVEY.md §2.1.  The
reference composes external etcd (discovery/lease/watch) + NATS (request
transport, events) + raw TCP (response streams).  This runtime is
self-contained: a single ``Coordinator`` service provides the etcd-equivalent
KV/lease/watch plane *and* the NATS-equivalent pub/sub event plane, and the
request/response data plane is direct duplex TCP between clients and workers
(``dynamo_tpu.runtime.rpc``).
"""

from dynamo_tpu.runtime.coordinator import Coordinator, CoordClient
from dynamo_tpu.runtime.runtime import DistributedRuntime, Runtime
from dynamo_tpu.runtime.component import Component, Endpoint, Instance, Namespace
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode

__all__ = [
    "Coordinator",
    "CoordClient",
    "Runtime",
    "DistributedRuntime",
    "Namespace",
    "Component",
    "Endpoint",
    "Instance",
    "PushRouter",
    "RouterMode",
]
