"""Streaming RPC data plane: direct duplex TCP between callers and workers.

The reference splits the data plane: requests ride NATS to the worker's service
subject, and the worker dials back a raw TCP stream for the response
(``lib/runtime/src/pipeline/network/egress/addressed_router.rs:86-161``,
``ingress/push_handler.rs:25-133``).  That split exists because NATS provides
the discovery/queueing.  Here discovery comes from the coordinator, so we use
one duplex TCP connection per (caller, worker) pair and multiplex many
concurrent request streams over it with stream ids — fewer hops, same
semantics: a request frame out, a stream of response frames back, terminated by
a ``final`` sentinel (the sentinel is how stream-drop faults are detected:
missing ``final`` == "stream ended before generation completed").

Server side: ``RpcServer`` hosts named endpoints.  A handler is an async
callable ``handler(payload, ctx) -> AsyncIterator[Any]``; whatever it yields is
msgpack-framed back.  Cancellation: callers send a ``cancel`` frame; the
handler's task is cancelled and ``ctx.cancelled`` is set (parity with
``AsyncEngineContext.stop_generating``, reference ``lib/runtime/src/engine.rs``).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, Optional

from dynamo_tpu.runtime.codec import Raw, read_frame, send_frame
from dynamo_tpu.utils.aio import reap_task

logger = logging.getLogger(__name__)

Handler = Callable[[Any, "RequestContext"], AsyncIterator[Any]]


class StreamEndedError(ConnectionError):
    """Response stream dropped before the final sentinel arrived.

    The migration operator keys on this (reference ``lib/llm/src/migration.rs``:
    "Stream ended before generation completed")."""


@dataclass
class RequestContext:
    """Per-request context passed to endpoint handlers."""

    request_id: str
    endpoint: str
    headers: Dict[str, Any] = field(default_factory=dict)
    _cancel_event: asyncio.Event = field(default_factory=asyncio.Event)

    @property
    def cancelled(self) -> bool:
        return self._cancel_event.is_set()

    def cancel(self) -> None:
        self._cancel_event.set()

    async def wait_cancelled(self) -> None:
        await self._cancel_event.wait()


@dataclass
class EndpointStats:
    """Per-endpoint counters, scraped via the ``__stats__`` builtin endpoint
    (parity: NATS ``$SRV.STATS`` scraping, reference ``metrics_aggregator.rs``)."""

    requests: int = 0
    active: int = 0
    errors: int = 0
    data: Dict[str, Any] = field(default_factory=dict)  # custom stats-handler payload


class RpcServer:
    """Hosts endpoint handlers on one TCP listen port per process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: Dict[str, Handler] = {}
        self._stats: Dict[str, EndpointStats] = {}
        self._stats_providers: Dict[str, Callable[[], Any]] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._active_tasks: set = set()
        self._conn_writers: set = set()

    def register(self, endpoint: str, handler: Handler,
                 stats_provider: Optional[Callable[[], Any]] = None) -> None:
        self._handlers[endpoint] = handler
        self._stats.setdefault(endpoint, EndpointStats())
        if stats_provider is not None:
            self._stats_providers[endpoint] = stats_provider

    def unregister(self, endpoint: str) -> None:
        self._handlers.pop(endpoint, None)
        self._stats_providers.pop(endpoint, None)

    def stats(self, endpoint: str) -> EndpointStats:
        return self._stats.setdefault(endpoint, EndpointStats())

    async def start(self) -> "RpcServer":
        # 16 MiB stream buffers: KV-block frames are tens of MB; the 64 KiB
        # default limit makes readexactly drain them in tiny wakeups
        # (measured 0.9 -> multi-GB/s loopback with the larger window)
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, limit=16 * 1024 * 1024)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server:
            self._server.close()
        # never cancel/await the task calling stop() (a handler may trigger
        # shutdown of its own server) — that would self-cancel forever
        cur = asyncio.current_task()
        tasks = [t for t in self._active_tasks if t is not cur]
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        # close live connections BEFORE wait_closed: since py3.12 wait_closed
        # blocks until every connection handler returns
        for w in list(self._conn_writers):
            try:
                w.close()
            except Exception:
                pass
        if self._server:
            await self._server.wait_closed()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        wlock = asyncio.Lock()
        streams: Dict[int, RequestContext] = {}
        stream_tasks: Dict[int, asyncio.Task] = {}
        self._conn_writers.add(writer)

        async def send(obj: Any, raw: Any = None) -> None:
            async with wlock:
                await send_frame(writer, obj, raw)

        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                op = frame.get("op")
                if op == "req":
                    sid = frame["sid"]
                    ctx = RequestContext(
                        request_id=frame.get("headers", {}).get("request_id", str(sid)),
                        endpoint=frame["endpoint"],
                        headers=frame.get("headers", {}),
                    )
                    streams[sid] = ctx
                    task = asyncio.create_task(
                        self._run_stream(send, sid, ctx, frame.get("payload")))
                    self._active_tasks.add(task)
                    stream_tasks[sid] = task
                    task.add_done_callback(self._active_tasks.discard)
                    task.add_done_callback(lambda _t, s=sid: streams.pop(s, None))
                    task.add_done_callback(lambda _t, s=sid: stream_tasks.pop(s, None))
                elif op == "cancel":
                    # cooperative signal first (handlers can flush/cleanup via
                    # ctx.cancelled), then hard-cancel so a handler blocked in
                    # an await can't leak the stream slot forever
                    ctx = streams.get(frame["sid"])
                    if ctx:
                        ctx.cancel()
                    task = stream_tasks.get(frame["sid"])
                    if task is not None:
                        task.cancel()
                elif op == "ping":
                    await send({"op": "pong", "rid": frame.get("rid")})
        except ConnectionError:
            pass  # CancelledError must propagate (see utils/aio.reap_task)
        finally:
            self._conn_writers.discard(writer)
            for ctx in streams.values():
                ctx.cancel()
            try:
                writer.close()
            except Exception:
                pass

    async def _run_stream(self, send, sid: int, ctx: RequestContext,
                          payload: Any) -> None:
        name = ctx.endpoint
        if name == "__stats__":
            out = {
                ep: {"requests": st.requests, "active": st.active,
                     "errors": st.errors,
                     "data": (self._stats_providers[ep]()
                              if ep in self._stats_providers else st.data)}
                for ep, st in self._stats.items()
            }
            await send({"op": "data", "sid": sid, "payload": out})
            await send({"op": "final", "sid": sid})
            return
        handler = self._handlers.get(name)
        if handler is None:
            await send({"op": "err", "sid": sid,
                        "error": f"no such endpoint: {name}"})
            return
        st = self.stats(name)
        st.requests += 1
        st.active += 1
        try:
            agen = handler(payload, ctx)
            async for item in agen:
                if ctx.cancelled:
                    await agen.aclose()
                    break
                if isinstance(item, Raw):
                    # bulk binary (KV blocks): metadata in the msgpack part,
                    # bytes as a zero-copy two-part trailer
                    await send({"op": "data", "sid": sid,
                                "payload": item.obj}, raw=item.raw)
                else:
                    await send({"op": "data", "sid": sid, "payload": item})
            await send({"op": "final", "sid": sid})
        except asyncio.CancelledError:
            # caller cancelled (or server stopping): nothing more to send; the
            # client side tears its stream down locally on cancel
            raise
        except (ConnectionError, RuntimeError) as e:
            # connection gone: nothing more to send
            logger.debug("stream %d connection lost: %s", sid, e)
            st.errors += 1
        except Exception as e:
            st.errors += 1
            logger.exception("endpoint %s handler error", name)
            try:
                await send({"op": "err", "sid": sid, "error": str(e)})
            except Exception:
                pass
        finally:
            st.active -= 1


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class ResponseStream:
    """Async iterator over one request's response frames.

    Raises ``StreamEndedError`` if the connection drops before ``final``; a
    server-reported handler error raises ``RuntimeError``.
    """

    def __init__(self, conn: "RpcConnection", sid: int):
        self._conn = conn
        self.sid = sid
        self.queue: asyncio.Queue = asyncio.Queue()
        self.finished = False

    def __aiter__(self) -> "ResponseStream":
        return self

    async def __anext__(self) -> Any:
        if self.finished:
            raise StopAsyncIteration
        kind, value = await self.queue.get()
        if kind == "data":
            return value
        self.finished = True
        self._conn._streams.pop(self.sid, None)
        if kind == "final":
            raise StopAsyncIteration
        if kind == "err":
            raise RuntimeError(value)
        raise StreamEndedError("stream ended before generation completed")

    async def cancel(self) -> None:
        """Tell the worker to stop and finish this stream locally (the worker
        may be hard-cancelled mid-await and never send a final frame)."""
        await self._conn.send_cancel(self.sid)
        if not self.finished:
            self.finished = True
            self._conn._streams.pop(self.sid, None)


class RpcConnection:
    """One multiplexed duplex connection to a worker's RpcServer."""

    def __init__(self, address: str):
        host, _, port = address.rpartition(":")
        self.address = address
        self.host, self.port = host or "127.0.0.1", int(port)
        self._sids = itertools.count(1)
        self._streams: Dict[int, ResponseStream] = {}
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._wlock: Optional[asyncio.Lock] = None
        self._reader_task: Optional[asyncio.Task] = None
        self.alive = False

    async def connect(self) -> "RpcConnection":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, limit=16 * 1024 * 1024)
        self._wlock = asyncio.Lock()
        self._reader_task = asyncio.create_task(self._read_loop())
        self.alive = True
        return self

    async def close(self) -> None:
        self.alive = False
        await reap_task(self._reader_task)
        if self._writer:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame is None:
                    break
                op = frame.get("op")
                sid = frame.get("sid")
                stream = self._streams.get(sid)
                if stream is None:
                    continue
                if op == "data":
                    payload = frame.get("payload")
                    if "_raw" in frame and isinstance(payload, dict):
                        # two-part frame: surface the raw trailer inside the
                        # payload the handler yielded it with
                        payload["_raw"] = frame["_raw"]
                    stream.queue.put_nowait(("data", payload))
                elif op == "final":
                    stream.queue.put_nowait(("final", None))
                elif op == "err":
                    stream.queue.put_nowait(("err", frame.get("error")))
        except ConnectionError:
            pass  # CancelledError must propagate (see utils/aio.reap_task)
        finally:
            self.alive = False
            for stream in list(self._streams.values()):
                stream.queue.put_nowait(("drop", None))
            self._streams.clear()

    async def request(self, endpoint: str, payload: Any,
                      headers: Optional[Dict[str, Any]] = None) -> ResponseStream:
        if not self.alive:
            raise ConnectionError(f"connection to {self.address} is down")
        sid = next(self._sids)
        stream = ResponseStream(self, sid)
        self._streams[sid] = stream
        try:
            async with self._wlock:
                await send_frame(self._writer, {
                    "op": "req", "sid": sid, "endpoint": endpoint,
                    "payload": payload, "headers": headers or {}})
        except (ConnectionError, RuntimeError) as e:
            self._streams.pop(sid, None)
            self.alive = False
            raise ConnectionError(str(e)) from e
        return stream

    async def send_cancel(self, sid: int) -> None:
        if not self.alive:
            return
        try:
            async with self._wlock:
                await send_frame(self._writer, {"op": "cancel", "sid": sid})
        except (ConnectionError, RuntimeError):
            self.alive = False


class RpcClientPool:
    """Connection pool: one live RpcConnection per worker address."""

    def __init__(self) -> None:
        self._conns: Dict[str, RpcConnection] = {}
        self._locks: Dict[str, asyncio.Lock] = {}

    async def get(self, address: str) -> RpcConnection:
        conn = self._conns.get(address)
        if conn is not None and conn.alive:
            return conn
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._conns.get(address)
            if conn is not None and conn.alive:
                return conn
            conn = RpcConnection(address)
            await conn.connect()
            self._conns[address] = conn
            return conn

    def drop(self, address: str) -> None:
        conn = self._conns.pop(address, None)
        if conn is not None:
            asyncio.ensure_future(conn.close())

    async def close(self) -> None:
        for conn in list(self._conns.values()):
            await conn.close()
        self._conns.clear()


__all__ = [
    "RpcServer",
    "RpcConnection",
    "RpcClientPool",
    "ResponseStream",
    "RequestContext",
    "StreamEndedError",
    "EndpointStats",
    "Handler",
]
