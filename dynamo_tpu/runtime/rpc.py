"""Streaming RPC data plane: direct duplex TCP between callers and workers.

The reference splits the data plane: requests ride NATS to the worker's service
subject, and the worker dials back a raw TCP stream for the response
(``lib/runtime/src/pipeline/network/egress/addressed_router.rs:86-161``,
``ingress/push_handler.rs:25-133``).  That split exists because NATS provides
the discovery/queueing.  Here discovery comes from the coordinator, so we use
one duplex TCP connection per (caller, worker) pair and multiplex many
concurrent request streams over it with stream ids — fewer hops, same
semantics: a request frame out, a stream of response frames back, terminated by
a ``final`` sentinel (the sentinel is how stream-drop faults are detected:
missing ``final`` == "stream ended before generation completed").

Server side: ``RpcServer`` hosts named endpoints.  A handler is an async
callable ``handler(payload, ctx) -> AsyncIterator[Any]``; whatever it yields is
msgpack-framed back.  Cancellation: callers send a ``cancel`` frame; the
handler's task is cancelled and ``ctx.cancelled`` is set (parity with
``AsyncEngineContext.stop_generating``, reference ``lib/runtime/src/engine.rs``).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, List, Optional

from dynamo_tpu.runtime.codec import Raw, read_frame, send_frame
from dynamo_tpu.utils.aio import reap_task
from dynamo_tpu.utils.tracing import get_tracer

logger = logging.getLogger(__name__)

Handler = Callable[[Any, "RequestContext"], AsyncIterator[Any]]

# Keepalive health probing defaults (gRPC-style: any inbound frame counts as
# liveness proof; pings only generate traffic when the connection is quiet).
# A worker that is alive-but-stuck — engine deadlock, GC pause, network
# partition with the TCP connection still open — never closes its socket, so
# stream-drop detection alone hangs callers forever.  The ping loop bounds
# that: after ``interval * miss_budget`` seconds of silence the connection is
# torn down and every in-flight stream takes the existing ``drop`` path.
# Defaults layer: RuntimeConfig (dataclass -> TOML -> DYN_RUNTIME_* env),
# then the short-form DYN_KEEPALIVE_* env wins.  Resolved lazily (at pool
# construction, not import) so programmatic/monkeypatched env changes take
# effect and importing this module never does TOML file I/O.
def keepalive_defaults() -> "tuple[float, int]":
    interval, budget = 5.0, 3
    try:
        from dynamo_tpu.utils.config import RuntimeConfig
        cfg = RuntimeConfig.load()
        interval, budget = cfg.keepalive_interval_s, cfg.keepalive_miss_budget
    except Exception:  # a bad TOML/env must not break connection setup
        logger.warning("bad runtime config; keepalive falls back to "
                       "%.1fs x %d", interval, budget, exc_info=True)
    # short-form env strings need coercion (RuntimeConfig.load already
    # type-coerces its own sources); fall back per-value so one bad knob
    # doesn't discard the other's configured value
    raw_interval = os.environ.get("DYN_KEEPALIVE_INTERVAL")
    raw_budget = os.environ.get("DYN_KEEPALIVE_MISS_BUDGET")
    try:
        interval = float(raw_interval) if raw_interval is not None else interval
    except (TypeError, ValueError):
        logger.warning("malformed DYN_KEEPALIVE_INTERVAL %r; using %.1fs",
                       raw_interval, interval)
    try:
        budget = int(raw_budget) if raw_budget is not None else budget
    except (TypeError, ValueError):
        logger.warning("malformed DYN_KEEPALIVE_MISS_BUDGET %r; using %d",
                       raw_budget, budget)
    return interval, budget

# Wire header carrying the request deadline (absolute unix seconds, caller's
# clock — same-DC clock skew is far below useful deadline granularity).
DEADLINE_HEADER = "deadline_unix"
# Wire header carrying the frontend-minted request id: every hop propagates
# it (router sink, disagg forwards) instead of synthesizing a stream-local
# one, so one id follows the request across processes and into logs.
REQUEST_ID_HEADER = "request_id"


def request_headers(deadline_unix: Optional[float] = None,
                    request_id: Optional[str] = None,
                    extra: Optional[Dict[str, Any]] = None
                    ) -> Optional[Dict[str, Any]]:
    """RPC headers for one hop: deadline + request id (+ extras); None when
    empty.  The one place the wire shape of request-metadata propagation is
    written down — every hop builds its headers here.  Trace context
    (``trace_id``/``parent_span_id``) is NOT added here: the connection
    injects it from the ambient span at send time (see ``request``)."""
    h: Dict[str, Any] = {}
    if deadline_unix is not None:
        h[DEADLINE_HEADER] = deadline_unix
    if request_id:
        h[REQUEST_ID_HEADER] = request_id
    if extra:
        h.update(extra)
    return h or None


def deadline_headers(deadline_unix: Optional[float]) -> Optional[Dict[str, Any]]:
    """Back-compat shim: headers carrying only a deadline."""
    return request_headers(deadline_unix=deadline_unix)


class StreamEndedError(ConnectionError):
    """Response stream dropped before the final sentinel arrived.

    The migration operator keys on this (reference ``lib/llm/src/migration.rs``:
    "Stream ended before generation completed")."""


class StreamMigrationSignal(Exception):
    """Raised by a handler to end its stream WITHOUT a final sentinel.

    The server answers with an explicit ``drop`` frame, so the caller's
    ``ResponseStream`` raises ``StreamEndedError`` immediately — the same
    stream-failover path a crashed worker triggers via connection teardown,
    minus the keepalive detection delay. A draining worker uses this to
    hand its in-flight streams to the migration operator on purpose (after
    shipping a resume token as the last data frame)."""


class DeadlineExceededError(TimeoutError):
    """The request's end-to-end deadline passed before the stream finished.

    Deliberately NOT a ConnectionError subclass: the migration operator
    replays on connection-shaped failures, and an expired request must not
    be replayed onto another worker nobody is waiting for."""


@dataclass
class RequestContext:
    """Per-request context passed to endpoint handlers."""

    request_id: str
    endpoint: str
    headers: Dict[str, Any] = field(default_factory=dict)
    # absolute unix-seconds deadline propagated from the caller (``req``
    # frame header); None = no deadline
    deadline_unix: Optional[float] = None
    _cancel_event: asyncio.Event = field(default_factory=asyncio.Event)

    @property
    def cancelled(self) -> bool:
        return self._cancel_event.is_set()

    @property
    def deadline_expired(self) -> bool:
        return self.deadline_unix is not None and time.time() >= self.deadline_unix

    def time_remaining(self) -> Optional[float]:
        """Seconds until the deadline (negative if past); None = no deadline."""
        if self.deadline_unix is None:
            return None
        return self.deadline_unix - time.time()

    def cancel(self) -> None:
        self._cancel_event.set()

    async def wait_cancelled(self) -> None:
        await self._cancel_event.wait()


@dataclass
class EndpointStats:
    """Per-endpoint counters, scraped via the ``__stats__`` builtin endpoint
    (parity: NATS ``$SRV.STATS`` scraping, reference ``metrics_aggregator.rs``)."""

    requests: int = 0
    active: int = 0
    errors: int = 0
    data: Dict[str, Any] = field(default_factory=dict)  # custom stats-handler payload


class RpcServer:
    """Hosts endpoint handlers on one TCP listen port per process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: Dict[str, Handler] = {}
        self._stats: Dict[str, EndpointStats] = {}
        self._stats_providers: Dict[str, Callable[[], Any]] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._active_tasks: set = set()
        self._conn_writers: set = set()

    def register(self, endpoint: str, handler: Handler,
                 stats_provider: Optional[Callable[[], Any]] = None) -> None:
        self._handlers[endpoint] = handler
        self._stats.setdefault(endpoint, EndpointStats())
        if stats_provider is not None:
            self._stats_providers[endpoint] = stats_provider

    def unregister(self, endpoint: str) -> None:
        self._handlers.pop(endpoint, None)
        self._stats_providers.pop(endpoint, None)

    def stats(self, endpoint: str) -> EndpointStats:
        return self._stats.setdefault(endpoint, EndpointStats())

    async def start(self) -> "RpcServer":
        # 16 MiB stream buffers: KV-block frames are tens of MB; the 64 KiB
        # default limit makes readexactly drain them in tiny wakeups
        # (measured 0.9 -> multi-GB/s loopback with the larger window)
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, limit=16 * 1024 * 1024)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server:
            self._server.close()
        # never cancel/await the task calling stop() (a handler may trigger
        # shutdown of its own server) — that would self-cancel forever
        cur = asyncio.current_task()
        tasks = [t for t in self._active_tasks if t is not cur]
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        # close live connections BEFORE wait_closed: since py3.12 wait_closed
        # blocks until every connection handler returns
        for w in list(self._conn_writers):
            try:
                w.close()
            except Exception:
                pass
        if self._server:
            await self._server.wait_closed()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        wlock = asyncio.Lock()
        streams: Dict[int, RequestContext] = {}
        stream_tasks: Dict[int, asyncio.Task] = {}
        self._conn_writers.add(writer)

        async def send(obj: Any, raw: Any = None) -> None:
            async with wlock:
                await send_frame(writer, obj, raw)

        async def pong(rid: Any) -> None:
            try:
                await send({"op": "pong", "rid": rid})
            except (ConnectionError, RuntimeError):
                pass  # peer vanished; the read loop will notice

        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                op = frame.get("op")
                if op == "req":
                    sid = frame["sid"]
                    headers = frame.get("headers", {}) or {}
                    deadline = headers.get(DEADLINE_HEADER)
                    try:
                        deadline = (float(deadline)
                                    if deadline is not None else None)
                    except (TypeError, ValueError):
                        # a malformed header must fail open (no deadline),
                        # not unwind the read loop and kill every stream
                        # multiplexed on this connection
                        logger.warning("ignoring malformed %s header %r",
                                       DEADLINE_HEADER, deadline)
                        deadline = None
                    ctx = RequestContext(
                        # the frontend-minted id propagated in headers; the
                        # stream-local sid is only a last-resort fallback
                        # for callers that sent no id at all
                        request_id=headers.get(REQUEST_ID_HEADER, str(sid)),
                        endpoint=frame["endpoint"],
                        headers=headers,
                        deadline_unix=deadline,
                    )
                    streams[sid] = ctx
                    task = asyncio.create_task(
                        self._run_stream(send, sid, ctx, frame.get("payload")))
                    self._active_tasks.add(task)
                    stream_tasks[sid] = task
                    task.add_done_callback(self._active_tasks.discard)
                    task.add_done_callback(lambda _t, s=sid: streams.pop(s, None))
                    task.add_done_callback(lambda _t, s=sid: stream_tasks.pop(s, None))
                elif op == "cancel":
                    # cooperative signal first (handlers can flush/cleanup via
                    # ctx.cancelled), then hard-cancel so a handler blocked in
                    # an await can't leak the stream slot forever
                    ctx = streams.get(frame["sid"])
                    if ctx:
                        ctx.cancel()
                    task = stream_tasks.get(frame["sid"])
                    if task is not None:
                        task.cancel()
                elif op == "ping":
                    # answer off the read loop: awaiting the shared wlock
                    # here would park cancel/req processing behind any large
                    # in-flight send — and the cancel path is exactly what a
                    # deadline-expired peer needs processed promptly
                    task = asyncio.create_task(pong(frame.get("rid")))
                    self._active_tasks.add(task)
                    task.add_done_callback(self._active_tasks.discard)
        except ConnectionError:
            pass  # CancelledError must propagate (see utils/aio.reap_task)
        finally:
            self._conn_writers.discard(writer)
            for ctx in streams.values():
                ctx.cancel()
            try:
                writer.close()
            except Exception:
                pass

    async def _run_stream(self, send, sid: int, ctx: RequestContext,
                          payload: Any) -> None:
        name = ctx.endpoint
        if name == "__stats__":
            out = {
                ep: {"requests": st.requests, "active": st.active,
                     "errors": st.errors,
                     "data": (self._stats_providers[ep]()
                              if ep in self._stats_providers else st.data)}
                for ep, st in self._stats.items()
            }
            await send({"op": "data", "sid": sid, "payload": out})
            await send({"op": "final", "sid": sid})
            return
        handler = self._handlers.get(name)
        if handler is None:
            await send({"op": "err", "sid": sid,
                        "error": f"no such endpoint: {name}"})
            return
        st = self.stats(name)
        st.requests += 1
        st.active += 1
        try:
            agen = handler(payload, ctx)
            async for item in agen:
                if ctx.cancelled:
                    await agen.aclose()
                    break
                if isinstance(item, Raw):
                    # bulk binary (KV blocks): metadata in the msgpack part,
                    # bytes as a zero-copy two-part trailer
                    await send({"op": "data", "sid": sid,
                                "payload": item.obj}, raw=item.raw)
                else:
                    await send({"op": "data", "sid": sid, "payload": item})
            await send({"op": "final", "sid": sid})
        except StreamMigrationSignal:
            # deliberate graceful handoff: every data frame (including the
            # migration/resume token) is already on the wire — end the
            # stream abnormally so the caller's migration operator fires
            # NOW instead of waiting out keepalive detection
            try:
                await send({"op": "drop", "sid": sid})
            except Exception:  # noqa: BLE001 — conn gone: drop path anyway
                pass
        except asyncio.CancelledError:
            # caller cancelled (or server stopping): nothing more to send; the
            # client side tears its stream down locally on cancel
            raise
        except (ConnectionError, RuntimeError) as e:
            # connection gone: nothing more to send
            logger.debug("stream %d connection lost: %s", sid, e)
            st.errors += 1
        except Exception as e:
            st.errors += 1
            logger.exception("endpoint %s handler error", name)
            try:
                await send({"op": "err", "sid": sid, "error": str(e)})
            except Exception:
                pass
        finally:
            st.active -= 1


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class ResponseStream:
    """Async iterator over one request's response frames.

    Raises ``StreamEndedError`` if the connection drops before ``final``; a
    server-reported handler error raises ``RuntimeError``; an expired request
    deadline raises ``DeadlineExceededError`` (enforced between frames, so a
    silent worker can't hold a caller past its deadline).
    """

    def __init__(self, conn: "RpcConnection", sid: int,
                 deadline_unix: Optional[float] = None):
        self._conn = conn
        self.sid = sid
        self.deadline_unix = deadline_unix
        self.queue: asyncio.Queue = asyncio.Queue()
        self.finished = False

    def __aiter__(self) -> "ResponseStream":
        return self

    async def __anext__(self) -> Any:
        if self.finished:
            raise StopAsyncIteration
        if self.deadline_unix is None:
            kind, value = await self.queue.get()
        else:
            remaining = self.deadline_unix - time.time()
            try:
                if remaining <= 0:
                    raise asyncio.TimeoutError
                try:
                    # fast path: a frame already queued skips wait_for's
                    # per-token task + timer allocation on the hot path
                    kind, value = self.queue.get_nowait()
                except asyncio.QueueEmpty:
                    kind, value = await asyncio.wait_for(self.queue.get(),
                                                         timeout=remaining)
            except asyncio.TimeoutError:
                # raise AT the deadline: the cancel frame is sent in the
                # background (send_cancel can wait seconds on a writer lock
                # wedged by the very connection that went silent)
                self.finished = True
                self._conn._streams.pop(self.sid, None)
                self._conn.spawn_cancel(self.sid)
                while not self.queue.empty():
                    self.queue.get_nowait()
                raise DeadlineExceededError(
                    "request deadline exceeded mid-stream") from None
        if kind == "data":
            return value
        self.finished = True
        self._conn._streams.pop(self.sid, None)
        if kind == "final":
            raise StopAsyncIteration
        if kind == "err":
            raise RuntimeError(value)
        raise StreamEndedError("stream ended before generation completed")

    async def cancel(self) -> None:
        """Tell the worker to stop and finish this stream locally (the worker
        may be hard-cancelled mid-await and never send a final frame).

        Idempotent: a second cancel (or one on an already-finished stream) is
        a no-op, and queued frames are drained so a late ``drop`` sentinel
        can't leak into a reused sid map."""
        if self.finished:
            return
        self.finished = True
        self._conn._streams.pop(self.sid, None)
        await self._conn.send_cancel(self.sid)
        while not self.queue.empty():
            self.queue.get_nowait()


class RpcConnection:
    """One multiplexed duplex connection to a worker's RpcServer.

    ``keepalive_interval > 0`` arms a ping loop: when nothing (data, pong,
    anything) has arrived for ``keepalive_interval * keepalive_miss_budget``
    seconds the connection is torn down — in-flight streams get the ``drop``
    sentinel (so migration/failover fire exactly as for a crashed worker) and
    ``on_unexpected_close`` is invoked (the pool uses it to notify clients so
    the instance is marked down)."""

    def __init__(self, address: str, keepalive_interval: float = 0.0,
                 keepalive_miss_budget: int = 3):
        host, _, port = address.rpartition(":")
        self.address = address
        self.host, self.port = host or "127.0.0.1", int(port)
        self.keepalive_interval = keepalive_interval
        self.keepalive_miss_budget = max(1, keepalive_miss_budget)
        self._sids = itertools.count(1)
        self._streams: Dict[int, ResponseStream] = {}
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._wlock: Optional[asyncio.Lock] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._ping_task: Optional[asyncio.Task] = None
        self._bg_tasks: set = set()  # fire-and-forget cancels (spawn_cancel)
        self._last_seen = 0.0  # loop time of the last inbound frame
        self._closing = False  # explicit close() — don't fire death callbacks
        self.keepalive_expired = False
        # fired (synchronously, once) when the connection dies without an
        # explicit close(): conn drop OR keepalive miss-budget exhaustion
        self.on_unexpected_close: Optional[Callable[["RpcConnection"], None]] = None
        self.alive = False

    async def connect(self) -> "RpcConnection":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, limit=16 * 1024 * 1024)
        self._wlock = asyncio.Lock()
        self._last_seen = asyncio.get_running_loop().time()
        self._reader_task = asyncio.create_task(self._read_loop())
        if self.keepalive_interval > 0:
            self._ping_task = asyncio.create_task(self._ping_loop())
        self.alive = True
        return self

    async def close(self) -> None:
        self.alive = False
        self._closing = True
        cur = asyncio.current_task()
        if self._ping_task is not None and self._ping_task is not cur:
            await reap_task(self._ping_task)
        await reap_task(self._reader_task)
        for task in list(self._bg_tasks):
            await reap_task(task)
        self._bg_tasks.clear()
        if self._writer:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass

    async def _ping_loop(self) -> None:
        """Probe a quiet connection; kill it when the miss budget is
        exhausted.  Any inbound frame resets the silence clock, so a healthy
        connection under load never pays for pongs it doesn't need, and a
        connection is only ever torn down after a probe sent SINCE the last
        inbound frame went unanswered (so even miss_budget=1 can't expire a
        healthy idle connection that was never probed).

        Caveat: inbound liveness is credited per COMPLETE frame, so on links
        where a single RPC-plane frame can take longer than
        ``interval * miss_budget`` to arrive (bulk KV riding the RPC
        fallback cross-host), size the budget above the worst-case frame
        time or disable probing for that pool.  The outbound analogue is
        handled below: a probe that can't be written because a large send
        holds the writer only counts as missed when the transport's write
        buffer is NOT draining (a frozen peer stops draining it; a slow
        healthy one keeps consuming)."""
        loop = asyncio.get_running_loop()
        rids = itertools.count(1)
        budget_s = self.keepalive_interval * self.keepalive_miss_budget
        last_ping = 0.0  # loop time of the newest (attempted) probe
        last_buf: Optional[int] = None  # write-buffer size at last miss
        while True:
            await asyncio.sleep(self.keepalive_interval)
            now = loop.time()
            silent_for = now - self._last_seen
            if silent_for >= budget_s and last_ping > self._last_seen:
                logger.warning(
                    "rpc connection %s silent for %.2fs (keepalive budget "
                    "%.2fs): tearing down", self.address, silent_for, budget_s)
                self.keepalive_expired = True
                self._abort()
                return
            if silent_for < self.keepalive_interval / 2:
                last_buf = None
                continue  # recent traffic proves liveness — no probe needed
            try:
                # bound the probe: _wlock may be held by a request blocked
                # in drain() against a peer that stopped reading — waiting
                # on it unbounded would starve the budget check above and
                # defeat frozen-worker detection exactly when it matters
                await asyncio.wait_for(self._send_ping(next(rids)),
                                       timeout=self.keepalive_interval)
                last_ping = now
                last_buf = None
            except asyncio.TimeoutError:
                # probe blocked behind a large in-flight send: only count
                # it as missed when the peer isn't draining our bytes
                buf = self._write_buffer_size()
                if buf is not None and last_buf is not None and buf < last_buf:
                    self._last_seen = now  # peer is consuming: alive
                else:
                    last_ping = now
                last_buf = buf
            except (ConnectionError, RuntimeError):
                self._abort()
                return

    def _write_buffer_size(self) -> Optional[int]:
        try:
            return self._writer.transport.get_write_buffer_size()
        except Exception:
            return None

    async def _send_ping(self, rid: int) -> None:
        async with self._wlock:
            await send_frame(self._writer, {"op": "ping", "rid": rid})

    def _abort(self) -> None:
        """Tear down from inside the connection's own tasks: cancelling the
        reader fires its ``finally`` (drop sentinels + death callback)."""
        self.alive = False
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass

    async def _read_loop(self) -> None:
        loop = asyncio.get_running_loop()
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame is None:
                    break
                self._last_seen = loop.time()
                op = frame.get("op")
                if op == "pong":
                    continue
                sid = frame.get("sid")
                stream = self._streams.get(sid)
                if stream is None:
                    continue
                if op == "data":
                    payload = frame.get("payload")
                    if "_raw" in frame and isinstance(payload, dict):
                        # two-part frame: surface the raw trailer inside the
                        # payload the handler yielded it with
                        payload["_raw"] = frame["_raw"]
                    stream.queue.put_nowait(("data", payload))
                elif op == "final":
                    stream.queue.put_nowait(("final", None))
                elif op == "err":
                    stream.queue.put_nowait(("err", frame.get("error")))
                elif op == "drop":
                    # server-initiated graceful stream handoff (worker
                    # drain): same terminal state as a dropped connection,
                    # scoped to one stream
                    stream.queue.put_nowait(("drop", None))
                    self._streams.pop(sid, None)
        except ConnectionError:
            pass  # CancelledError must propagate (see utils/aio.reap_task)
        finally:
            self.alive = False
            for stream in list(self._streams.values()):
                stream.queue.put_nowait(("drop", None))
            self._streams.clear()
            if not self._closing and self.on_unexpected_close is not None:
                cb, self.on_unexpected_close = self.on_unexpected_close, None
                try:
                    cb(self)
                except Exception:
                    logger.exception("connection death callback failed")

    async def request(self, endpoint: str, payload: Any,
                      headers: Optional[Dict[str, Any]] = None) -> ResponseStream:
        if not self.alive:
            raise ConnectionError(f"connection to {self.address} is down")
        # trace context rides EVERY hop automatically: the caller's current
        # span (contextvar) becomes the remote hop's parent, so router,
        # disagg, and aux forwards stitch without per-call-site wiring
        trace_ctx = get_tracer().current_headers()
        if trace_ctx:
            merged = dict(trace_ctx)
            merged.update(headers or {})
            headers = merged
        sid = next(self._sids)
        deadline = (headers or {}).get(DEADLINE_HEADER)
        stream = ResponseStream(
            self, sid,
            deadline_unix=float(deadline) if deadline is not None else None)
        self._streams[sid] = stream
        try:
            async with self._wlock:
                await send_frame(self._writer, {
                    "op": "req", "sid": sid, "endpoint": endpoint,
                    "payload": payload, "headers": headers or {}})
        except (ConnectionError, RuntimeError) as e:
            self._streams.pop(sid, None)
            self.alive = False
            raise ConnectionError(str(e)) from e
        return stream

    def spawn_cancel(self, sid: int) -> None:
        """Fire-and-forget cancel frame, tracked so it is reaped at close
        (used by the deadline path, which must not block on the writer)."""
        task = asyncio.ensure_future(self.send_cancel(sid))
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    async def send_cancel(self, sid: int) -> None:
        if not self.alive:
            return
        try:
            # best-effort and BOUNDED: _wlock may be held by a send blocked
            # against a stuck peer, and cancel rides the deadline path —
            # which must never wait on the very connection that's wedged
            # (keepalive will tear it down)
            await asyncio.wait_for(self._send_cancel(sid), timeout=5.0)
        except asyncio.TimeoutError:
            pass
        except (ConnectionError, RuntimeError):
            self.alive = False

    async def _send_cancel(self, sid: int) -> None:
        async with self._wlock:
            await send_frame(self._writer, {"op": "cancel", "sid": sid})


class RpcClientPool:
    """Connection pool: one live RpcConnection per worker address.

    Every pooled connection runs the keepalive ping loop (interval 0
    disables).  When a connection dies without an explicit ``drop`` — remote
    crash or keepalive expiry — registered down-listeners are notified with
    the address, so endpoint clients can mark the backing instance down ahead
    of lease expiry (frozen-worker detection as fast as crashed-worker
    detection)."""

    def __init__(self, keepalive_interval: Optional[float] = None,
                 keepalive_miss_budget: Optional[int] = None) -> None:
        default_interval, default_budget = keepalive_defaults()
        self.keepalive_interval = (keepalive_interval
                                   if keepalive_interval is not None
                                   else default_interval)
        self.keepalive_miss_budget = (keepalive_miss_budget
                                      if keepalive_miss_budget is not None
                                      else default_budget)
        self._conns: Dict[str, RpcConnection] = {}
        self._locks: Dict[str, asyncio.Lock] = {}
        self._down_listeners: List[Callable[[str], None]] = []
        self._close_tasks: set = set()

    def add_down_listener(self, cb: Callable[[str], None]) -> None:
        """``cb(address)`` fires when a pooled connection dies unexpectedly."""
        self._down_listeners.append(cb)

    def remove_down_listener(self, cb: Callable[[str], None]) -> None:
        try:
            self._down_listeners.remove(cb)
        except ValueError:
            pass

    async def get(self, address: str) -> RpcConnection:
        conn = self._conns.get(address)
        if conn is not None and conn.alive:
            return conn
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._conns.get(address)
            if conn is not None and conn.alive:
                return conn
            conn = RpcConnection(
                address, keepalive_interval=self.keepalive_interval,
                keepalive_miss_budget=self.keepalive_miss_budget)
            conn.on_unexpected_close = self._conn_died
            await conn.connect()
            self._conns[address] = conn
            return conn

    def _conn_died(self, conn: RpcConnection) -> None:
        if self._conns.get(conn.address) is conn:
            self._conns.pop(conn.address, None)
        for cb in list(self._down_listeners):
            try:
                cb(conn.address)
            except Exception:
                logger.exception("pool down-listener failed for %s",
                                 conn.address)

    def drop(self, address: str) -> None:
        conn = self._conns.pop(address, None)
        if conn is not None:
            conn.on_unexpected_close = None  # explicit drop, not a death
            # track the close task: an unreferenced ensure_future can be
            # GC'd mid-flight and swallows exceptions silently
            task = asyncio.ensure_future(conn.close())
            self._close_tasks.add(task)
            task.add_done_callback(self._reap_close)

    def _reap_close(self, task: asyncio.Task) -> None:
        self._close_tasks.discard(task)
        if not task.cancelled() and task.exception() is not None:
            logger.warning("pooled connection close failed: %r",
                           task.exception())

    async def close(self) -> None:
        for conn in list(self._conns.values()):
            conn.on_unexpected_close = None
            await conn.close()
        self._conns.clear()
        if self._close_tasks:
            await asyncio.gather(*list(self._close_tasks),
                                 return_exceptions=True)


__all__ = [
    "RpcServer",
    "RpcConnection",
    "RpcClientPool",
    "ResponseStream",
    "RequestContext",
    "StreamEndedError",
    "StreamMigrationSignal",
    "DeadlineExceededError",
    "EndpointStats",
    "Handler",
    "DEADLINE_HEADER",
    "REQUEST_ID_HEADER",
    "deadline_headers",
    "request_headers",
    "keepalive_defaults",
]
