"""GGUF single-file model support: metadata, config, tokenizer vocab, weights.

Parity: reference ``lib/llm/src/gguf/*.rs`` (GGUF metadata/tokenizer parsing
for llama.cpp-style models, used by ``LocalModel`` and the model card). This
reader is written from the public GGUF v3 layout:

  header:  magic "GGUF" | version u32 | tensor_count u64 | kv_count u64
  kv:      key string | value_type u32 | value
  tensors: name string | n_dims u32 | dims u64[n] | ggml_type u32 | offset u64
  data:    aligned to general.alignment (default 32)

Weights load for unquantized ggml types (F32, F16, BF16) into the same
stacked-layer pytree the HF loader produces (llama.cpp ``blk.N.*`` naming).
Quantized formats raise a clear error — dequantization is a follow-up, the
metadata/tokenizer path works for any file.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np

from dynamo_tpu.models.config import ModelConfig

GGUF_MAGIC = b"GGUF"

# metadata value types
_U8, _I8, _U16, _I16, _U32, _I32, _F32, _BOOL, _STR, _ARR, _U64, _I64, _F64 = \
    range(13)

_SCALAR_FMT = {_U8: "<B", _I8: "<b", _U16: "<H", _I16: "<h", _U32: "<I",
               _I32: "<i", _F32: "<f", _U64: "<Q", _I64: "<q", _F64: "<d"}

# ggml tensor types we can load without dequantization
GGML_F32, GGML_F16 = 0, 1
GGML_BF16 = 30
_TENSOR_DTYPES = {GGML_F32: np.dtype("<f4"), GGML_F16: np.dtype("<f2"),
                  GGML_BF16: np.dtype("<u2")}  # bf16 read as raw u16


def _read(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    data = f.read(size)
    if len(data) != size:
        raise ValueError("truncated GGUF file")
    return struct.unpack(fmt, data)[0]


def _read_string(f: BinaryIO) -> str:
    n = _read(f, "<Q")
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int):
    if vtype in _SCALAR_FMT:
        v = _read(f, _SCALAR_FMT[vtype])
        return v
    if vtype == _BOOL:
        return bool(_read(f, "<B"))
    if vtype == _STR:
        return _read_string(f)
    if vtype == _ARR:
        elem_type = _read(f, "<I")
        count = _read(f, "<Q")
        return [_read_value(f, elem_type) for _ in range(count)]
    raise ValueError(f"unknown GGUF metadata type {vtype}")


class GgufFile:
    """Parsed GGUF: metadata dict + tensor directory (lazy data loads)."""

    def __init__(self, path: str):
        self.path = path
        self.metadata: Dict[str, Any] = {}
        # name -> (shape, ggml_type, absolute_offset)
        self.tensors: Dict[str, Tuple[Tuple[int, ...], int, int]] = {}
        with open(path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path} is not a GGUF file")
            version = _read(f, "<I")
            if version < 2:
                raise ValueError(f"unsupported GGUF version {version}")
            tensor_count = _read(f, "<Q")
            kv_count = _read(f, "<Q")
            for _ in range(kv_count):
                key = _read_string(f)
                vtype = _read(f, "<I")
                self.metadata[key] = _read_value(f, vtype)
            infos: List[Tuple[str, Tuple[int, ...], int, int]] = []
            for _ in range(tensor_count):
                name = _read_string(f)
                n_dims = _read(f, "<I")
                dims = tuple(_read(f, "<Q") for _ in range(n_dims))
                ggml_type = _read(f, "<I")
                offset = _read(f, "<Q")
                # GGUF dims are stored innermost-first; numpy wants outermost
                infos.append((name, tuple(reversed(dims)), ggml_type, offset))
            align = int(self.metadata.get("general.alignment", 32))
            base = f.tell()
            base = (base + align - 1) // align * align
            for name, shape, ggml_type, offset in infos:
                self.tensors[name] = (shape, ggml_type, base + offset)

    # -- tensor data -------------------------------------------------------

    def load_tensor(self, name: str) -> np.ndarray:
        shape, ggml_type, offset = self.tensors[name]
        dtype = _TENSOR_DTYPES.get(ggml_type)
        if dtype is None:
            raise NotImplementedError(
                f"tensor {name!r} uses quantized ggml type {ggml_type}; "
                f"only F32/F16/BF16 GGUF files load directly")
        count = int(np.prod(shape)) if shape else 1
        with open(self.path, "rb") as f:
            f.seek(offset)
            raw = f.read(count * dtype.itemsize)
        arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
        if ggml_type == GGML_BF16:
            arr = (arr.astype(np.uint32) << 16).view(np.float32)
        return np.ascontiguousarray(arr)

    # -- model config ------------------------------------------------------

    def to_model_config(self, dtype: str = "bfloat16") -> ModelConfig:
        md = self.metadata
        arch = md.get("general.architecture", "llama")

        def g(key, default=None):
            return md.get(f"{arch}.{key}", default)

        heads = int(g("attention.head_count"))
        hidden = int(g("embedding_length"))
        vocab = md.get(f"{arch}.vocab_size")
        if vocab is None:
            vocab = len(md.get("tokenizer.ggml.tokens", [])) or 32000
        return ModelConfig(
            vocab_size=int(vocab),
            hidden_size=hidden,
            intermediate_size=int(g("feed_forward_length")),
            num_layers=int(g("block_count")),
            num_heads=heads,
            num_kv_heads=int(g("attention.head_count_kv", heads)),
            head_dim=int(g("attention.key_length", hidden // heads)),
            rope_theta=float(g("rope.freq_base", 10000.0)),
            rms_norm_eps=float(g("attention.layer_norm_rms_epsilon", 1e-5)),
            max_position_embeddings=int(g("context_length", 8192)),
            tie_word_embeddings="output.weight" not in self.tensors,
            model_type=arch,
            dtype=dtype,
        )

    def special_token_ids(self) -> Dict[str, Optional[int]]:
        md = self.metadata
        return {
            "eos": md.get("tokenizer.ggml.eos_token_id"),
            "bos": md.get("tokenizer.ggml.bos_token_id"),
        }


# llama.cpp tensor name -> (pytree path, transpose?)
_GGUF_MAP = {
    "token_embd.weight": (("embed",), False),
    "output_norm.weight": (("final_norm",), False),
    "output.weight": (("lm_head",), True),
    "blk.{i}.attn_norm.weight": (("layers", "attn_norm"), False),
    "blk.{i}.attn_q.weight": (("layers", "wq"), True),
    "blk.{i}.attn_k.weight": (("layers", "wk"), True),
    "blk.{i}.attn_v.weight": (("layers", "wv"), True),
    "blk.{i}.attn_output.weight": (("layers", "wo"), True),
    "blk.{i}.ffn_norm.weight": (("layers", "mlp_norm"), False),
    "blk.{i}.ffn_gate.weight": (("layers", "w_gate"), True),
    "blk.{i}.ffn_up.weight": (("layers", "w_up"), True),
    "blk.{i}.ffn_down.weight": (("layers", "w_down"), True),
}


def load_gguf_params(cfg: ModelConfig, path: str) -> Dict[str, Any]:
    """Assemble the stacked-layer param pytree from a GGUF file."""
    import jax.numpy as jnp

    gf = GgufFile(path)
    staged: Dict[tuple, Any] = {}
    per_layer: Dict[tuple, Dict[int, np.ndarray]] = {}
    for name in gf.tensors:
        layer = None
        key = name
        if name.startswith("blk."):
            rest = name[len("blk."):]
            idx, _, tail = rest.partition(".")
            layer = int(idx)
            key = f"blk.{{i}}.{tail}"
        spec = _GGUF_MAP.get(key)
        if spec is None:
            continue
        tree_path, transpose = spec
        t = gf.load_tensor(name)
        if transpose:
            t = np.ascontiguousarray(t.T)
        if layer is None:
            staged[tree_path] = t
        else:
            per_layer.setdefault(tree_path, {})[layer] = t

    for tree_path, by_layer in per_layer.items():
        missing = set(range(cfg.num_layers)) - set(by_layer)
        if missing:
            raise ValueError(f"GGUF missing layers {sorted(missing)} "
                             f"for {tree_path}")
        staged[tree_path] = np.stack(
            [by_layer[i] for i in range(cfg.num_layers)])

    expected = {tp for tp, _ in _GGUF_MAP.values()}
    if cfg.tie_word_embeddings:
        expected.discard(("lm_head",))
    absent = expected - set(staged)
    if absent:
        raise ValueError(f"GGUF at {path} missing weights for {sorted(absent)}")

    params: Dict[str, Any] = {}
    target = jnp.dtype(cfg.dtype)
    for tree_path, arr in staged.items():
        node = params
        for k in tree_path[:-1]:
            node = node.setdefault(k, {})
        node[tree_path[-1]] = jnp.asarray(arr).astype(target)
    return params


__all__ = ["GgufFile", "load_gguf_params"]
