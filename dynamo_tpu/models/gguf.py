"""GGUF single-file model support: metadata, config, tokenizer vocab, weights.

Parity: reference ``lib/llm/src/gguf/*.rs`` (GGUF metadata/tokenizer parsing
for llama.cpp-style models, used by ``LocalModel`` and the model card). This
reader is written from the public GGUF v3 layout:

  header:  magic "GGUF" | version u32 | tensor_count u64 | kv_count u64
  kv:      key string | value_type u32 | value
  tensors: name string | n_dims u32 | dims u64[n] | ggml_type u32 | offset u64
  data:    aligned to general.alignment (default 32)

Weights load into the same stacked-layer pytree the HF loader produces
(llama.cpp ``blk.N.*`` naming). Unquantized ggml types (F32, F16, BF16) load
directly; the common quantized types (Q4_0, Q8_0, Q4_K, Q6_K — the formats
llama.cpp's default Q4_K_M / Q8_0 exports use) dequantize to float on load
from the public ggml block layouts. Other quantized types raise a clear
error.

llama-arch Q/K layout: llama.cpp's HF converter permutes attn_q/attn_k rows
per head into interleaved-rope order; this loader applies the inverse so the
weights match this repo's rotate-half rope (``ops/rope.py``).
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np

from dynamo_tpu.models.config import ModelConfig

GGUF_MAGIC = b"GGUF"

# metadata value types
_U8, _I8, _U16, _I16, _U32, _I32, _F32, _BOOL, _STR, _ARR, _U64, _I64, _F64 = \
    range(13)

_SCALAR_FMT = {_U8: "<B", _I8: "<b", _U16: "<H", _I16: "<h", _U32: "<I",
               _I32: "<i", _F32: "<f", _U64: "<Q", _I64: "<q", _F64: "<d"}

# ggml tensor types we can load without dequantization
GGML_F32, GGML_F16 = 0, 1
GGML_BF16 = 30
_TENSOR_DTYPES = {GGML_F32: np.dtype("<f4"), GGML_F16: np.dtype("<f2"),
                  GGML_BF16: np.dtype("<u2")}  # bf16 read as raw u16

# quantized ggml types: type -> (elements per block, bytes per block)
GGML_Q4_0, GGML_Q8_0, GGML_Q4_K, GGML_Q6_K = 2, 8, 12, 14
_QUANT_BLOCKS = {GGML_Q4_0: (32, 18), GGML_Q8_0: (32, 34),
                 GGML_Q4_K: (256, 144), GGML_Q6_K: (256, 210)}


def _f16_col(b: np.ndarray) -> np.ndarray:
    """Two uint8 columns -> float32 column vector."""
    return b.copy().view(np.float16).astype(np.float32)


def _dequant_q8_0(b: np.ndarray) -> np.ndarray:
    # block: f16 d | 32x int8 q;  v = d*q
    d = _f16_col(b[:, 0:2])
    q = b[:, 2:].copy().view(np.int8).astype(np.float32)
    return d * q


def _dequant_q4_0(b: np.ndarray) -> np.ndarray:
    # block: f16 d | 16 bytes of nibbles; elem j = lo(qs[j]), j+16 = hi(qs[j])
    d = _f16_col(b[:, 0:2])
    qs = b[:, 2:]
    lo = (qs & 0xF).astype(np.float32) - 8.0
    hi = (qs >> 4).astype(np.float32) - 8.0
    return d * np.concatenate([lo, hi], axis=1)


def _q4k_scales(sc: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Unpack the 12-byte packed 6-bit (scale, min) pairs of a K-quant
    super-block into [nb, 8] float arrays."""
    sc = sc.astype(np.uint16)
    nb = sc.shape[0]
    scales = np.empty((nb, 8), np.float32)
    mins = np.empty((nb, 8), np.float32)
    for j in range(4):
        scales[:, j] = sc[:, j] & 63
        mins[:, j] = sc[:, j + 4] & 63
    for j in range(4, 8):
        scales[:, j] = (sc[:, j + 4] & 0xF) | ((sc[:, j - 4] >> 6) << 4)
        mins[:, j] = (sc[:, j + 4] >> 4) | ((sc[:, j] >> 6) << 4)
    return scales, mins


def _dequant_q4_k(b: np.ndarray) -> np.ndarray:
    # super-block (256): f16 d | f16 dmin | 12B packed 6-bit scales/mins |
    # 128B nibbles; sub-block 2j = lo nibbles of chunk j, 2j+1 = hi nibbles;
    # v = d*sc*q - dmin*m
    d = _f16_col(b[:, 0:2])
    dmin = _f16_col(b[:, 2:4])
    scales, mins = _q4k_scales(b[:, 4:16])
    qs = b[:, 16:144]
    out = np.empty((b.shape[0], 256), np.float32)
    for j in range(4):
        q = qs[:, 32 * j:32 * j + 32]
        lo = (q & 0xF).astype(np.float32)
        hi = (q >> 4).astype(np.float32)
        out[:, 64 * j:64 * j + 32] = (
            d * scales[:, [2 * j]] * lo - dmin * mins[:, [2 * j]])
        out[:, 64 * j + 32:64 * j + 64] = (
            d * scales[:, [2 * j + 1]] * hi - dmin * mins[:, [2 * j + 1]])
    return out


def _dequant_q6_k(b: np.ndarray) -> np.ndarray:
    # super-block (256): 128B ql (low nibbles) | 64B qh (2-bit highs) |
    # 16x int8 scales (one per 16 elems) | f16 d;  v = d*scale*(q-32)
    ql_all = b[:, 0:128]
    qh_all = b[:, 128:192]
    scales = b[:, 192:208].copy().view(np.int8).astype(np.float32)
    d = _f16_col(b[:, 208:210])
    out = np.empty((b.shape[0], 256), np.float32)
    idx16 = np.arange(32) // 16  # scale index within a 32-elem quarter
    for half in range(2):
        ql = ql_all[:, 64 * half:64 * half + 64]
        qh = qh_all[:, 32 * half:32 * half + 32]
        sch = scales[:, 8 * half:8 * half + 8]
        base = 128 * half
        quarters = (
            ((ql[:, :32] & 0xF) | ((qh & 3) << 4), 0),
            ((ql[:, 32:] & 0xF) | (((qh >> 2) & 3) << 4), 2),
            ((ql[:, :32] >> 4) | (((qh >> 4) & 3) << 4), 4),
            ((ql[:, 32:] >> 4) | (((qh >> 6) & 3) << 4), 6),
        )
        for k, (q, s0) in enumerate(quarters):
            out[:, base + 32 * k:base + 32 * k + 32] = (
                d * sch[:, idx16 + s0] * (q.astype(np.float32) - 32.0))
    return out


_DEQUANT = {GGML_Q4_0: _dequant_q4_0, GGML_Q8_0: _dequant_q8_0,
            GGML_Q4_K: _dequant_q4_k, GGML_Q6_K: _dequant_q6_k}


def _read(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    data = f.read(size)
    if len(data) != size:
        raise ValueError("truncated GGUF file")
    return struct.unpack(fmt, data)[0]


def _read_string(f: BinaryIO) -> str:
    n = _read(f, "<Q")
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int):
    if vtype in _SCALAR_FMT:
        v = _read(f, _SCALAR_FMT[vtype])
        return v
    if vtype == _BOOL:
        return bool(_read(f, "<B"))
    if vtype == _STR:
        return _read_string(f)
    if vtype == _ARR:
        elem_type = _read(f, "<I")
        count = _read(f, "<Q")
        return [_read_value(f, elem_type) for _ in range(count)]
    raise ValueError(f"unknown GGUF metadata type {vtype}")


class GgufFile:
    """Parsed GGUF: metadata dict + tensor directory (lazy data loads)."""

    def __init__(self, path: str):
        self.path = path
        self.metadata: Dict[str, Any] = {}
        # name -> (shape, ggml_type, absolute_offset)
        self.tensors: Dict[str, Tuple[Tuple[int, ...], int, int]] = {}
        with open(path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path} is not a GGUF file")
            version = _read(f, "<I")
            if version < 2:
                raise ValueError(f"unsupported GGUF version {version}")
            tensor_count = _read(f, "<Q")
            kv_count = _read(f, "<Q")
            for _ in range(kv_count):
                key = _read_string(f)
                vtype = _read(f, "<I")
                self.metadata[key] = _read_value(f, vtype)
            infos: List[Tuple[str, Tuple[int, ...], int, int]] = []
            for _ in range(tensor_count):
                name = _read_string(f)
                n_dims = _read(f, "<I")
                dims = tuple(_read(f, "<Q") for _ in range(n_dims))
                ggml_type = _read(f, "<I")
                offset = _read(f, "<Q")
                # GGUF dims are stored innermost-first; numpy wants outermost
                infos.append((name, tuple(reversed(dims)), ggml_type, offset))
            align = int(self.metadata.get("general.alignment", 32))
            base = f.tell()
            base = (base + align - 1) // align * align
            for name, shape, ggml_type, offset in infos:
                self.tensors[name] = (shape, ggml_type, base + offset)

    # -- tensor data -------------------------------------------------------

    def load_tensor(self, name: str) -> np.ndarray:
        shape, ggml_type, offset = self.tensors[name]
        count = int(np.prod(shape)) if shape else 1
        if ggml_type in _QUANT_BLOCKS:
            per_block, block_bytes = _QUANT_BLOCKS[ggml_type]
            if count % per_block:
                raise ValueError(
                    f"tensor {name!r}: {count} elements not divisible by "
                    f"the {per_block}-element quant block")
            n_blocks = count // per_block
            with open(self.path, "rb") as f:
                f.seek(offset)
                raw = f.read(n_blocks * block_bytes)
            if len(raw) != n_blocks * block_bytes:
                raise ValueError(f"truncated tensor data for {name!r}")
            blocks = np.frombuffer(raw, np.uint8).reshape(n_blocks,
                                                          block_bytes)
            return _DEQUANT[ggml_type](blocks).reshape(shape)
        dtype = _TENSOR_DTYPES.get(ggml_type)
        if dtype is None:
            raise NotImplementedError(
                f"tensor {name!r} uses unsupported ggml type {ggml_type}; "
                f"supported: F32/F16/BF16/Q4_0/Q8_0/Q4_K/Q6_K")
        with open(self.path, "rb") as f:
            f.seek(offset)
            raw = f.read(count * dtype.itemsize)
        arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
        if ggml_type == GGML_BF16:
            arr = (arr.astype(np.uint32) << 16).view(np.float32)
        return np.ascontiguousarray(arr)

    # -- model config ------------------------------------------------------

    def to_model_config(self, dtype: str = "bfloat16") -> ModelConfig:
        md = self.metadata
        arch = md.get("general.architecture", "llama")

        def g(key, default=None):
            return md.get(f"{arch}.{key}", default)

        heads = int(g("attention.head_count"))
        hidden = int(g("embedding_length"))
        vocab = md.get(f"{arch}.vocab_size")
        if vocab is None:
            vocab = len(md.get("tokenizer.ggml.tokens", [])) or 32000
        return ModelConfig(
            vocab_size=int(vocab),
            hidden_size=hidden,
            intermediate_size=int(g("feed_forward_length")),
            num_layers=int(g("block_count")),
            num_heads=heads,
            num_kv_heads=int(g("attention.head_count_kv", heads)),
            head_dim=int(g("attention.key_length", hidden // heads)),
            rope_theta=float(g("rope.freq_base", 10000.0)),
            rms_norm_eps=float(g("attention.layer_norm_rms_epsilon", 1e-5)),
            max_position_embeddings=int(g("context_length", 8192)),
            tie_word_embeddings="output.weight" not in self.tensors,
            model_type=arch,
            dtype=dtype,
        )

    def special_token_ids(self) -> Dict[str, Optional[int]]:
        md = self.metadata
        return {
            "eos": md.get("tokenizer.ggml.eos_token_id"),
            "bos": md.get("tokenizer.ggml.bos_token_id"),
        }


# llama.cpp tensor name -> (pytree path, transpose?)
_GGUF_MAP = {
    "token_embd.weight": (("embed",), False),
    "output_norm.weight": (("final_norm",), False),
    "output.weight": (("lm_head",), True),
    "blk.{i}.attn_norm.weight": (("layers", "attn_norm"), False),
    "blk.{i}.attn_q.weight": (("layers", "wq"), True),
    "blk.{i}.attn_k.weight": (("layers", "wk"), True),
    "blk.{i}.attn_v.weight": (("layers", "wv"), True),
    "blk.{i}.attn_output.weight": (("layers", "wo"), True),
    "blk.{i}.ffn_norm.weight": (("layers", "mlp_norm"), False),
    "blk.{i}.ffn_gate.weight": (("layers", "w_gate"), True),
    "blk.{i}.ffn_up.weight": (("layers", "w_up"), True),
    "blk.{i}.ffn_down.weight": (("layers", "w_down"), True),
}


def _unpermute_rope_rows(w: np.ndarray, n_head: int) -> np.ndarray:
    """Invert llama.cpp's HF->GGUF per-head row permutation on a
    [out_features, in_features] Q/K weight.

    The converter maps row ``a*(d/2)+b -> 2b+a`` within each head
    (``w.reshape(H, 2, d/2, in).swapaxes(1, 2)``) to turn HF rotate-half
    layout into GGUF interleaved-rope layout; this applies the inverse so
    rotate-half rope sees the original HF rows.
    """
    out_dim, in_dim = w.shape
    head = out_dim // n_head
    return np.ascontiguousarray(
        w.reshape(n_head, head // 2, 2, in_dim)
        .swapaxes(1, 2)
        .reshape(out_dim, in_dim))


# architectures whose GGUF files carry converter-permuted Q/K (llama-arch
# covers Llama and Mistral exports)
_PERMUTED_QK_ARCHS = {"llama"}


def load_gguf_params(cfg: ModelConfig, path: str) -> Dict[str, Any]:
    """Assemble the stacked-layer param pytree from a GGUF file."""
    import jax.numpy as jnp

    gf = GgufFile(path)
    arch = gf.metadata.get("general.architecture", "llama")
    staged: Dict[tuple, Any] = {}
    per_layer: Dict[tuple, Dict[int, np.ndarray]] = {}
    for name in gf.tensors:
        layer = None
        key = name
        if name.startswith("blk."):
            rest = name[len("blk."):]
            idx, _, tail = rest.partition(".")
            layer = int(idx)
            key = f"blk.{{i}}.{tail}"
        spec = _GGUF_MAP.get(key)
        if spec is None:
            continue
        tree_path, transpose = spec
        t = gf.load_tensor(name)
        if arch in _PERMUTED_QK_ARCHS:
            if key == "blk.{i}.attn_q.weight":
                t = _unpermute_rope_rows(t, cfg.num_heads)
            elif key == "blk.{i}.attn_k.weight":
                t = _unpermute_rope_rows(t, cfg.num_kv_heads)
        if transpose:
            t = np.ascontiguousarray(t.T)
        if layer is None:
            staged[tree_path] = t
        else:
            per_layer.setdefault(tree_path, {})[layer] = t

    for tree_path, by_layer in per_layer.items():
        missing = set(range(cfg.num_layers)) - set(by_layer)
        if missing:
            raise ValueError(f"GGUF missing layers {sorted(missing)} "
                             f"for {tree_path}")
        staged[tree_path] = np.stack(
            [by_layer[i] for i in range(cfg.num_layers)])

    expected = {tp for tp, _ in _GGUF_MAP.values()}
    if cfg.tie_word_embeddings:
        expected.discard(("lm_head",))
    absent = expected - set(staged)
    if absent:
        raise ValueError(f"GGUF at {path} missing weights for {sorted(absent)}")

    params: Dict[str, Any] = {}
    target = jnp.dtype(cfg.dtype)
    for tree_path, arr in staged.items():
        node = params
        for k in tree_path[:-1]:
            node = node.setdefault(k, {})
        node[tree_path[-1]] = jnp.asarray(arr).astype(target)
    return params


__all__ = ["GgufFile", "load_gguf_params"]
