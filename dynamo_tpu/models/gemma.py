"""Gemma-2 family decoder — pure-functional jax over the paged KV cache.

Same serving contract as ``models/llama.py`` (``init_params`` /
``forward`` scan / ``forward_unrolled``), covering the gemma-2
architecture differences (verified against transformers'
``Gemma2ForCausalLM`` in tests):

- GeGLU MLP: ``gelu_tanh(x@gate) * (x@up) @ down``;
- sandwich norms: pre+post norms around BOTH attention and the MLP
  (4 RMSNorms per layer), with gemma's ``x * (1 + w)`` RMSNorm;
- embedding scaled by ``sqrt(hidden_size)``;
- attention-logit and final-logit soft-capping;
- alternating sliding-window layers (even layers sliding, odd global —
  HF gemma-2 convention), expressed as a per-layer window arg to the
  paged attention mask so the SAME paged cache serves both kinds;
- query scale from ``query_pre_attn_scalar`` instead of ``head_dim``.

Both stacked Pallas kernels (decode AND prefill, ``ops/pallas/``) carry
the per-layer window + softcap operands, so the scan forward serves this
family fully on kernels under ``attn_impl="pallas"``; ``forward_unrolled``
still ignores the override (the per-layer decode kernel variant has no
window/softcap) and runs the XLA paths.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import make_pages, make_pages_list
from dynamo_tpu.ops.attention import (
    paged_attention,
    paged_attention_layer,
    write_kv,
    write_kv_layer,
)
from dynamo_tpu.ops.rope import apply_rope
from dynamo_tpu.ops import quant

Params = Dict[str, Any]


def _rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    """gemma RMSNorm: f32 compute, ``x * (1 + w)`` (weights zero-init)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * (1.0 + w.astype(jnp.float32))).astype(x.dtype)


def layer_windows(cfg: ModelConfig) -> jnp.ndarray:
    """Per-layer attention window: even layers sliding, odd global (0)."""
    if not cfg.sliding_window:
        return jnp.zeros((cfg.num_layers,), jnp.int32)
    return jnp.asarray([cfg.sliding_window if (i % 2 == 0) else 0
                        for i in range(cfg.num_layers)], jnp.int32)


def _sm_scale(cfg: ModelConfig) -> float:
    base = cfg.query_pre_attn_scalar or cfg.head_dim
    return base ** -0.5


def init_params(cfg: ModelConfig, rng: jax.Array,
                scale: float = 0.02) -> Params:
    dtype = jnp.dtype(cfg.dtype)
    keys = iter(jax.random.split(rng, 16))

    def zeros(shape):
        return jnp.zeros(shape, dtype=dtype)  # gemma norms are zero-init

    def randn(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * scale
                ).astype(dtype)

    L, H, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    layers: Dict[str, jnp.ndarray] = {
        "attn_norm": zeros((L, H)),
        "post_attn_norm": zeros((L, H)),
        "pre_ffw_norm": zeros((L, H)),
        "post_ffw_norm": zeros((L, H)),
        "wq": randn(next(keys), (L, H, cfg.q_size)),
        "wk": randn(next(keys), (L, H, cfg.kv_size)),
        "wv": randn(next(keys), (L, H, cfg.kv_size)),
        "wo": randn(next(keys), (L, cfg.q_size, H)),
        "w_gate": randn(next(keys), (L, H, I)),
        "w_up": randn(next(keys), (L, H, I)),
        "w_down": randn(next(keys), (L, I, H)),
    }
    params: Params = {
        "embed": randn(next(keys), (cfg.vocab_size, H)),
        "layers": layers,
        "final_norm": zeros((H,)),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = randn(next(keys), (H, cfg.vocab_size))
    return params


def _project_qkv(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                 h: jnp.ndarray, positions: jnp.ndarray):
    B, S, _ = h.shape
    x = _rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
    q = quant.mm(lp, "wq", x).reshape(B, S, cfg.num_heads, cfg.head_dim)
    k = quant.mm(lp, "wk", x).reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
    v = quant.mm(lp, "wv", x).reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
    q = apply_rope(q, positions, cfg.rope_theta)
    k = apply_rope(k, positions, cfg.rope_theta)
    return q, k, v


def _finish_layer(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                  h: jnp.ndarray, attn: jnp.ndarray) -> jnp.ndarray:
    B, S, _ = h.shape
    eps = cfg.rms_norm_eps
    attn_out = quant.mm(lp, "wo", attn.reshape(B, S, cfg.q_size))
    h = h + _rms_norm(attn_out, lp["post_attn_norm"], eps)
    x = _rms_norm(h, lp["pre_ffw_norm"], eps)
    act = (jax.nn.gelu(quant.mm(lp, "w_gate", x), approximate=True)
           * quant.mm(lp, "w_up", x))
    mlp = quant.mm(lp, "w_down", act)
    return h + _rms_norm(mlp, lp["post_ffw_norm"], eps)


def _logits(cfg: ModelConfig, params: Params, h: jnp.ndarray,
            new_lens: jnp.ndarray, window: int = 1) -> jnp.ndarray:
    """Logits at each row's last ``window`` real new positions ([B, V], or
    [B, W, V] for the speculative-verify step — see llama._logits)."""
    h = _rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    if window == 1:
        last = jnp.maximum(new_lens - 1, 0)
        h_sel = jnp.take_along_axis(
            h, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    else:
        offs = jnp.arange(window, dtype=jnp.int32)[None, :]
        idx = jnp.maximum(new_lens[:, None] - window + offs, 0)
        h_sel = jnp.take_along_axis(h, idx[..., None], axis=1)
    lm8 = params.get("lm_head_q")
    if lm8 is not None:
        logits = quant.qdot(h_sel, lm8, params["lm_head_scale"],
                            out_dtype=jnp.float32)
    else:
        lm_head = params.get("lm_head")
        if lm_head is None:
            lm_head = params["embed"].T
        # model-dtype operands + f32 accumulation (see llama._logits)
        logits = jnp.dot(h_sel, lm_head,
                         preferred_element_type=jnp.float32)
    cap = cfg.final_logit_softcap
    if cap:
        logits = jnp.tanh(logits / cap) * cap
    return logits


def _embed(cfg: ModelConfig, params: Params,
           tokens: jnp.ndarray) -> jnp.ndarray:
    h = params["embed"][tokens]
    # gemma scales embeddings by sqrt(H), cast through the model dtype the
    # way HF does (the normalizer is rounded to bf16 there)
    normalizer = jnp.asarray(math.sqrt(cfg.hidden_size), h.dtype)
    return h * normalizer


def forward(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
            positions: jnp.ndarray, pages: jnp.ndarray,
            page_table: jnp.ndarray, total_lens: jnp.ndarray,
            new_lens: jnp.ndarray,
            attn_impl: Optional[Callable] = None,
            logits_window: int = 1
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scan-over-layers forward. ``attn_impl`` is honored only when it
    advertises ``supports_window_softcap`` (both stacked Pallas kernels —
    decode and prefill — carry gemma's per-layer sliding window + logit
    soft-capping) — otherwise the XLA paths serve, with identical math."""
    if not getattr(attn_impl, "supports_window_softcap", False):
        attn_impl = None
    attn_impl = attn_impl or paged_attention
    sm_scale = _sm_scale(cfg)
    softcap = cfg.attn_logit_softcap or None  # static: both paths accept
    windows = layer_windows(cfg)
    h = _embed(cfg, params, tokens)

    def body(carry, xs):
        h, pages = carry
        lp, lidx, win = xs
        q, k, v = _project_qkv(cfg, lp, h, positions)
        pages = write_kv(pages, lidx, k, v, page_table, positions, new_lens)
        attn = attn_impl(q, pages, lidx, page_table, positions,
                         total_lens, sm_scale, window=win,
                         softcap=softcap)
        h = _finish_layer(cfg, lp, h, attn)
        return (h, pages), None

    (h, pages), _ = jax.lax.scan(
        body, (h, pages),
        (params["layers"], jnp.arange(cfg.num_layers), windows))
    return _logits(cfg, params, h, new_lens, window=logits_window), pages


def forward_unrolled(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                     positions: jnp.ndarray, pages_list: List[jnp.ndarray],
                     page_table: jnp.ndarray, total_lens: jnp.ndarray,
                     new_lens: jnp.ndarray,
                     attn_impl: Optional[Callable] = None,
                     logits_window: int = 1
                     ) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
    """Unrolled forward. ``attn_impl`` is IGNORED: the Pallas decode kernel
    implements neither soft-capping nor sliding windows, so gemma always
    takes the XLA attention paths."""
    del attn_impl
    sm_scale = _sm_scale(cfg)
    softcap = (jnp.asarray(cfg.attn_logit_softcap, jnp.float32)
               if cfg.attn_logit_softcap else None)
    windows = layer_windows(cfg)
    h = _embed(cfg, params, tokens)
    out_pages: List[jnp.ndarray] = []
    for l in range(cfg.num_layers):
        lp = {k: v[l] for k, v in params["layers"].items()}
        q, k, v = _project_qkv(cfg, lp, h, positions)
        kv = write_kv_layer(pages_list[l], k, v, page_table, positions,
                            new_lens)
        attn = paged_attention_layer(q, kv, page_table, positions,
                                     total_lens, sm_scale,
                                     window=windows[l], softcap=softcap)
        h = _finish_layer(cfg, lp, h, attn)
        out_pages.append(kv)
    return _logits(cfg, params, h, new_lens, window=logits_window), out_pages


__all__ = ["init_params", "forward", "forward_unrolled", "make_pages",
           "make_pages_list", "layer_windows"]
