"""Mixture-of-Experts decoder (mixtral, qwen3-moe, deepseek-style top-k).

The reference serves MoE models only through external engines (wide-EP
DeepSeek-R1 via SGLang DeepEP, SURVEY §2.7); here the MoE layer is native
jax, sharing the Llama attention path (``models/llama.py`` helpers) and
swapping the dense MLP for routed experts:

- router: softmax over expert logits, top-k selection, optional
  renormalization (``norm_topk_prob``).
- two expert-compute backends, selected by ``cfg.moe_backend``:
  "dense" computes every expert over every token with routing weights as a
  mask — simple, fully static shapes, the right trade at decode batch
  sizes (tens of tokens); "dispatch" (``moe_mlp_dispatch``) gathers each
  expert's routed tokens into a fixed-capacity buffer first, cutting
  expert FLOPs from E to ~k x capacity_factor per token — the wide-EP
  path for large expert counts. Under GSPMD both shard the expert axis
  over ``ep`` so each chip computes only its local experts.

Weight layout (stacked for scan): ``w_router [L, H, E]``,
``w_gate/w_up [L, E, H, I]``, ``w_down [L, E, I, H]``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (
    Params,
    _finish_attn,
    _logits,
    _project_qkv,
    _rms_norm,
)
from dynamo_tpu.models import llama
from dynamo_tpu.ops.attention import (
    paged_attention,
    paged_attention_layer,
    write_kv,
    write_kv_layer,
)


def moe_mlp(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
            x: jnp.ndarray) -> jnp.ndarray:
    """Routed expert MLP. x: [B, S, H] (already normed) -> [B, S, H]."""
    top_w, top_i = _router_topk(cfg, lp, x)         # [B, S, k]
    # dense per-expert weights [B, S, E] (zero for unrouted experts)
    weights = jnp.sum(
        jax.nn.one_hot(top_i, cfg.num_experts, dtype=jnp.float32)
        * top_w[..., None], axis=2)                 # [B, S, E]
    gate = jnp.einsum("bsh,ehi->bsei", x, lp["w_gate"])
    up = jnp.einsum("bsh,ehi->bsei", x, lp["w_up"])
    act = jax.nn.silu(gate) * up
    out = jnp.einsum("bsei,eih->bseh", act, lp["w_down"])  # [B, S, E, H]
    return jnp.einsum("bse,bseh->bsh", weights.astype(out.dtype), out)


# decode-size batches get their dispatch capacity padded to 4x the
# expected per-expert load: drops become vanishingly rare where they would
# perturb a live conversation token, at a buffer cost that is negligible
# at these sizes (ADVICE r4: C was often 1-2 at decode, silently dropping)
_SMALL_BATCH_T = 64


def _router_topk(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                 x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Shared router: softmax over expert logits, top-k, optional renorm.
    x: [..., H] -> (weights [..., k] f32, indices [..., k] int32)."""
    logits = x @ lp["w_router"]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_w, top_i = jax.lax.top_k(probs, cfg.num_experts_per_tok)
    if cfg.norm_topk_prob:
        top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)
    return top_w, top_i


def moe_mlp_dispatch(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                     x: jnp.ndarray, ep_mesh=None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Capacity-factor token dispatch (GShard/Switch style): each expert
    computes only a fixed-capacity buffer of its ROUTED tokens instead of
    every token — expert FLOPs drop from ``E`` to ``~k * capacity_factor``
    per token, which is what makes wide-EP (DeepSeek-R1/Mixtral-class
    expert counts) credible. Reference role: SGLang DeepEP wide-EP
    (``components/backends/sglang/docs/dsr1-wideep-h100.md``); here the
    dispatch is a stable sort by expert + capacity-slot scatter/gather.

    Tokens routed past an expert's capacity are dropped for that expert
    (combine weight zero) — standard overflow semantics; raise
    ``cfg.moe_capacity_factor`` to make drops impossible at a given batch.
    Returns ``(out [B, S, H], dropped_assignments scalar int32)`` — the
    drop count flows to worker stats so operators can tell overflow
    degradation from model behavior (VERDICT r4 weak 5).

    ``ep_mesh`` (a Mesh with an ``ep`` axis, passed by the engine when EP
    is active) pins the ``[E, C, H]`` dispatch buffers to ``P("ep")`` so
    each chip holds only its ``[E_local, C]`` slice; XLA lowers the
    token scatter/combine across shards to all-to-alls on ICI.
    """
    B, S, H = x.shape
    xt = x.reshape(B * S, H)
    top_w, top_i = _router_topk(cfg, lp, xt)              # [T, k]
    out, dropped = expert_dispatch(
        xt, top_w, top_i, lp["w_gate"], lp["w_up"], lp["w_down"],
        cfg.num_experts, cfg.moe_capacity_factor, ep_mesh=ep_mesh)
    return out.reshape(B, S, H).astype(x.dtype), dropped


def expert_dispatch(xt: jnp.ndarray, top_w: jnp.ndarray,
                    top_i: jnp.ndarray, w_gate, w_up, w_down,
                    num_experts: int, capacity_factor: float,
                    ep_mesh=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sort-based capacity dispatch core (routing-agnostic — the deepseek
    family reuses it with its own gate). Memory LINEAR in tokens (a
    one-hot [T, E, C] combine tensor is O(T^2 k cf / E): ~GBs at prefill
    chunk sizes). Assignments group by expert via a stable argsort; each
    one's rank inside its expert group is its capacity slot, ranks >= C
    drop (token-major priority within an expert: earlier tokens win).
    Small (decode-size) batches pad C to 4x the expected per-expert load
    so drops there are vanishingly rare (``_SMALL_BATCH_T``).

    xt [T, H]; top_w/top_i [T, k]; expert weights [E, H, I]/[E, I, H].
    Returns ``(out [T, H] float32, dropped_assignments scalar int32)``
    (caller casts out). ``ep_mesh``: see ``moe_mlp_dispatch``."""
    import math
    T, H = xt.shape
    E = num_experts
    k = top_i.shape[1]
    C = max(1, min(T, math.ceil(T * k * capacity_factor / E)))
    if T <= _SMALL_BATCH_T:
        C = min(T, max(C, math.ceil(4 * T * k / E)))

    def shard_ep(arr):
        """Pin an [E, ...] buffer's expert axis to the mesh's ep axis."""
        if ep_mesh is None or ep_mesh.shape.get("ep", 1) <= 1:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec
        spec = PartitionSpec("ep", *([None] * (arr.ndim - 1)))
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(ep_mesh, spec))

    A = T * k
    flat_e = top_i.reshape(A)
    flat_w = top_w.reshape(A).astype(jnp.float32)
    flat_t = jnp.repeat(jnp.arange(T), k)
    order = jnp.argsort(flat_e, stable=True)
    sorted_e = flat_e[order]
    sorted_t = flat_t[order]
    sorted_w = flat_w[order]
    counts = jnp.zeros((E,), jnp.int32).at[flat_e].add(1)
    starts = jnp.cumsum(counts) - counts                  # [E]
    rank = jnp.arange(A) - starts[sorted_e]
    keep = rank < C
    dropped = jnp.sum(~keep).astype(jnp.int32)
    # overflow assignments route to a trash row past the expert buffers
    dest = jnp.where(keep, sorted_e * C + rank, E * C)

    xe = jnp.zeros((E * C + 1, H), xt.dtype).at[dest].set(xt[sorted_t])
    xe = shard_ep(xe[:E * C].reshape(E, C, H))            # [E, C, H]
    gate = jnp.einsum("ech,ehi->eci", xe, w_gate)
    up = jnp.einsum("ech,ehi->eci", xe, w_up)
    ye = shard_ep(jnp.einsum("eci,eih->ech", jax.nn.silu(gate) * up,
                             w_down))                     # [E, C, H]

    ye_flat = jnp.concatenate(
        [ye.reshape(E * C, H).astype(jnp.float32),
         jnp.zeros((1, H), jnp.float32)])                 # trash row = 0
    contrib = ye_flat[dest] * sorted_w[:, None]           # [A, H]
    out = jnp.zeros((T, H), jnp.float32).at[sorted_t].add(contrib)
    return out, dropped


def _moe_layer_tail(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                    h: jnp.ndarray, attn: jnp.ndarray, ep_mesh=None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (h, dropped_assignments) — dropped is a static 0 on the
    dense backend (it computes every expert; nothing can drop)."""
    h = _finish_attn(cfg, lp, h, attn)
    x = _rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
    if cfg.moe_backend == "dispatch":
        mlp, dropped = moe_mlp_dispatch(cfg, lp, x, ep_mesh=ep_mesh)
    else:
        mlp, dropped = moe_mlp(cfg, lp, x), jnp.zeros((), jnp.int32)
    return h + mlp, dropped


def init_params(cfg: ModelConfig, rng: jax.Array,
                scale: float = 0.02) -> Params:
    """Random init; attention/embedding weights come from llama.init_params,
    dense-MLP weights are replaced by the expert stack."""
    params = llama.init_params(cfg, rng, scale)
    layers = params["layers"]
    for k in ("w_gate", "w_up", "w_down"):
        del layers[k]
    dtype = jnp.dtype(cfg.dtype)
    L, H, E = cfg.num_layers, cfg.hidden_size, cfg.num_experts
    I = cfg.moe_intermediate_size or cfg.intermediate_size
    keys = iter(jax.random.split(jax.random.fold_in(rng, 7), 4))

    def randn(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    layers["w_router"] = randn(next(keys), (L, H, E))
    layers["w_gate"] = randn(next(keys), (L, E, H, I))
    layers["w_up"] = randn(next(keys), (L, E, H, I))
    layers["w_down"] = randn(next(keys), (L, E, I, H))
    return params


def forward(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
            positions: jnp.ndarray, pages: jnp.ndarray,
            page_table: jnp.ndarray, total_lens: jnp.ndarray,
            new_lens: jnp.ndarray,
            attn_impl: Optional[Callable] = None, ep_mesh=None,
            logits_window: int = 1
            ) -> Tuple[jnp.ndarray, jnp.ndarray, dict]:
    """Scan-over-layers MoE forward (llama.forward contract plus a third
    ``aux`` return: ``{"moe_dropped_assignments": scalar}`` summed over
    layers — the engine forwards it to worker stats)."""
    sm_scale = cfg.head_dim ** -0.5
    attn_impl = attn_impl or paged_attention
    h = params["embed"][tokens]

    def body(carry, xs):
        h, pages = carry
        lp, lidx = xs
        q, k, v = _project_qkv(cfg, lp, h, positions)
        pages = write_kv(pages, lidx, k, v, page_table, positions, new_lens)
        attn = attn_impl(q, pages, lidx, page_table, positions,
                         total_lens, sm_scale)
        h, dropped = _moe_layer_tail(cfg, lp, h, attn, ep_mesh=ep_mesh)
        return (h, pages), dropped

    (h, pages), drops = jax.lax.scan(
        body, (h, pages), (params["layers"], jnp.arange(cfg.num_layers)))
    aux = {"moe_dropped_assignments": jnp.sum(drops)}
    return (_logits(cfg, params, h, new_lens, window=logits_window),
            pages, aux)


def forward_unrolled(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                     positions: jnp.ndarray, pages_list: List[jnp.ndarray],
                     page_table: jnp.ndarray, total_lens: jnp.ndarray,
                     new_lens: jnp.ndarray,
                     attn_impl: Optional[Callable] = None, ep_mesh=None,
                     logits_window: int = 1
                     ) -> Tuple[jnp.ndarray, List[jnp.ndarray], dict]:
    """Unrolled MoE forward (llama.forward_unrolled contract plus the
    ``aux`` drop-count return, see ``forward``)."""
    sm_scale = cfg.head_dim ** -0.5
    attn_impl = attn_impl or paged_attention_layer
    h = params["embed"][tokens]
    out_pages: List[jnp.ndarray] = []
    total_dropped = jnp.zeros((), jnp.int32)
    for l in range(cfg.num_layers):
        lp = {k: v[l] for k, v in params["layers"].items()}
        q, k, v = _project_qkv(cfg, lp, h, positions)
        kv = write_kv_layer(pages_list[l], k, v, page_table, positions,
                            new_lens)
        attn = attn_impl(q, kv, page_table, positions, total_lens, sm_scale)
        h, dropped = _moe_layer_tail(cfg, lp, h, attn, ep_mesh=ep_mesh)
        total_dropped = total_dropped + dropped
        out_pages.append(kv)
    aux = {"moe_dropped_assignments": total_dropped}
    return (_logits(cfg, params, h, new_lens, window=logits_window),
            out_pages, aux)


__all__ = ["forward", "forward_unrolled", "init_params", "moe_mlp",
           "moe_mlp_dispatch", "expert_dispatch"]
