"""DeepSeek V2/V3 family: MLA attention + shared/routed MoE, pure jax.

The reference serves DeepSeek only through SGLang's CUDA stack (the wide-EP
DSR1 recipe, ``components/backends/sglang/docs/dsr1-wideep-h100.md``); here
the architecture is native. The TPU-first choice is Multi-head Latent
Attention in its **absorbed** inference form:

- The paged KV cache stores ONLY the compressed latent per token — slot 0
  of the generic page layout holds the rms-normed ``c_kv``
  (``kv_lora_rank`` wide), slot 1 the shared roped key (``qk_rope_head_dim``
  wide, zero-padded to the latent width). At DeepSeek-V3 geometry that is
  ~1 KB/token vs ~16 KB for equivalent MHA — the cache reduction that makes
  long-context R1 serving fit HBM.
- Attention runs IN LATENT SPACE: ``kv_b_proj`` is split into per-head
  ``W_UK``/``W_UV``; queries absorb ``W_UK`` (``q_nope @ W_UK``) so scores
  are ``q_lat · c_kv + q_pe · k_pe``, and the attention output re-expands
  through ``W_UV`` — no per-head K/V ever materializes for the context.
  This is algebraically identical to the HF eager path
  (``transformers/models/deepseek_v2/modeling_deepseek_v2.py:339-430``,
  checked by the parity test).
- RoPE follows ``cfg.rope_interleave``: the complex-pair convention HF
  defaults to for this family, or llama's rotate-half when a checkpoint
  ships de-interleaved weights; V3 additionally folds the yarn mscale
  into the softmax scale (``_mla_scale``).
- Layers are heterogeneous (``first_k_dense_replace`` dense layers, then
  MoE): the scan forward runs TWO scans over two stacked pytrees
  (``dense_layers`` / ``moe_layers``) sharing one paged cache, keeping the
  single-compiled-layer-body property per layer kind.
- The MoE gate matches HF exactly per generation: V2's f32 softmax scores
  with ``greedy`` / ``group_limited_greedy`` top-k (no renorm), and V3's
  aux-loss-free ``noaux_tc`` gate (sigmoid scores, e_score_correction_bias
  group selection, renormalized weights) — both scaled by
  ``routed_scaling_factor``; routed experts compute densely or via the
  capacity dispatch (``cfg.moe_backend``), plus the always-on shared
  experts.

Weight layout matches HF checkpoints after transpose; ``load_params``
assembles the two layer stacks from safetensors.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (
    _logits,
    _rms_norm,
    make_pages,
    make_pages_list,
)
from dynamo_tpu.ops.attention import NEG_INF, write_kv, write_kv_layer

Params = Dict[str, Any]


def yarn_freqs(cfg: ModelConfig) -> Tuple[np.ndarray, float]:
    """(inv_freq [dr/2], attention_factor) — HF's
    ``_compute_yarn_parameters`` (``modeling_rope_utils.py:246``) for the
    rope head dim; identity when the config carries no yarn scaling."""
    import math

    dr = cfg.qk_rope_head_dim
    base = cfg.rope_theta
    pos_freqs = base ** (np.arange(0, dr, 2, dtype=np.float64) / dr)
    if not cfg.rope_scaling_factor:
        return (1.0 / pos_freqs).astype(np.float32), 1.0
    factor = cfg.rope_scaling_factor
    orig = cfg.rope_orig_max_position or cfg.max_position_embeddings

    def get_mscale(scale, mscale=1.0):
        if scale <= 1:
            return 1.0
        return 0.1 * mscale * math.log(scale) + 1.0

    if cfg.rope_attention_factor:
        attention_factor = cfg.rope_attention_factor
    elif cfg.rope_mscale and cfg.rope_mscale_all_dim:
        attention_factor = (get_mscale(factor, cfg.rope_mscale)
                            / get_mscale(factor, cfg.rope_mscale_all_dim))
    else:
        attention_factor = get_mscale(factor)

    def correction_dim(num_rot):
        return (dr * math.log(orig / (num_rot * 2 * math.pi))
                / (2 * math.log(base)))

    low = max(math.floor(correction_dim(cfg.rope_beta_fast)), 0)
    high = min(math.ceil(correction_dim(cfg.rope_beta_slow)), dr - 1)
    if low == high:
        high += 0.001
    ramp = np.clip((np.arange(dr // 2, dtype=np.float64) - low)
                   / (high - low), 0, 1)
    extrapolation_factor = 1 - ramp
    inv_freq = ((1.0 / (factor * pos_freqs))
                * (1 - extrapolation_factor)
                + (1.0 / pos_freqs) * extrapolation_factor)
    return inv_freq.astype(np.float32), float(attention_factor)


def rope_interleaved(x: jnp.ndarray, positions: jnp.ndarray,
                     theta: float,
                     inv_freq: Optional[np.ndarray] = None,
                     scale: float = 1.0,
                     interleaved: bool = True) -> jnp.ndarray:
    """RoPE in either deepseek convention, the result scaled by the yarn
    ``attention_factor`` (HF multiplies the cos/sin magnitude):

    - ``interleaved=True`` — complex-pair (HF ``apply_rotary_emb`` /
      ``rope_interleave=True``): consecutive PAIRS (x[2i], x[2i+1]) rotate;
    - ``interleaved=False`` — llama rotate-half over (x[:D/2], x[D/2:]).

    x: [B, S, ..., D]; positions: [B, S]."""
    D = x.shape[-1]
    if inv_freq is None:
        inv_freq = 1.0 / (theta ** (jnp.arange(0, D, 2,
                                               dtype=jnp.float32) / D))
    else:
        inv_freq = jnp.asarray(inv_freq, jnp.float32)
    ang = positions.astype(jnp.float32)[..., None] * inv_freq  # [B, S, D/2]
    while ang.ndim < x.ndim:
        ang = ang[..., None, :]
    cos, sin = jnp.cos(ang) * scale, jnp.sin(ang) * scale
    if interleaved:
        xr = x[..., 0::2].astype(jnp.float32)
        xi = x[..., 1::2].astype(jnp.float32)
        out = jnp.stack([xr * cos - xi * sin, xr * sin + xi * cos],
                        axis=-1)
        return out.reshape(x.shape).astype(x.dtype)
    x1 = x[..., :D // 2].astype(jnp.float32)
    x2 = x[..., D // 2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


# ------------------------------------------------------------------- params

def _attn_leaves(cfg: ModelConfig, key, scale: float,
                 n: int) -> Dict[str, jnp.ndarray]:
    dtype = jnp.dtype(cfg.dtype)
    H = cfg.hidden_size
    qk_head = cfg.qk_nope_head_dim + cfg.qk_rope_head_dim
    keys = iter(jax.random.split(key, 8))

    def randn(shape):
        return (jax.random.normal(next(keys), (n,) + shape, jnp.float32)
                * scale).astype(dtype)

    leaves = {
        "attn_norm": jnp.ones((n, H), dtype),
        "wkv_a": randn((H, cfg.kv_lora_rank + cfg.qk_rope_head_dim)),
        "kv_a_norm": jnp.ones((n, cfg.kv_lora_rank), dtype),
        "wkv_b": randn((cfg.kv_lora_rank,
                        cfg.num_heads * (cfg.qk_nope_head_dim
                                         + cfg.v_head_dim))),
        "wo": randn((cfg.num_heads * cfg.v_head_dim, H)),
        "mlp_norm": jnp.ones((n, H), dtype),
    }
    if cfg.q_lora_rank:
        leaves["wq_a"] = randn((H, cfg.q_lora_rank))
        leaves["q_a_norm"] = jnp.ones((n, cfg.q_lora_rank), dtype)
        leaves["wq_b"] = randn((cfg.q_lora_rank, cfg.num_heads * qk_head))
    else:
        leaves["wq"] = randn((H, cfg.num_heads * qk_head))
    return leaves


def init_params(cfg: ModelConfig, rng: jax.Array,
                scale: float = 0.02) -> Params:
    """Random init with the two-stack layer layout (tests/benchmarks)."""
    dtype = jnp.dtype(cfg.dtype)
    H, E = cfg.hidden_size, cfg.num_experts
    Im = cfg.moe_intermediate_size or cfg.intermediate_size
    K = cfg.first_k_dense_replace
    M = cfg.num_layers - K
    k_dense, k_moe, k_embed, k_head = jax.random.split(rng, 4)

    def randn(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * scale) \
            .astype(dtype)

    params: Params = {
        "embed": randn(k_embed, (cfg.vocab_size, H)),
        "final_norm": jnp.ones((H,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = randn(k_head, (H, cfg.vocab_size))
    if K:
        dl = _attn_leaves(cfg, k_dense, scale, K)
        ks = iter(jax.random.split(jax.random.fold_in(k_dense, 1), 3))
        dl["w_gate"] = randn(next(ks), (K, H, cfg.intermediate_size))
        dl["w_up"] = randn(next(ks), (K, H, cfg.intermediate_size))
        dl["w_down"] = randn(next(ks), (K, cfg.intermediate_size, H))
        params["dense_layers"] = dl
    if M:
        ml = _attn_leaves(cfg, k_moe, scale, M)
        ks = iter(jax.random.split(jax.random.fold_in(k_moe, 1), 8))
        ml["w_router"] = randn(next(ks), (M, H, E))
        if cfg.topk_method == "noaux_tc":
            ml["router_bias"] = jnp.zeros((M, E), jnp.float32)
        ml["w_gate"] = randn(next(ks), (M, E, H, Im))
        ml["w_up"] = randn(next(ks), (M, E, H, Im))
        ml["w_down"] = randn(next(ks), (M, E, Im, H))
        if cfg.n_shared_experts:
            Is = Im * cfg.n_shared_experts
            ml["ws_gate"] = randn(next(ks), (M, H, Is))
            ml["ws_up"] = randn(next(ks), (M, H, Is))
            ml["ws_down"] = randn(next(ks), (M, Is, H))
        params["moe_layers"] = ml
    return params


# ---------------------------------------------------------------- attention

def _mla_qkv(cfg: ModelConfig, lp: Dict[str, jnp.ndarray], h: jnp.ndarray,
             positions: jnp.ndarray):
    """Pre-attention MLA math: queries (latent-absorbed + rope) and the new
    tokens' cache rows. Returns (q_lat [B,S,nh,dkv], q_pe [B,S,nh,dr],
    c_kv [B,S,dkv], k_pe [B,S,dr], w_uv [nh,dkv,dv])."""
    B, S, H = h.shape
    nh = cfg.num_heads
    dn, dr = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    dkv, dv = cfg.kv_lora_rank, cfg.v_head_dim
    eps = cfg.rms_norm_eps
    x = _rms_norm(h, lp["attn_norm"], eps)
    if cfg.q_lora_rank:
        q = _rms_norm(x @ lp["wq_a"], lp["q_a_norm"], eps) @ lp["wq_b"]
    else:
        q = x @ lp["wq"]
    q = q.reshape(B, S, nh, dn + dr)
    q_nope, q_pe = q[..., :dn], q[..., dn:]
    inv_freq, att_scale = yarn_freqs(cfg)
    q_pe = rope_interleaved(q_pe, positions, cfg.rope_theta,
                            inv_freq=inv_freq, scale=att_scale,
                            interleaved=cfg.rope_interleave)

    ckv = x @ lp["wkv_a"]                                  # [B,S,dkv+dr]
    c_kv = _rms_norm(ckv[..., :dkv], lp["kv_a_norm"], eps)
    k_pe = rope_interleaved(ckv[..., dkv:], positions, cfg.rope_theta,
                            inv_freq=inv_freq, scale=att_scale,
                            interleaved=cfg.rope_interleave)

    w_kb = lp["wkv_b"].reshape(dkv, nh, dn + dv)
    w_uk = w_kb[..., :dn].transpose(1, 0, 2)               # [nh, dkv, dn]
    w_uv = w_kb[..., dn:].transpose(1, 0, 2)               # [nh, dkv, dv]
    # absorb W_UK into the queries: scores run in latent space
    q_lat = jnp.einsum("bsnd,nkd->bsnk", q_nope.astype(jnp.float32),
                       w_uk.astype(jnp.float32))
    return q_lat, q_pe, c_kv, k_pe, w_uv


def _cache_rows(cfg: ModelConfig, c_kv: jnp.ndarray, k_pe: jnp.ndarray):
    """(k_new, v_new) for the generic paged write: slot 0 = latent,
    slot 1 = rope key padded to the latent width. Both [B, S, 1, dkv]."""
    pad = cfg.kv_lora_rank - cfg.qk_rope_head_dim
    k_pe_padded = jnp.pad(k_pe, ((0, 0), (0, 0), (0, pad)))
    return c_kv[:, :, None, :], k_pe_padded[:, :, None, :]


# pages per streamed chunk on the blockwise path (matches ops/attention)
PAGES_PER_CHUNK = 8


def _mla_scale(cfg: ModelConfig) -> float:
    """Softmax scale. V3 folds the yarn mscale into the SCORE scale
    (``modeling_deepseek_v3.py:371-377``: scaling *= mscale^2 when
    rope_scaling carries mscale_all_dim); V2 expresses it through the
    rope attention_factor instead (handled in ``yarn_freqs``)."""
    import math

    scale = (cfg.qk_nope_head_dim + cfg.qk_rope_head_dim) ** -0.5
    if (cfg.model_type == "deepseek_v3" and cfg.rope_scaling_factor
            and cfg.rope_mscale_all_dim):
        m = (0.1 * cfg.rope_mscale_all_dim
             * math.log(cfg.rope_scaling_factor) + 1.0
             if cfg.rope_scaling_factor > 1 else 1.0)
        scale *= m * m
    return scale


def _expand_and_project(cfg: ModelConfig, lp, h, lat, w_uv) -> jnp.ndarray:
    """lat [B,S,nh,dkv] latent attention output -> W_UV expand -> wo
    residual."""
    B, S, H = h.shape
    out = jnp.einsum("bsnk,nkd->bsnd", lat, w_uv.astype(jnp.float32))
    out = out.reshape(B, S, cfg.num_heads * cfg.v_head_dim).astype(h.dtype)
    return h + out @ lp["wo"]


def _mla_attend(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                h: jnp.ndarray, q_lat, q_pe, w_uv,
                ckv_ctx: jnp.ndarray, kpe_ctx: jnp.ndarray,
                positions: jnp.ndarray, total_lens: jnp.ndarray
                ) -> jnp.ndarray:
    """Latent-space attention + output projection residual (direct path:
    decode steps / small tables — the full [B,nh,S,T] scores fit).
    ckv_ctx/kpe_ctx: [B, T, dkv] / [B, T, dr] gathered context."""
    sm_scale = _mla_scale(cfg)
    T = ckv_ctx.shape[1]
    scores = (jnp.einsum("bsnk,btk->bnst", q_lat,
                         ckv_ctx.astype(jnp.float32))
              + jnp.einsum("bsnd,btd->bnst", q_pe.astype(jnp.float32),
                           kpe_ctx.astype(jnp.float32))) * sm_scale
    t_pos = jnp.arange(T)[None, None, None, :]
    mask = ((t_pos <= positions[:, None, :, None])
            & (t_pos < total_lens[:, None, None, None]))
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)                # [B,nh,S,T]
    lat = jnp.einsum("bnst,btk->bsnk", probs,
                     ckv_ctx.astype(jnp.float32))          # [B,S,nh,dkv]
    return _expand_and_project(cfg, lp, h, lat, w_uv)


def _mla_attend_blockwise(cfg: ModelConfig, lp, h, q_lat, q_pe, w_uv,
                          gather_chunk, num_table_pages: int, ps: int,
                          positions: jnp.ndarray, total_lens: jnp.ndarray
                          ) -> jnp.ndarray:
    """Flash-style chunked latent attention for prefill (S > 1): the
    context streams in page chunks with an online softmax, so the peak
    intermediate is ``[B, nh, S, span]`` scores + a fixed
    ``[B, nh, S, dkv]`` latent accumulator regardless of context length —
    the full-gather path's ``[B, nh, S, T]`` scores are GBs per layer at
    DeepSeek-V3 head counts (same failure mode
    ``ops/attention._attend_blockwise`` exists for)."""
    B, S, H = h.shape
    nh, dkv = cfg.num_heads, cfg.kv_lora_rank
    sm_scale = _mla_scale(cfg)
    span = PAGES_PER_CHUNK * ps
    n_static = -(-num_table_pages // PAGES_PER_CHUNK)
    n_chunks = jnp.minimum(
        (jnp.max(total_lens) + span - 1) // span, n_static)
    q_pe32 = q_pe.astype(jnp.float32)

    def body(c, carry):
        num, den, mx = carry
        ckv, kpe = gather_chunk(c)            # [B, span, dkv] / [B, span, dr]
        s = (jnp.einsum("bsnk,btk->bnst", q_lat, ckv.astype(jnp.float32))
             + jnp.einsum("bsnd,btd->bnst", q_pe32,
                          kpe.astype(jnp.float32))) * sm_scale
        t_pos = c * span + jnp.arange(span)
        mask = ((t_pos[None, None, None, :] <= positions[:, None, :, None])
                & (t_pos[None, None, None, :]
                   < total_lens[:, None, None, None]))
        s = jnp.where(mask, s, NEG_INF)
        mx_new = jnp.maximum(mx, jnp.max(s, axis=-1))      # [B,nh,S]
        p = jnp.exp(s - mx_new[..., None])
        p = jnp.where((mx_new > NEG_INF / 2)[..., None], p, 0.0)
        scale = jnp.where(mx > NEG_INF / 2, jnp.exp(mx - mx_new), 0.0)
        pv = jnp.einsum("bnst,btk->bnsk", p, ckv.astype(jnp.float32))
        num = num * scale[..., None] + pv
        den = den * scale + jnp.sum(p, axis=-1)
        return num, den, mx_new

    num0 = jnp.zeros((B, nh, S, dkv), jnp.float32)
    den0 = jnp.zeros((B, nh, S), jnp.float32)
    mx0 = jnp.full((B, nh, S), NEG_INF, jnp.float32)
    num, den, _mx = jax.lax.fori_loop(0, n_chunks, body, (num0, den0, mx0))
    lat = (num / jnp.maximum(den, 1e-20)[..., None]) \
        .transpose(0, 2, 1, 3)                             # [B,S,nh,dkv]
    return _expand_and_project(cfg, lp, h, lat, w_uv)


def _gather_ctx(cfg: ModelConfig, gathered: jnp.ndarray):
    """[B, P, 2, 1, ps, dkv] gathered pages -> latent/rope context."""
    B, P, _two, _one, ps, dkv = gathered.shape
    ckv = gathered[:, :, 0, 0].reshape(B, P * ps, dkv)
    kpe = gathered[:, :, 1, 0].reshape(B, P * ps, dkv)[
        ..., :cfg.qk_rope_head_dim]
    return ckv, kpe


# --------------------------------------------------------------------- MoE

def _gate(cfg: ModelConfig, lp: Dict[str, jnp.ndarray], x: jnp.ndarray):
    """HF-exact DeepSeek gate, per generation: V2 = f32 softmax scores
    with greedy / group-limited top-k (no renorm); V3 (``noaux_tc``) =
    the sigmoid + e_score_correction_bias gate (``_gate_noaux``). Both
    scale by routed_scaling_factor."""
    if cfg.topk_method == "noaux_tc":
        return _gate_noaux(cfg, lp, x)
    scores = jax.nn.softmax(
        (x.astype(jnp.float32) @ lp["w_router"].astype(jnp.float32)),
        axis=-1)                                           # [B,S,E]
    k = cfg.num_experts_per_tok
    if cfg.topk_method == "group_limited_greedy":
        B, S, E = scores.shape
        g = cfg.n_group
        group_scores = scores.reshape(B, S, g, E // g).max(axis=-1)
        _gv, gi = jax.lax.top_k(group_scores, cfg.topk_group)
        group_mask = jnp.sum(
            jax.nn.one_hot(gi, g, dtype=scores.dtype), axis=2)  # [B,S,g]
        score_mask = jnp.repeat(group_mask, E // g, axis=-1)
        masked = jnp.where(score_mask > 0, scores, 0.0)
        top_w, top_i = jax.lax.top_k(masked, k)
    elif cfg.topk_method == "greedy":
        top_w, top_i = jax.lax.top_k(scores, k)
    else:
        raise NotImplementedError(f"topk_method {cfg.topk_method!r}")
    return top_w * cfg.routed_scaling_factor, top_i


def _gate_noaux(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                x: jnp.ndarray):
    """V3 aux-loss-free gate (``DeepseekV3TopkRouter``): sigmoid scores,
    bias-corrected group-limited selection (group score = sum of its top-2
    corrected scores), weights taken from the UNCORRECTED scores,
    normalized (+1e-20) when norm_topk_prob, scaled."""
    scores = jax.nn.sigmoid(
        x.astype(jnp.float32) @ lp["w_router"].astype(jnp.float32))
    sfc = scores + lp["router_bias"].astype(jnp.float32)   # [B,S,E]
    B, S, E = scores.shape
    g, k = cfg.n_group, cfg.num_experts_per_tok
    group_scores = jnp.sum(
        jax.lax.top_k(sfc.reshape(B, S, g, E // g), 2)[0], axis=-1)
    _gv, gi = jax.lax.top_k(group_scores, cfg.topk_group)
    group_mask = jnp.sum(jax.nn.one_hot(gi, g, dtype=sfc.dtype), axis=2)
    score_mask = jnp.repeat(group_mask, E // g, axis=-1)
    masked = jnp.where(score_mask > 0, sfc, 0.0)
    _w, top_i = jax.lax.top_k(masked, k)
    top_w = jnp.take_along_axis(scores, top_i, axis=-1)
    if cfg.norm_topk_prob:
        top_w = top_w / (jnp.sum(top_w, axis=-1, keepdims=True) + 1e-20)
    return top_w * cfg.routed_scaling_factor, top_i


def _moe_mlp(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
             x: jnp.ndarray, ep_mesh=None
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Routed experts + shared experts. ``cfg.moe_backend`` picks the
    routed compute: dense-mask (every expert, decode-batch default) or the
    capacity-factor token dispatch (``models/moe.py expert_dispatch`` —
    the wide-EP path that makes 256-expert DeepSeek-V3 credible).
    Returns ``(out, dropped_assignments)`` (dropped is a static 0 on the
    dense backend); ``ep_mesh`` pins dispatch buffers to the ep axis."""
    top_w, top_i = _gate(cfg, lp, x)
    dropped = jnp.zeros((), jnp.int32)
    if cfg.moe_backend == "dispatch":
        from dynamo_tpu.models.moe import expert_dispatch
        B, S, H = x.shape
        routed, dropped = expert_dispatch(
            x.reshape(B * S, H), top_w.reshape(B * S, -1),
            top_i.reshape(B * S, -1), lp["w_gate"], lp["w_up"],
            lp["w_down"], cfg.num_experts,
            cfg.moe_capacity_factor, ep_mesh=ep_mesh)
        routed = routed.reshape(B, S, H).astype(x.dtype)
    else:
        weights = jnp.sum(
            jax.nn.one_hot(top_i, cfg.num_experts, dtype=jnp.float32)
            * top_w[..., None], axis=2)                    # [B,S,E]
        gate = jnp.einsum("bsh,ehi->bsei", x, lp["w_gate"])
        up = jnp.einsum("bsh,ehi->bsei", x, lp["w_up"])
        act = jax.nn.silu(gate) * up
        routed = jnp.einsum("bse,bseh->bsh", weights.astype(x.dtype),
                            jnp.einsum("bsei,eih->bseh", act,
                                       lp["w_down"]))
    if cfg.n_shared_experts:
        shared = (jax.nn.silu(x @ lp["ws_gate"])
                  * (x @ lp["ws_up"])) @ lp["ws_down"]
        routed = routed + shared
    return routed, dropped


def _dense_mlp(lp: Dict[str, jnp.ndarray], x: jnp.ndarray) -> jnp.ndarray:
    return (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]


# ----------------------------------------------------------------- forward

def _layer_step(cfg: ModelConfig, lp, h, positions, total_lens, new_lens,
                page_table, pages, lidx, *, moe: bool, layered: bool,
                use_pallas: bool = False, ep_mesh=None):
    """One decoder layer against the paged latent cache. ``layered`` means
    ``pages`` is the per-layer buffer (unrolled path) instead of the
    stacked cache. ``use_pallas`` routes S==1 through the MLA Pallas
    decode kernel (``ops/pallas/mla_decode.py``) when the geometry
    supports it. Returns ``(h, pages, dropped_assignments)``."""
    from dynamo_tpu.ops.attention import _pad_table

    q_lat, q_pe, c_kv, k_pe, w_uv = _mla_qkv(cfg, lp, h, positions)
    k_new, v_new = _cache_rows(cfg, c_kv, k_pe)
    if layered:
        pages = write_kv_layer(pages, k_new, v_new, page_table, positions,
                               new_lens)
    else:
        pages = write_kv(pages, lidx, k_new, v_new, page_table, positions,
                         new_lens)
    S = h.shape[1]
    P = page_table.shape[1]
    ps = pages.shape[-2]
    if use_pallas and S == 1:
        from dynamo_tpu.ops.pallas.mla_decode import (
            mla_paged_decode_layer, mla_paged_decode_stacked)

        if layered:
            lat = mla_paged_decode_layer(q_lat, q_pe, pages, page_table,
                                         total_lens, _mla_scale(cfg))
        else:
            lat = mla_paged_decode_stacked(q_lat, q_pe, pages, lidx,
                                           page_table, total_lens,
                                           _mla_scale(cfg))
        h = _expand_and_project(cfg, lp, h, lat, w_uv)
    elif use_pallas and not layered:
        from dynamo_tpu.ops.pallas.mla_prefill import (
            mla_paged_prefill_stacked)

        lat = mla_paged_prefill_stacked(q_lat, q_pe, pages, lidx,
                                        page_table, positions, total_lens,
                                        _mla_scale(cfg))
        h = _expand_and_project(cfg, lp, h, lat, w_uv)
    elif S > 1 and P > PAGES_PER_CHUNK:
        table = _pad_table(page_table, PAGES_PER_CHUNK)

        def gather_chunk(c):
            tbl = jax.lax.dynamic_slice(
                table, (0, c * PAGES_PER_CHUNK),
                (table.shape[0], PAGES_PER_CHUNK))
            g = pages[tbl] if layered else pages[lidx, tbl]
            return _gather_ctx(cfg, g)

        h = _mla_attend_blockwise(cfg, lp, h, q_lat, q_pe, w_uv,
                                  gather_chunk, P, ps, positions,
                                  total_lens)
    else:
        gathered = (pages[page_table] if layered
                    else pages[lidx, page_table])
        ckv_ctx, kpe_ctx = _gather_ctx(cfg, gathered)
        h = _mla_attend(cfg, lp, h, q_lat, q_pe, w_uv, ckv_ctx, kpe_ctx,
                        positions, total_lens)
    x = _rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
    if moe:
        mlp, dropped = _moe_mlp(cfg, lp, x, ep_mesh=ep_mesh)
    else:
        mlp, dropped = _dense_mlp(lp, x), jnp.zeros((), jnp.int32)
    h = h + mlp
    return h, pages, dropped


def forward(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
            positions: jnp.ndarray, pages: jnp.ndarray,
            page_table: jnp.ndarray, total_lens: jnp.ndarray,
            new_lens: jnp.ndarray,
            attn_impl: Optional[Callable] = None, ep_mesh=None,
            logits_window: int = 1
            ) -> Tuple[jnp.ndarray, jnp.ndarray, dict]:
    """Scan forward (llama.forward contract plus the ``aux`` third return
    carrying ``moe_dropped_assignments``, like models/moe.py). The GQA
    Pallas kernels the engine passes as ``attn_impl`` cannot run latent
    attention, so they are never CALLED here — but an impl carrying the
    ``pallas_paged_kernel`` marker (both stacked kernels set it) opts
    the family into its OWN latent kernels when the geometry supports it
    (kv_lora_rank % 128 == 0 — true for real V2/V3 checkpoints): S==1
    steps ride ``ops/pallas/mla_decode.py``, S>1 chunks
    ``ops/pallas/mla_prefill.py``. Any other non-None impl is ignored
    (the XLA paths serve), matching gemma's marker pattern."""
    from dynamo_tpu.ops.pallas.mla_decode import supports as mla_supports

    use_pallas = (getattr(attn_impl, "pallas_paged_kernel", False)
                  and mla_supports(cfg.kv_lora_rank, pages.shape[-2]))
    K = cfg.first_k_dense_replace
    h = params["embed"][tokens]
    total_dropped = jnp.zeros((), jnp.int32)

    def body(moe):
        def step(carry, xs):
            h, pages = carry
            lp, lidx = xs
            h, pages, dropped = _layer_step(
                cfg, lp, h, positions, total_lens, new_lens, page_table,
                pages, lidx, moe=moe, layered=False, use_pallas=use_pallas,
                ep_mesh=ep_mesh)
            return (h, pages), dropped
        return step

    if K and "dense_layers" in params:
        (h, pages), _ = jax.lax.scan(
            body(False), (h, pages),
            (params["dense_layers"], jnp.arange(K)))
    if "moe_layers" in params:
        (h, pages), drops = jax.lax.scan(
            body(True), (h, pages),
            (params["moe_layers"], K + jnp.arange(cfg.num_layers - K)))
        total_dropped = jnp.sum(drops)
    aux = {"moe_dropped_assignments": total_dropped}
    return (_logits(cfg, params, h, new_lens, window=logits_window),
            pages, aux)


def forward_unrolled(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                     positions: jnp.ndarray, pages_list: List[jnp.ndarray],
                     page_table: jnp.ndarray, total_lens: jnp.ndarray,
                     new_lens: jnp.ndarray,
                     attn_impl: Optional[Callable] = None, ep_mesh=None,
                     logits_window: int = 1
                     ) -> Tuple[jnp.ndarray, List[jnp.ndarray], dict]:
    """Python-unrolled forward over per-layer latent buffers. An
    ``attn_impl`` carrying the ``pallas_paged_kernel`` marker opts S==1
    steps into the per-layer MLA Pallas kernel (see ``forward``)."""
    from dynamo_tpu.ops.pallas.mla_decode import supports as mla_supports

    use_pallas = (getattr(attn_impl, "pallas_paged_kernel", False)
                  and mla_supports(cfg.kv_lora_rank,
                                   pages_list[0].shape[-2]))
    K = cfg.first_k_dense_replace
    h = params["embed"][tokens]
    out_pages: List[jnp.ndarray] = []
    total_dropped = jnp.zeros((), jnp.int32)
    for l in range(cfg.num_layers):
        moe = l >= K
        stack = params["moe_layers"] if moe else params["dense_layers"]
        li = l - K if moe else l
        lp = {k: v[li] for k, v in stack.items()}
        h, kv, dropped = _layer_step(
            cfg, lp, h, positions, total_lens, new_lens, page_table,
            pages_list[l], 0, moe=moe, layered=True,
            use_pallas=use_pallas, ep_mesh=ep_mesh)
        total_dropped = total_dropped + dropped
        out_pages.append(kv)
    aux = {"moe_dropped_assignments": total_dropped}
    return (_logits(cfg, params, h, new_lens, window=logits_window),
            out_pages, aux)


# ------------------------------------------------------------------ loader

def load_params(cfg: ModelConfig, path: str,
                shardings: Optional[Dict[str, Any]] = None) -> Params:
    """Assemble the two-stack pytree from an HF deepseek checkpoint."""
    from safetensors import safe_open

    from dynamo_tpu.models.hf_loader import _checkpoint_files

    K = cfg.first_k_dense_replace
    attn = {
        "input_layernorm.weight": ("attn_norm", False),
        "self_attn.kv_a_proj_with_mqa.weight": ("wkv_a", True),
        "self_attn.kv_a_layernorm.weight": ("kv_a_norm", False),
        "self_attn.kv_b_proj.weight": ("wkv_b", True),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("mlp_norm", False),
    }
    if cfg.q_lora_rank:
        attn.update({
            "self_attn.q_a_proj.weight": ("wq_a", True),
            "self_attn.q_a_layernorm.weight": ("q_a_norm", False),
            "self_attn.q_b_proj.weight": ("wq_b", True),
        })
    else:
        attn["self_attn.q_proj.weight"] = ("wq", True)
    dense_mlp = {
        "mlp.gate_proj.weight": ("w_gate", True),
        "mlp.up_proj.weight": ("w_up", True),
        "mlp.down_proj.weight": ("w_down", True),
    }
    moe_mlp_names = {
        "mlp.gate.weight": ("w_router", True),
        "mlp.gate.e_score_correction_bias": ("router_bias", False),
        "mlp.shared_experts.gate_proj.weight": ("ws_gate", True),
        "mlp.shared_experts.up_proj.weight": ("ws_up", True),
        "mlp.shared_experts.down_proj.weight": ("ws_down", True),
    }
    expert_names = {
        "gate_proj.weight": "w_gate",
        "up_proj.weight": "w_up",
        "down_proj.weight": "w_down",
    }
    top = {
        "model.embed_tokens.weight": (("embed",), False),
        "model.norm.weight": (("final_norm",), False),
    }
    if not cfg.tie_word_embeddings:
        top["lm_head.weight"] = (("lm_head",), True)

    staged: Dict[tuple, Any] = {}
    by_layer: Dict[Tuple[str, str], Dict[int, np.ndarray]] = {}
    by_expert: Dict[str, Dict[Tuple[int, int], np.ndarray]] = {}
    for f in _checkpoint_files(path):
        with safe_open(f, framework="np") as sf:
            for name in sf.keys():
                if name in top:
                    tree_path, tr = top[name]
                    t = sf.get_tensor(name)
                    staged[tree_path] = (np.ascontiguousarray(t.T)
                                         if tr else t)
                    continue
                if not name.startswith("model.layers."):
                    continue
                rest = name[len("model.layers."):]
                idx, _, tail = rest.partition(".")
                layer = int(idx)
                stack = "dense_layers" if layer < K else "moe_layers"
                if tail in attn or (stack == "dense_layers"
                                    and tail in dense_mlp) \
                        or (stack == "moe_layers"
                            and tail in moe_mlp_names):
                    leaf, tr = (attn.get(tail) or dense_mlp.get(tail)
                                or moe_mlp_names.get(tail))
                    t = sf.get_tensor(name)
                    if tr:
                        t = np.ascontiguousarray(t.T)
                    by_layer.setdefault((stack, leaf), {})[layer] = t
                    continue
                if tail.startswith("mlp.experts."):
                    sub = tail[len("mlp.experts."):]
                    j, _, wname = sub.partition(".")
                    leaf = expert_names.get(wname)
                    if leaf is not None:
                        t = np.ascontiguousarray(sf.get_tensor(name).T)
                        by_expert.setdefault(leaf, {})[
                            (layer, int(j))] = t

    for (stack, leaf), d in by_layer.items():
        if stack == "dense_layers":
            idxs = list(range(K))
        else:
            idxs = list(range(K, cfg.num_layers))
        missing = set(idxs) - set(d)
        if missing:
            raise ValueError(f"missing layers {sorted(missing)} for "
                             f"{stack}.{leaf}")
        staged[(stack, leaf)] = np.stack([d[i] for i in idxs])
    for leaf, d in by_expert.items():
        want = {(i, j) for i in range(K, cfg.num_layers)
                for j in range(cfg.num_experts)}
        missing = want - set(d)
        if missing:
            raise ValueError(
                f"checkpoint missing {len(missing)} expert tensors for "
                f"moe_layers.{leaf} (e.g. {sorted(missing)[:3]})")
        staged[("moe_layers", leaf)] = np.stack([
            np.stack([d[(i, j)] for j in range(cfg.num_experts)])
            for i in range(K, cfg.num_layers)])

    params: Params = {}
    dtype = jnp.dtype(cfg.dtype)
    for tree_path, arr in staged.items():
        node = params
        for k in tree_path[:-1]:
            node = node.setdefault(k, {})
        # the V3 gate's e_score_correction_bias stays f32: rounding it to
        # bf16 flips expert selections near group/top-k boundaries
        leaf_dtype = (jnp.float32 if tree_path[-1] == "router_bias"
                      else dtype)
        leaf = jnp.asarray(arr).astype(leaf_dtype)
        if shardings is not None:
            spec = shardings
            for k in tree_path:
                spec = spec.get(k) if isinstance(spec, dict) else None
                if spec is None:
                    break
            if spec is not None:
                leaf = jax.device_put(leaf, spec)
        node[tree_path[-1]] = leaf
    return params


__all__ = ["init_params", "forward", "forward_unrolled", "load_params",
           "rope_interleaved", "make_pages", "make_pages_list"]
