"""Load HF safetensors checkpoints into the stacked-layer param pytree.

Capability parity: reference ``lib/llm/src/local_model.rs`` resolves an HF repo
directory for its engines; here the weights are actually consumed natively.
Torch ``Linear`` stores [out, in]; we transpose to [in, out] and stack all
layers on a leading axis (the ``lax.scan`` layout of ``models/llama.py``).
MoE checkpoints (mixtral ``block_sparse_moe``, qwen3-moe ``mlp.experts``)
additionally stack the expert axis: ``[L, E, ...]``.

Sharded checkpoints (``model.safetensors.index.json``) are supported; tensors
are loaded one file at a time to bound host RAM. Optionally a sharding pytree
can be supplied so each stacked array is placed directly with
``jax.device_put`` (avoids a full host copy of the assembled model).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig

try:
    from safetensors import safe_open
except ImportError:  # pragma: no cover
    safe_open = None


def _checkpoint_files(path: str) -> List[str]:
    index = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        return sorted({os.path.join(path, v) for v in weight_map.values()})
    single = os.path.join(path, "model.safetensors")
    if os.path.exists(single):
        return [single]
    raise FileNotFoundError(f"no safetensors checkpoint under {path}")


# HF tensor name -> (pytree path, transpose?). "{i}" is the layer index,
# "{j}" the expert index (expert tensors stack on a second axis).
def _name_map(cfg: ModelConfig) -> Dict[str, Any]:
    m = {
        "model.embed_tokens.weight": (("embed",), False),
        "model.norm.weight": (("final_norm",), False),
        "model.layers.{i}.input_layernorm.weight": (("layers", "attn_norm"), False),
        "model.layers.{i}.self_attn.q_proj.weight": (("layers", "wq"), True),
        "model.layers.{i}.self_attn.k_proj.weight": (("layers", "wk"), True),
        "model.layers.{i}.self_attn.v_proj.weight": (("layers", "wv"), True),
        "model.layers.{i}.self_attn.o_proj.weight": (("layers", "wo"), True),
        "model.layers.{i}.post_attention_layernorm.weight": (("layers", "mlp_norm"), False),
    }
    if cfg.num_experts:
        if cfg.model_type == "mixtral":
            m.update({
                "model.layers.{i}.block_sparse_moe.gate.weight":
                    (("layers", "w_router"), True),
                # mixtral naming: w1 = gate, w3 = up, w2 = down
                "model.layers.{i}.block_sparse_moe.experts.{j}.w1.weight":
                    (("layers", "w_gate"), True),
                "model.layers.{i}.block_sparse_moe.experts.{j}.w3.weight":
                    (("layers", "w_up"), True),
                "model.layers.{i}.block_sparse_moe.experts.{j}.w2.weight":
                    (("layers", "w_down"), True),
            })
        else:  # qwen3_moe / deepseek-style naming
            m.update({
                "model.layers.{i}.mlp.gate.weight":
                    (("layers", "w_router"), True),
                "model.layers.{i}.mlp.experts.{j}.gate_proj.weight":
                    (("layers", "w_gate"), True),
                "model.layers.{i}.mlp.experts.{j}.up_proj.weight":
                    (("layers", "w_up"), True),
                "model.layers.{i}.mlp.experts.{j}.down_proj.weight":
                    (("layers", "w_down"), True),
            })
    else:
        m.update({
            "model.layers.{i}.mlp.gate_proj.weight": (("layers", "w_gate"), True),
            "model.layers.{i}.mlp.up_proj.weight": (("layers", "w_up"), True),
            "model.layers.{i}.mlp.down_proj.weight": (("layers", "w_down"), True),
        })
    if cfg.model_type.startswith("gemma"):
        # gemma-2 sandwich norms: post_attention_layernorm is the POST
        # norm on the attention residual (not the llama mlp_norm), plus
        # dedicated pre/post feed-forward norms
        m.update({
            "model.layers.{i}.post_attention_layernorm.weight":
                (("layers", "post_attn_norm"), False),
            "model.layers.{i}.pre_feedforward_layernorm.weight":
                (("layers", "pre_ffw_norm"), False),
            "model.layers.{i}.post_feedforward_layernorm.weight":
                (("layers", "post_ffw_norm"), False),
        })
    if not cfg.tie_word_embeddings:
        m["lm_head.weight"] = (("lm_head",), True)
    if cfg.attention_bias:
        m["model.layers.{i}.self_attn.q_proj.bias"] = (("layers", "bq"), False)
        m["model.layers.{i}.self_attn.k_proj.bias"] = (("layers", "bk"), False)
        m["model.layers.{i}.self_attn.v_proj.bias"] = (("layers", "bv"), False)
    if cfg.qk_norm:
        m["model.layers.{i}.self_attn.q_norm.weight"] = (("layers", "q_norm"), False)
        m["model.layers.{i}.self_attn.k_norm.weight"] = (("layers", "k_norm"), False)
    return m


_EXPERT_RE = re.compile(r"experts\.(\d+)\.")


def _match(name: str, patterns: Dict[str, Any]
           ) -> Tuple[Any, Optional[int], Optional[int]]:
    """Returns (spec, layer_index, expert_index)."""
    if name in patterns:
        return patterns[name], None, None
    if not name.startswith("model.layers."):
        return None, None, None
    rest = name[len("model.layers."):]
    idx, _, tail = rest.partition(".")
    try:
        layer = int(idx)
    except ValueError:
        return None, None, None
    key = f"model.layers.{{i}}.{tail}"
    if key in patterns:
        return patterns[key], layer, None
    m = _EXPERT_RE.search(tail)
    if m:
        tail2 = tail.replace(f"experts.{m.group(1)}.", "experts.{j}.", 1)
        key = f"model.layers.{{i}}.{tail2}"
        if key in patterns:
            return patterns[key], layer, int(m.group(1))
    return None, None, None


def load_hf_params(cfg: ModelConfig, path: str,
                   shardings: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Assemble the param pytree from an HF checkpoint directory."""
    if safe_open is None:  # pragma: no cover
        raise RuntimeError("safetensors not available")
    if cfg.kv_lora_rank:
        # deepseek MLA: heterogeneous layer stacks (dense + MoE) use a
        # dedicated loader (models/deepseek.py)
        from dynamo_tpu.models.deepseek import load_params
        return load_params(cfg, path, shardings)
    patterns = _name_map(cfg)
    staged: Dict[tuple, Any] = {}
    per_layer: Dict[tuple, Dict[int, np.ndarray]] = {}
    per_expert: Dict[tuple, Dict[Tuple[int, int], np.ndarray]] = {}
    for f in _checkpoint_files(path):
        with safe_open(f, framework="np") as sf:
            for name in sf.keys():
                spec, layer, expert = _match(name, patterns)
                if spec is None:
                    continue
                (tree_path, transpose) = spec
                t = sf.get_tensor(name)
                if transpose:
                    t = np.ascontiguousarray(t.T)
                if layer is None:
                    staged[tree_path] = t
                elif expert is None:
                    per_layer.setdefault(tree_path, {})[layer] = t
                else:
                    per_expert.setdefault(tree_path, {})[(layer, expert)] = t

    for tree_path, by_layer in per_layer.items():
        missing = set(range(cfg.num_layers)) - set(by_layer)
        if missing:
            raise ValueError(f"checkpoint missing layers {sorted(missing)} for {tree_path}")
        staged[tree_path] = np.stack([by_layer[i] for i in range(cfg.num_layers)])

    for tree_path, by_le in per_expert.items():
        want = {(i, j) for i in range(cfg.num_layers)
                for j in range(cfg.num_experts)}
        missing = want - set(by_le)
        if missing:
            raise ValueError(
                f"checkpoint missing {len(missing)} expert tensors for "
                f"{tree_path} (e.g. {sorted(missing)[:3]})")
        staged[tree_path] = np.stack([
            np.stack([by_le[(i, j)] for j in range(cfg.num_experts)])
            for i in range(cfg.num_layers)])

    # every expected weight family must have appeared — catches truncated
    # checkpoints and architectures whose tensor names we didn't map (which
    # would otherwise surface as a KeyError deep inside the jitted forward)
    absent = {tp for tp, _ in patterns.values()} - set(staged)
    if absent:
        raise ValueError(
            f"checkpoint at {path} is missing weights for {sorted(absent)}; "
            f"unsupported architecture or incomplete download")

    params: Dict[str, Any] = {}
    target_dtype = jnp.dtype(cfg.dtype)
    for tree_path, arr in staged.items():
        node = params
        for k in tree_path[:-1]:
            node = node.setdefault(k, {})
        leaf = jnp.asarray(arr).astype(target_dtype)
        if shardings is not None:
            spec = shardings
            for k in tree_path:
                spec = spec.get(k) if isinstance(spec, dict) else None
                if spec is None:
                    break
            if spec is not None:
                leaf = jax.device_put(leaf, spec)
        node[tree_path[-1]] = leaf
    return params


__all__ = ["load_hf_params"]
