"""Model architecture config, constructed from HF ``config.json``.

Capability parity: reference ``lib/llm/src/model_card/model.rs:87-230`` reads
HF config for context length / arch metadata; here the config additionally
drives the native jax model (the reference never builds the model itself).

Covers the Llama family tree: llama/llama-3, mistral, qwen2/qwen3 (qwen3 adds
per-head q/k RMS norm), the MoE variants (mixtral/qwen3_moe/deepseek-style
``num_experts``/``top_k`` routing) handled by ``models/moe.py``, and the
gemma-2 family (GeGLU, sandwich norms, logit softcaps, alternating
sliding-window layers) handled by ``models/gemma.py``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 8192
    tie_word_embeddings: bool = False
    qk_norm: bool = False          # qwen3-style per-head q/k RMSNorm
    attention_bias: bool = False   # qwen2-style qkv bias
    model_type: str = "llama"
    dtype: str = "bfloat16"
    # MoE (0 experts => dense MLP)
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 0
    norm_topk_prob: bool = True
    # expert compute: "dense" runs every expert on every token (static
    # shapes, fine at decode batch sizes); "dispatch" gathers each expert's
    # routed tokens into a fixed-capacity buffer first, cutting expert
    # FLOPs from E to ~k x capacity_factor per token (the wide-EP path)
    moe_backend: str = "dense"
    # dispatch capacity per expert = ceil(T * k / E * this); tokens routed
    # past capacity are dropped (their combine weight is zero) — the
    # standard GShard/Switch overflow semantics
    moe_capacity_factor: float = 2.0
    # DeepSeek V2/V3 MLA + MoE shape (models/deepseek.py). kv_lora_rank
    # > 0 selects the MLA family: the KV cache stores the compressed
    # latent (+ the shared rope key) instead of per-head K/V.
    q_lora_rank: int = 0               # 0 = direct q projection
    kv_lora_rank: int = 0
    qk_rope_head_dim: int = 0
    qk_nope_head_dim: int = 0
    v_head_dim: int = 0
    n_shared_experts: int = 0
    first_k_dense_replace: int = 0     # leading dense (non-MoE) layers
    routed_scaling_factor: float = 1.0
    topk_method: str = "greedy"        # greedy | group_limited_greedy
    n_group: int = 1
    topk_group: int = 1
    # YaRN rope scaling (real DeepSeek checkpoints ship
    # rope_scaling={type: yarn, ...}); factor 0 = disabled
    rope_scaling_factor: float = 0.0
    rope_orig_max_position: int = 0
    rope_beta_fast: float = 32.0
    rope_beta_slow: float = 1.0
    rope_mscale: float = 0.0
    rope_mscale_all_dim: float = 0.0
    rope_attention_factor: float = 0.0  # 0 = infer from factor/mscale
    # deepseek rope convention: True = complex-pair interleaved (the HF
    # default for this family), False = llama-style rotate-half halves
    rope_interleave: bool = True
    # gemma-2 family (models/gemma.py)
    sliding_window: int = 0            # 0 = all layers global attention
    attn_logit_softcap: float = 0.0    # 0 = disabled
    final_logit_softcap: float = 0.0
    query_pre_attn_scalar: float = 0.0  # 0 = use head_dim

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    @classmethod
    def from_hf(cls, hf: Dict[str, Any], dtype: str = "bfloat16") -> "ModelConfig":
        heads = hf["num_attention_heads"]
        mt = hf.get("model_type", "llama")
        num_experts = hf.get("num_local_experts", hf.get("num_experts", 0)) or 0
        extra: Dict[str, Any] = {}
        if mt.startswith("deepseek"):
            num_experts = hf.get("n_routed_experts", 0) or 0
            extra = dict(
                q_lora_rank=int(hf.get("q_lora_rank") or 0),
                kv_lora_rank=int(hf.get("kv_lora_rank") or 0),
                qk_rope_head_dim=int(hf.get("qk_rope_head_dim") or 0),
                qk_nope_head_dim=int(hf.get("qk_nope_head_dim") or 0),
                v_head_dim=int(hf.get("v_head_dim") or 0),
                n_shared_experts=int(hf.get("n_shared_experts") or 0),
                first_k_dense_replace=int(
                    hf.get("first_k_dense_replace") or 0),
                routed_scaling_factor=float(
                    hf.get("routed_scaling_factor") or 1.0),
                # V3 checkpoints route with the aux-loss-free sigmoid gate;
                # HF's DeepseekV3Config does not serialize topk_method, so
                # the model type implies it
                topk_method=hf.get(
                    "topk_method",
                    "noaux_tc" if mt == "deepseek_v3" else "greedy"),
                n_group=int(hf.get("n_group") or 1),
                topk_group=int(hf.get("topk_group") or 1),
            )
            rs = hf.get("rope_scaling") or {}
            rtype = rs.get("rope_type", rs.get("type"))
            if rtype == "yarn":
                extra.update(
                    rope_scaling_factor=float(rs.get("factor") or 1.0),
                    rope_orig_max_position=int(
                        rs.get("original_max_position_embeddings") or 0),
                    rope_beta_fast=float(rs.get("beta_fast") or 32.0),
                    rope_beta_slow=float(rs.get("beta_slow") or 1.0),
                    rope_mscale=float(rs.get("mscale") or 0.0),
                    rope_mscale_all_dim=float(
                        rs.get("mscale_all_dim") or 0.0),
                    rope_attention_factor=float(
                        rs.get("attention_factor") or 0.0),
                )
            elif rtype is not None:
                raise NotImplementedError(
                    f"deepseek rope_scaling type {rtype!r} (only yarn is "
                    "implemented)")
            extra["rope_interleave"] = bool(
                hf.get("rope_interleave", True))
        mla = bool(extra.get("kv_lora_rank"))
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=heads,
            # MLA: the paged cache stores ONE shared latent per token —
            # [N, 2, 1, ps, kv_lora_rank], slot 0 = compressed kv latent,
            # slot 1 = the (padded) shared rope key — so the generic cache
            # machinery sizes from Hkv=1 x head_dim=kv_lora_rank
            num_kv_heads=1 if mla else hf.get("num_key_value_heads", heads),
            head_dim=(extra["kv_lora_rank"] if mla
                      else hf.get("head_dim") or hf["hidden_size"] // heads),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
            max_position_embeddings=hf.get("max_position_embeddings", 8192),
            # transformers omits fields equal to its per-arch defaults:
            # gemma ties embeddings by default and serializes nothing
            tie_word_embeddings=bool(hf.get("tie_word_embeddings",
                                            mt.startswith("gemma"))),
            qk_norm=mt in ("qwen3", "qwen3_moe"),
            attention_bias=bool(hf.get("attention_bias", mt == "qwen2")),
            model_type=mt,
            dtype=dtype,
            num_experts=num_experts,
            num_experts_per_tok=hf.get("num_experts_per_tok", 2),
            moe_intermediate_size=hf.get("moe_intermediate_size",
                                         hf.get("intermediate_size", 0)),
            norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
            sliding_window=int(hf.get("sliding_window") or 0)
            if mt.startswith("gemma") else 0,
            attn_logit_softcap=float(hf.get("attn_logit_softcapping") or 0.0),
            final_logit_softcap=float(
                hf.get("final_logit_softcapping") or 0.0),
            query_pre_attn_scalar=float(
                hf.get("query_pre_attn_scalar") or 0.0),
            **extra,
        )

    @classmethod
    def from_pretrained(cls, path: str, dtype: str = "bfloat16") -> "ModelConfig":
        with open(os.path.join(path, "config.json")) as f:
            return cls.from_hf(json.load(f), dtype=dtype)

    @classmethod
    def llama32_3b(cls, **kw) -> "ModelConfig":
        """Llama-3.2-3B geometry — the single-chip flagship/bench config
        (bf16 params + KV fit a v5e chip; head_dim=128 rides the Pallas
        decode kernel). Shared by bench.py and __graft_entry__.py."""
        defaults = dict(
            vocab_size=128256, hidden_size=3072, intermediate_size=8192,
            num_layers=28, num_heads=24, num_kv_heads=8, head_dim=128,
            rope_theta=500000.0, max_position_embeddings=8192,
            tie_word_embeddings=True, dtype="bfloat16")
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def tiny(cls, **kw) -> "ModelConfig":
        """A toy config for tests (runs in ms on CPU)."""
        defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                        rope_theta=10000.0, max_position_embeddings=512,
                        dtype="float32")
        defaults.update(kw)
        return cls(**defaults)


__all__ = ["ModelConfig"]
