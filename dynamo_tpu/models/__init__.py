"""Model family implementations (pure-functional jax).

Each family exposes ``init_params(cfg, rng)``, a scan ``forward`` and an
unrolled ``forward_unrolled`` over the paged KV cache. ``get_family(cfg)``
maps a config to its implementation: MoE configs (``num_experts > 0``,
covering mixtral / qwen3_moe / deepseek-style routing) use
``models.moe``; everything else in the Llama tree (llama 2/3, mistral,
qwen2/qwen3) uses ``models.llama``.
"""

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import forward, init_params, make_pages


def get_family(cfg: ModelConfig):
    """Return the module implementing this config's model family."""
    if cfg.kv_lora_rank:
        # MLA (deepseek v2/v3): latent paged cache, absorbed attention
        from dynamo_tpu.models import deepseek
        return deepseek
    if cfg.num_experts:
        from dynamo_tpu.models import moe
        return moe
    if cfg.model_type == "gemma2":
        # only gemma-2 is implemented; gemma-1/gemma-3 differ (norm
        # layout, qk-norm, dual rope thetas) and must not silently load
        from dynamo_tpu.models import gemma
        return gemma
    if cfg.model_type.startswith("gemma"):
        raise NotImplementedError(
            f"model_type {cfg.model_type!r}: only gemma2 is implemented")
    from dynamo_tpu.models import llama
    return llama


__all__ = ["ModelConfig", "forward", "init_params", "make_pages",
           "get_family"]
