"""Model family implementations (pure-functional jax).

Each model exposes: ``init_params(cfg, rng)``, ``forward(params, cfg, ...)``
over a paged KV cache, and an HF-checkpoint loader. The registry maps HF
``model_type`` strings to implementations.
"""

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import forward, init_params, make_pages

__all__ = ["ModelConfig", "forward", "init_params", "make_pages"]
