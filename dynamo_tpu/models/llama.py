"""Llama-family decoder (llama 2/3, mistral, qwen2/qwen3) — pure-functional jax.

The reference framework never implements a model; it shells out to vLLM/SGLang
on CUDA (SURVEY §2.5). Here the model loop is native and TPU-first:

- Params are a pytree of stacked per-layer arrays (leading ``L`` axis) and the
  decoder runs as ONE ``lax.scan`` over layers: a single compiled layer body,
  fast compiles, and XLA while-loop buffer aliasing so the paged KV cache
  (part of the scan carry) is updated in place — no per-step cache copies.
- One forward serves prefill chunks and decode steps (S = 1): new K/V is
  scattered into the paged cache, then queries attend to the gathered context
  (``dynamo_tpu.ops.attention``).
- Only the last real token's logits are computed ([B, V]); full [B, S, V]
  logit materialization would waste HBM on long prefill chunks.

Weight layout matches HF checkpoints after transpose (torch Linear stores
[out, in]; we store [in, out] so the forward is ``x @ w``).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.attention import paged_attention, write_kv
from dynamo_tpu.ops.rope import apply_rope

Params = Dict[str, Any]


def _rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _head_rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    """qwen3-style per-head norm: x is [B, S, H, Dh], w is [Dh]."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def make_pages(cfg: ModelConfig, num_pages: int, page_size: int,
               dtype=None) -> jnp.ndarray:
    """Allocate the paged KV cache: [L, 2, N, page_size, Hkv, Dh].

    Page 0 is reserved as the garbage page for padded writes — allocators must
    hand out pages starting at index 1.
    """
    dtype = dtype or jnp.dtype(cfg.dtype)
    return jnp.zeros((cfg.num_layers, 2, num_pages, page_size,
                      cfg.num_kv_heads, cfg.head_dim), dtype=dtype)


def init_params(cfg: ModelConfig, rng: jax.Array, scale: float = 0.02) -> Params:
    """Random-normal init (for tests/benchmarks; real serving loads HF weights)."""
    dtype = jnp.dtype(cfg.dtype)
    keys = iter(jax.random.split(rng, 16))

    def norm(shape):
        return jnp.ones(shape, dtype=dtype)

    def randn(key, shape):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(dtype)

    L, H, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    layers: Dict[str, jnp.ndarray] = {
        "attn_norm": norm((L, H)),
        "wq": randn(next(keys), (L, H, cfg.q_size)),
        "wk": randn(next(keys), (L, H, cfg.kv_size)),
        "wv": randn(next(keys), (L, H, cfg.kv_size)),
        "wo": randn(next(keys), (L, cfg.q_size, H)),
        "mlp_norm": norm((L, H)),
        "w_gate": randn(next(keys), (L, H, I)),
        "w_up": randn(next(keys), (L, H, I)),
        "w_down": randn(next(keys), (L, I, H)),
    }
    if cfg.attention_bias:
        layers["bq"] = jnp.zeros((L, cfg.q_size), dtype=dtype)
        layers["bk"] = jnp.zeros((L, cfg.kv_size), dtype=dtype)
        layers["bv"] = jnp.zeros((L, cfg.kv_size), dtype=dtype)
    if cfg.qk_norm:
        layers["q_norm"] = norm((L, cfg.head_dim))
        layers["k_norm"] = norm((L, cfg.head_dim))
    params: Params = {
        "embed": randn(next(keys), (cfg.vocab_size, H)),
        "layers": layers,
        "final_norm": norm((H,)),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = randn(next(keys), (H, cfg.vocab_size))
    return params


def forward(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
            positions: jnp.ndarray, pages: jnp.ndarray,
            page_table: jnp.ndarray, total_lens: jnp.ndarray,
            new_lens: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run the decoder over a batch of new tokens against the paged cache.

    tokens:     [B, S] new token ids (padded; pads masked via new_lens)
    positions:  [B, S] absolute positions of the new tokens
    pages:      paged KV cache (see make_pages); returned updated
    page_table: [B, P] physical page ids per sequence
    total_lens: [B] context length including the new tokens
    new_lens:   [B] real new tokens per sequence (<= S)

    Returns (logits [B, vocab] at each sequence's last real new token, pages).
    """
    B, S = tokens.shape
    eps = cfg.rms_norm_eps
    sm_scale = cfg.head_dim ** -0.5
    h = params["embed"][tokens]  # [B, S, H]

    def body(carry, xs):
        h, pages = carry
        lp, lidx = xs
        x = _rms_norm(h, lp["attn_norm"], eps)
        q = x @ lp["wq"]
        k = x @ lp["wk"]
        v = x @ lp["wv"]
        if cfg.attention_bias:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        q = q.reshape(B, S, cfg.num_heads, cfg.head_dim)
        k = k.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
        if cfg.qk_norm:
            q = _head_rms_norm(q, lp["q_norm"], eps)
            k = _head_rms_norm(k, lp["k_norm"], eps)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        pages = write_kv(pages, lidx, k, v, page_table, positions, new_lens)
        attn = paged_attention(q, pages, lidx, page_table, positions,
                               total_lens, sm_scale)
        h = h + attn.reshape(B, S, cfg.q_size) @ lp["wo"]
        x = _rms_norm(h, lp["mlp_norm"], eps)
        h = h + (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
        return (h, pages), None

    (h, pages), _ = jax.lax.scan(
        body, (h, pages),
        (params["layers"], jnp.arange(cfg.num_layers)))

    h = _rms_norm(h, params["final_norm"], eps)
    last = jnp.maximum(new_lens - 1, 0)                    # [B]
    h_last = jnp.take_along_axis(
        h, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]  # [B, H]
    lm_head = params.get("lm_head")
    if lm_head is None:
        lm_head = params["embed"].T
    logits = h_last.astype(jnp.float32) @ lm_head.astype(jnp.float32)
    return logits, pages


__all__ = ["init_params", "forward", "make_pages"]
